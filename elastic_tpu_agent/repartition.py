"""Dynamic fractional re-partitioning: close the utilization loop.

The sampler (PR 2) attributes granted-vs-used core%/HBM per pod; until
now that signal only raised overcommit alarms — idle grants stayed idle
while co-located pods starved, exactly the utilization gap ROADMAP item
2 names. FlexNPU (PAPERS.md) shows where fractional sharing earns its
keep: *dynamic* re-partitioning under prefill/decode co-location, with
the virtualization layer moving quota between phases as the imbalance
moves. This module is that layer for the agent's cooperative QoS
contract:

- **Opt-in**: only pods annotated ``elasticgpu.io/repartition`` (truthy)
  participate, as donors or borrowers. Quota renegotiation must never
  surprise a pod that didn't ask; everyone else keeps the static grant
  the scheduler gave them.
- **Grow / shrink**: a busy opted-in pod (measured usage ≥ ``busy_frac``
  of its effective grant) absorbs a co-located idle pod's slack —
  ``ELASTIC_TPU_CORE_UNITS`` (and HBM quota, donor-ratio-proportional)
  restamped into both pods' alloc specs under the owner's bind stripe,
  the same :func:`plugins.restamp_owner_env` path the drain signal uses.
  Donations move in bounded steps per tick (no oscillation) and unwind
  the same way: a donor coming back under pressure, a borrower going
  idle, or either side leaving the node returns the units.
- **QoS precedence**: a high-priority pod NEVER donates to a
  low-priority one (``qos.pod_priority``: annotation, else
  priorityClassName, else low). Low may donate upward; equals may trade.
- **Escalation**: sustained overcommit against the *effective* grant is
  no longer just an alarm — the pod's quota is clamped back to its base
  grant (borrowed units revoked, ``ELASTIC_TPU_THROTTLE`` +
  deadline stamped), and a pod still over quota at the deadline has its
  bindings reclaimed through the reconciler's existing ``reclaimed_pod``
  repair class. The reconciler suppresses unbound-assignment replays for
  evicted pods so kubelet's still-listed assignment cannot resurrect
  what enforcement just removed.

The per-pod usage signal is honest, not assumed: TPUs expose no
per-process duty counters, so opted-in pods self-report measured duty
through ``workloads/telemetry.write_usage_report`` (a file keyed by the
pod's allocation hash on the shared alloc dir) and the sampler
attributes only the remaining chip duty to non-reporting co-tenants.

Crash consistency follows the drain orchestrator's discipline: every
quota move is journaled into the Storage ``agent_state`` table BEFORE
any spec file changes (test-only failpoints ``repartition.pre_journal``
/ ``repartition.post_journal`` / ``repartition.mid_restamp`` plus the
per-file ``restamp.spec_file`` name the crash windows), every tick
re-asserts the journaled quotas idempotently, and :meth:`resume`
re-applies them on restart — a pod can end up mid-move torn for at most
one restart, never permanently, and throttle/evict deadlines survive the
process.

Supervised DEGRADED: losing re-partitioning must not take binding down;
/healthz and the doctor bundle surface the loss.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import faults
from .common import (
    SYSTEM_CLOCK,
    BytesPerMemoryUnit,
    EnvThrottle,
    EnvThrottleDeadline,
    ResourceTPUCore,
    ResourceTPUMemory,
)
from .qos import (
    AnnotationQoSCoreUnits,
    AnnotationQoSHBMLimit,
    EnvQoSCoreUnits,
    EnvQoSHBMFraction,
    EnvQoSHBMLimit,
    _annotation_int,
    pod_priority,
    repartition_opt_in,
)
from .storage.store import StorageError
from .types import PodContainer

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 10.0
# Units moved per (donor, borrower) pair per tick — bounded steps keep
# the loop stable against noisy duty samples.
DEFAULT_STEP_UNITS = 10
# A donor is idle below this fraction of its effective grant...
DEFAULT_IDLE_FRAC = 0.5
# ...a borrower is hungry above this fraction of its effective grant...
DEFAULT_BUSY_FRAC = 0.9
# ...and a donor whose usage climbs back above this fraction reclaims.
DEFAULT_PRESSURE_FRAC = 0.75
# A donor always keeps at least this many units of its base grant.
DEFAULT_MIN_KEEP_UNITS = 10
# Overcommit margin (percentage points over the EFFECTIVE grant) and how
# many consecutive ticks sustain it before the throttle clamp.
DEFAULT_OVERCOMMIT_MARGIN = 5.0
DEFAULT_THROTTLE_AFTER_TICKS = 3
# Wall-clock grace between the throttle clamp and binding reclaim.
DEFAULT_EVICT_AFTER_S = 300.0

_STATE_KEY = "repartition"


class RepartitionController:
    """Per-node live quota renegotiator (one instance per agent)."""

    def __init__(
        self,
        sampler,
        storage,
        sitter,
        plugin,
        reconciler,
        metrics=None,
        events=None,
        timeline=None,
        node_name: str = "",
        period_s: float = DEFAULT_PERIOD_S,
        step_units: int = DEFAULT_STEP_UNITS,
        idle_frac: float = DEFAULT_IDLE_FRAC,
        busy_frac: float = DEFAULT_BUSY_FRAC,
        pressure_frac: float = DEFAULT_PRESSURE_FRAC,
        min_keep_units: int = DEFAULT_MIN_KEEP_UNITS,
        overcommit_margin: float = DEFAULT_OVERCOMMIT_MARGIN,
        throttle_after_ticks: int = DEFAULT_THROTTLE_AFTER_TICKS,
        evict_after_s: float = DEFAULT_EVICT_AFTER_S,
        clock=None,
        rng=None,
        lag_tracker=None,
        bus=None,
        event_safety_net_factor: float = 1.0,
    ) -> None:
        self._sampler = sampler
        self._storage = storage
        self._sitter = sitter
        self._plugin = plugin
        self._reconciler = reconciler
        self._metrics = metrics
        self._events = events
        self._timeline = timeline
        self._node = node_name
        self.period_s = period_s
        self.step_units = max(1, step_units)
        self.idle_frac = idle_frac
        self.busy_frac = busy_frac
        self.pressure_frac = pressure_frac
        self.min_keep_units = max(0, min_keep_units)
        self.overcommit_margin = overcommit_margin
        self.throttle_after_ticks = max(1, throttle_after_ticks)
        self.evict_after_s = evict_after_s
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._rng = rng if rng is not None else random.Random()
        self._lag = lag_tracker  # DetectionLagTracker (latency.py)
        self._lock = threading.Lock()
        # Donation ledger: every executed move is an edge (or tops up an
        # existing one), so shrink-back knows exactly whose units went
        # where. deltas are DERIVED from edges, never stored separately.
        self._edges: List[dict] = []
        # pod -> {"since_ts", "deadline_ts", "reason"}
        self._throttles: Dict[str, dict] = {}
        # pods whose bindings QoS enforcement reclaimed (pod key -> the
        # UID the eviction acted on); the reconciler must not replay
        # their still-listed kubelet assignments back. UID-pinned like
        # the throttles: a pod re-created under the same name must not
        # inherit the suppression.
        self._evicted: Dict[str, str] = {}
        # Pods owed a restamp: journaled WITH the ledger before any spec
        # file changes and cleared per pod as its restamp lands, so a
        # crash mid-commit knows exactly whose on-disk quotas may still
        # reflect the PREVIOUS ledger (an unwound edge's borrower is no
        # longer an edge endpoint — the ledger alone can't name it).
        self._pending_restamp: set = set()
        self._over_streak: Dict[str, int] = {}
        # Per-pass memo for _base_quotas: one tick (or resume) asks for
        # the same pod's store record from the meta build, the edge
        # unwind, each restamp and each throttle emit — one storage
        # load per pod per pass, not four. Cleared at every pass start.
        self._base_cache: Dict[str, Optional[dict]] = {}
        self._repartitions = {"grow": 0, "shrink": 0}
        self._throttles_total = 0
        self._evictions_total = 0
        self._last_tick_ts: Optional[float] = None
        # The sampler-view timestamp the last USAGE-DRIVEN decisions
        # were made from: a view that has not advanced (sampler slower
        # than this loop, crashed, or circuit-broken — it is DEGRADED
        # too) must not be re-judged; enforcement-grade actions need
        # fresh evidence, never one frozen measurement re-counted.
        self._last_view_ts: Optional[float] = None
        self._last_error: Optional[str] = None
        # MigrationCoordinator (migration.py), assigned by the manager:
        # gates the QoS eviction the same way acks gate drain reclaim —
        # a throttled pod that answers the clamp with a durable
        # checkpoint is evicted with its work PRESERVED (record
        # published before the teardown), and may be evicted the moment
        # the ack lands instead of at the deadline (checkpointing under
        # a throttle is the pod accepting the move).
        self.migration = None
        self._resumed = False
        # Event bus (events.py): pod deltas and store-change events wake
        # a tick early (an evicted pod vanishing, a new tenant binding).
        # The sweep stretches only while fractional sharing is DISABLED
        # (tick is a no-op then) and the bus is healthy — with sharing
        # live, enforcement deadlines and usage-driven decisions keep
        # the base cadence, since sampler pressure is not event-visible.
        self._bus = bus
        self.event_safety_net_factor = max(1.0, float(
            event_safety_net_factor
        ))
        self._event_sub = None
        if bus is not None:
            from . import events as bus_events

            self._event_sub = bus.subscribe(
                "repartition",
                (bus_events.POD_DELTA, bus_events.STORE_BIND,
                 bus_events.ASSIGNMENT_DELTA),
            )
        self.event_ticks_total = 0

    # -- derived quota state ---------------------------------------------------

    def core_delta_percent(self, pod_key: str) -> float:
        """Signed core-unit delta this controller currently applies on
        top of ``pod_key``'s base grant (1 unit == 1 core percent). The
        sampler's overcommit detector reads this so effective grants,
        not base grants, are what usage is judged against."""
        with self._lock:
            return float(self._core_delta_locked(pod_key))

    def _core_delta_locked(self, pod_key: str) -> int:
        delta = 0
        for e in self._edges:
            if e["borrower"] == pod_key:
                delta += e["core_units"]
            if e["donor"] == pod_key:
                delta -= e["core_units"]
        return delta

    def _hbm_delta_locked(self, pod_key: str) -> int:
        delta = 0
        for e in self._edges:
            if e["borrower"] == pod_key:
                delta += e.get("hbm_bytes", 0)
            if e["donor"] == pod_key:
                delta -= e.get("hbm_bytes", 0)
        return delta

    def replay_suppressed(self, pod_key: str) -> bool:
        """True while QoS enforcement reclaimed this pod's bindings and
        the pod still exists — the reconciler's unbound-assignment
        replay would otherwise faithfully re-bind them."""
        with self._lock:
            return pod_key in self._evicted

    # -- pod metadata ----------------------------------------------------------

    def _spec_plugin(self):
        return getattr(self._plugin, "core", None)

    def _fractional(self) -> bool:
        """Whole-chip (exclusive) mode has no sub-chip units to move."""
        plugin = self._spec_plugin()
        return plugin is not None and not getattr(
            plugin, "_whole_chip", False
        )

    def _pod_meta(self, pod_key: str):
        """(annotations, pod) from the sitter cache, or (None, None)
        when the pod is unknown there (never force an apiserver round
        trip from this loop)."""
        ns, _, name = pod_key.partition("/")
        pod = self._sitter.get_pod(ns, name)
        if pod is None:
            return None, None
        ann = (pod.get("metadata") or {}).get("annotations") or {}
        return ann, pod

    def _base_quotas(self, pod_key: str) -> Optional[dict]:
        """The pod's store-derived base grant, or None when it has no
        usable records (memoized per policy pass — see _base_cache).
        Raises StorageError when the store cannot answer: unknowable is
        NOT absence — one transient sqlite failure must never read as
        "every peer departed" and unwind the whole ledger. Quota env is
        per container; the repartition contract addresses
        single-TPU-container pods, so the (single) core-holding
        container is the restamp target — pods with more are skipped
        (logged once per tick via the caller)."""
        if pod_key in self._base_cache:
            return self._base_cache[pod_key]
        # only successful answers are cached: a StorageError propagates
        # (and is retried on the next call/tick) without poisoning the
        # memo
        self._base_cache[pod_key] = out = self._load_base(pod_key)
        return out

    def _peer_departed(self, pod_key: str) -> bool:
        """True only when the store POSITIVELY answers "no record" —
        an unanswerable store keeps edges and throttles in place."""
        try:
            return self._base_quotas(pod_key) is None
        except StorageError:
            return False

    def _load_base(self, pod_key: str) -> Optional[dict]:
        ns, _, name = pod_key.partition("/")
        info = self._storage.load(ns, name)
        if info is None:
            return None
        target = None
        core_containers = 0
        for container, by_resource in info.allocations.items():
            core_units = 0
            hbm_bytes = 0
            chips: set = set()
            for resource, rec in by_resource.items():
                chips.update(rec.chip_indexes)
                if resource == ResourceTPUCore:
                    core_units += len(rec.device.ids)
                elif resource == ResourceTPUMemory:
                    hbm_bytes += len(rec.device.ids) * BytesPerMemoryUnit
            if core_units:
                core_containers += 1
                target = {
                    "owner": PodContainer(ns, name, container),
                    "records": dict(by_resource),
                    "core_units": core_units,
                    "hbm_bytes": hbm_bytes,
                    "chips": chips,
                }
        if target is None or core_containers != 1:
            return None
        return target

    def _chip_hbm_bytes(self) -> int:
        plugin = self._spec_plugin()
        chips = getattr(plugin, "_chips", None) or {}
        for chip in chips.values():
            return int(chip.hbm_bytes)
        return 0

    # -- journaled state -------------------------------------------------------

    def _journal_locked(self) -> None:
        self._storage.save_state(_STATE_KEY, {
            "edges": [dict(e) for e in self._edges],
            "throttles": {k: dict(v) for k, v in self._throttles.items()},
            "evicted": dict(self._evicted),
            "pending_restamp": sorted(self._pending_restamp),
            "repartitions_total": dict(self._repartitions),
            "throttles_total": self._throttles_total,
            "evictions_total": self._evictions_total,
        })

    def resume(self) -> None:
        """Reload the journaled ledger and re-assert every affected
        pod's quota env (idempotent — restamp skips already-correct
        files), so a crash anywhere between the journal write and the
        last spec file converges on the journaled state. Called before
        the boot reconcile (manager.run), like drain.resume, so replay
        suppression for evicted pods is armed before any repair runs."""
        self._base_cache = {}  # a re-resume must not restamp stale bases
        try:
            st = self._storage.load_state(_STATE_KEY)
        except Exception:  # noqa: BLE001 - unreadable journal: start clean
            logger.exception(
                "repartition: state journal unreadable; starting empty"
            )
            st = None
        if st:
            with self._lock:
                self._edges = [dict(e) for e in st.get("edges", [])]
                self._throttles = {
                    k: dict(v)
                    for k, v in (st.get("throttles") or {}).items()
                }
                evicted = st.get("evicted") or {}
                if isinstance(evicted, dict):
                    self._evicted = dict(evicted)
                else:  # pre-UID journal shape: a plain key list
                    self._evicted = {k: "" for k in evicted}
                self._pending_restamp = set(
                    st.get("pending_restamp", [])
                )
                self._repartitions.update(
                    st.get("repartitions_total") or {}
                )
                self._throttles_total = int(st.get("throttles_total", 0))
                self._evictions_total = int(st.get("evictions_total", 0))
                affected = (
                    self._affected_pods_locked() | self._pending_restamp
                )
            if affected:
                logger.warning(
                    "repartition: resuming journaled quota state for %s",
                    sorted(affected),
                )
            for pod_key in sorted(affected):
                try:
                    self._restamp_pod(pod_key)
                    with self._lock:
                        self._pending_restamp.discard(pod_key)
                except Exception:  # noqa: BLE001 - next tick re-asserts
                    logger.exception(
                        "repartition: resume restamp for %s failed",
                        pod_key,
                    )
            with self._lock:
                self._journal_locked()
        self._resumed = True

    def _affected_pods_locked(self) -> set:
        out = set(self._throttles)
        for e in self._edges:
            out.add(e["donor"])
            out.add(e["borrower"])
        return out

    # -- restamps --------------------------------------------------------------

    def _restamp_pod(self, pod_key: str) -> bool:
        """Re-assert ``pod_key``'s effective quota env (base grant +
        journaled deltas + throttle marker) into its on-disk alloc
        specs, under the owner's bind stripe via the shared restamp
        helper. Idempotent; returns False when the pod has no restamp
        target any more (gone, or not single-TPU-container)."""
        from .plugins import restamp_owner_env

        base = self._base_quotas(pod_key)
        plugin = self._spec_plugin()
        if base is None or plugin is None:
            return False
        with self._lock:
            core_delta = self._core_delta_locked(pod_key)
            hbm_delta = self._hbm_delta_locked(pod_key)
            throttle = (
                dict(self._throttles[pod_key])
                if pod_key in self._throttles else None
            )
        # The pod's own clamp-only-downward quota caps (qos.py) bind
        # restamps too: a donation unwinding (or a throttle lifting)
        # must never stamp a quota above the ceiling the pod declared
        # for itself at bind time. The ledger stays grant-denominated;
        # only the stamped env clamps.
        ann, _pod = self._pod_meta(pod_key)
        ann = ann or {}
        eff_core = max(0, base["core_units"] + core_delta)
        cap_units = _annotation_int(ann, AnnotationQoSCoreUnits)
        if cap_units is not None:
            eff_core = min(eff_core, cap_units)
        env = {EnvQoSCoreUnits: str(eff_core)}
        if base["hbm_bytes"]:
            eff_hbm = max(0, base["hbm_bytes"] + hbm_delta)
            cap_hbm = _annotation_int(ann, AnnotationQoSHBMLimit)
            if cap_hbm is not None:
                eff_hbm = min(eff_hbm, cap_hbm)
            env[EnvQoSHBMLimit] = str(eff_hbm)
            chip_hbm = self._chip_hbm_bytes()
            if chip_hbm:
                env[EnvQoSHBMFraction] = (
                    f"{min(1.0, eff_hbm / chip_hbm):.4f}"
                )
        remove = ()
        if throttle is not None:
            env[EnvThrottle] = throttle.get("reason", "overcommit")
            env[EnvThrottleDeadline] = str(int(throttle["deadline_ts"]))
        else:
            remove = (EnvThrottle, EnvThrottleDeadline)
        restamp_owner_env(
            plugin, base["owner"], base["records"], env,
            remove_keys=remove,
        )
        return True

    def _commit(self, dirty: set, moves: List[dict]) -> None:
        """Journal-then-restamp: the ledger lands durably BEFORE any
        spec file changes (a crash between the two is exactly what
        resume() converges), then every affected pod is re-stamped and
        the observability trail (metrics/timeline/events) emitted."""
        faults.fire("repartition.pre_journal")
        with self._lock:
            self._pending_restamp |= set(dirty)
            self._journal_locked()
        faults.fire("repartition.post_journal")
        for pod_key in sorted(dirty):
            try:
                self._restamp_pod(pod_key)
                with self._lock:
                    self._pending_restamp.discard(pod_key)
            except Exception:  # noqa: BLE001 - next tick re-asserts
                logger.exception(
                    "repartition: restamp for %s failed (re-asserted "
                    "next tick)", pod_key,
                )
            faults.fire("repartition.mid_restamp")
        with self._lock:
            # the pending set shrank (or kept its failures): record it
            self._journal_locked()
        for move in moves:
            self._emit_move(move)

    def _emit_move(self, move: dict) -> None:
        m = self._metrics
        direction = move["direction"]
        if self._lag is not None:
            # A move's divergence originated at whichever pod's demand
            # shift triggered it: the borrower under pressure (grow) or
            # the donor going idle (shrink). Origins come from marks the
            # sim/tests stamp at injection; unmarked moves record
            # nothing.
            self._lag.handled(
                "repartition", "repartition",
                key=move["borrower"] if direction == "grow"
                else move["donor"],
            )
        if m is not None and hasattr(m, "repartitions"):
            try:
                m.repartitions.labels(direction=direction).inc()
            except Exception:  # noqa: BLE001
                pass
        if self._timeline is not None:
            from .timeline import KIND_REPARTITION

            # BOTH pods' quotas changed, so both get the event in
            # their keyed history — "why did my pod's quota change?"
            # must answer from either side of the move.
            for role in ("donor", "borrower"):
                self._timeline.emit(
                    KIND_REPARTITION,
                    keys={
                        "pod": move[role],
                        "chips": [move["chip"]],
                    },
                    direction=direction,
                    role=role,
                    donor=move["donor"],
                    borrower=move["borrower"],
                    core_units=move["core_units"],
                    hbm_bytes=move.get("hbm_bytes", 0),
                    reason=move.get("reason", ""),
                )
        if self._events is not None:
            from .kube.events import ReasonRepartitioned

            ns, _, name = move["borrower"].partition("/")
            try:
                self._events.pod_event(
                    ns, name, ReasonRepartitioned,
                    f"{direction}: {move['core_units']} core unit(s) "
                    f"{'from' if direction == 'grow' else 'returned to'} "
                    f"{move['donor']} on chip {move['chip']}",
                )
            except Exception:  # noqa: BLE001 - observability only
                pass
        logger.info(
            "repartition %s: %s -> %s (%d units, %d HBM bytes, chip %d)%s",
            direction, move["donor"], move["borrower"],
            move["core_units"], move.get("hbm_bytes", 0), move["chip"],
            f" [{move['reason']}]" if move.get("reason") else "",
        )

    # -- the policy tick -------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> dict:
        """One policy pass; returns {"grown", "shrunk", "throttled",
        "evicted"} counts for tests and the status block."""
        faults.fire("repartition.tick")
        now = self._clock.time() if now is None else now
        result = {"grown": 0, "shrunk": 0, "throttled": 0, "evicted": 0}
        self._base_cache = {}
        if not self._fractional():
            return result
        view = (
            self._sampler.utilization_view()
            if self._sampler is not None else {"pods": {}}
        )
        pods = view.get("pods", {})
        # One metadata/base pass: who is opted in, at what priority,
        # with what base grant and measured usage.
        meta: Dict[str, dict] = {}
        for key, p in pods.items():
            ann, pod = self._pod_meta(key)
            if ann is None:
                continue
            try:
                base = self._base_quotas(key)
            except StorageError:
                continue  # unknowable this pass: no policy action
            if base is None:
                continue
            with self._lock:
                eff = base["core_units"] + self._core_delta_locked(key)
            meta[key] = {
                "opted": repartition_opt_in(ann),
                "priority": pod_priority(ann, pod),
                "uid": ((pod or {}).get("metadata") or {}).get("uid", ""),
                "base": base,
                "eff": eff,
                # the pod's clamp-only-downward self-cap: growth past
                # it would strand donated units the stamped env can
                # never expose
                "cap": _annotation_int(ann, AnnotationQoSCoreUnits),
                "used": p.get("used_percent"),
                "chips": dict(p.get("chips") or {}),
                "reported": bool(p.get("self_reported")),
            }
        view_ts = view.get("ts")
        fresh = view_ts is not None and view_ts != self._last_view_ts
        dirty: set = set()
        moves: List[dict] = []
        # Structure-driven pieces (peers departing, opt-outs, evicted
        # sweeps) run every tick; USAGE-driven pieces (pressure/idle
        # shrink, escalation streaks and deadlines, growth) only act on
        # a view that advanced since the last tick.
        self._unwind_dead_edges(meta, dirty, moves, result)
        if fresh:
            self._shrink_under_pressure(meta, now, dirty, moves, result)
        # BEFORE escalation: a stale throttle inherited across a pod
        # re-creation must never contribute an instant eviction.
        self._sweep_departed_throttles(meta, dirty)
        self._escalate_overcommit(
            meta, now, dirty, moves, result, fresh
        )
        if fresh:
            self._grow_from_slack(meta, now, dirty, moves, result)
            self._last_view_ts = view_ts
        self._sweep_evicted()
        # Streaks only exist for pods in this pass's view: a departed
        # pod's partial streak must not pass to a same-name successor,
        # and the dict must not grow with pod churn.
        self._over_streak = {
            k: v for k, v in self._over_streak.items() if k in meta
        }
        with self._lock:
            # restamps a previous tick (or resume) could not complete
            # are owed until they land
            dirty |= self._pending_restamp
        if dirty or moves:
            self._commit(dirty, moves)
        with self._lock:
            self._last_tick_ts = now
        return result

    # -- policy pieces ---------------------------------------------------------

    def _drop_edge(
        self, edge: dict, units: int, reason: str,
        dirty: set, moves: List[dict], result: dict, meta=None,
    ) -> None:
        """(no lock) Return ``units`` from an edge (whole edge when
        units >= its size); accounting + ledger only, restamps ride the
        commit. When ``meta`` is given, the pass's working effective
        grants are adjusted too — a later policy piece in the SAME tick
        must judge donors/borrowers against the post-shrink reality,
        not a stale eff that lets a pod be donated below its floor."""
        units = min(units, edge["core_units"])
        if units <= 0:
            return
        frac = units / edge["core_units"]
        hbm_back = int(edge.get("hbm_bytes", 0) * frac)
        with self._lock:
            edge["core_units"] -= units
            edge["hbm_bytes"] = edge.get("hbm_bytes", 0) - hbm_back
            if edge["core_units"] <= 0:
                self._edges.remove(edge)
            self._repartitions["shrink"] += 1
        if meta is not None:
            if edge["donor"] in meta:
                meta[edge["donor"]]["eff"] += units
            if edge["borrower"] in meta:
                meta[edge["borrower"]]["eff"] -= units
        dirty.add(edge["donor"])
        dirty.add(edge["borrower"])
        result["shrunk"] += 1
        moves.append({
            "direction": "shrink",
            "donor": edge["donor"],
            "borrower": edge["borrower"],
            "chip": edge["chip"],
            "core_units": units,
            "hbm_bytes": hbm_back,
            "reason": reason,
        })

    def _unwind_dead_edges(
        self, meta: dict, dirty: set, moves: List[dict], result: dict
    ) -> None:
        """Edges whose donor or borrower left the node (record gone)
        return their units — the survivor's restamp re-derives from its
        base grant, so a vanished peer can't strand a quota."""
        with self._lock:
            edges = list(self._edges)
        for edge in edges:
            gone = [
                k for k in (edge["donor"], edge["borrower"])
                if self._peer_departed(k)
            ]
            if gone:
                self._drop_edge(
                    edge, edge["core_units"],
                    f"peer gone: {','.join(gone)}", dirty, moves,
                    result, meta=meta,
                )

    def _shrink_under_pressure(
        self, meta: dict, now: float, dirty: set, moves: List[dict],
        result: dict,
    ) -> None:
        with self._lock:
            edges = list(self._edges)
        for edge in edges:
            if edge not in self._edges:
                continue  # already unwound this tick
            donor = meta.get(edge["donor"])
            borrower = meta.get(edge["borrower"])
            if donor is not None and donor["used"] is not None and (
                donor["used"] > self.pressure_frac * max(1, donor["eff"])
            ):
                # The donor needs its units back: reclaim one step.
                self._drop_edge(
                    edge, self.step_units, "donor under pressure",
                    dirty, moves, result, meta=meta,
                )
            elif borrower is not None and borrower["used"] is not None and (
                borrower["used"]
                < self.idle_frac * max(1, borrower["eff"])
            ):
                # The borrower stopped needing the growth: decay it.
                self._drop_edge(
                    edge, self.step_units, "borrower idle",
                    dirty, moves, result, meta=meta,
                )

    def _escalate_overcommit(
        self, meta: dict, now: float, dirty: set, moves: List[dict],
        result: dict, fresh: bool = True,
    ) -> None:
        for key, m in meta.items():
            if not m["opted"]:
                # Opting out (annotations are pod-controlled, read
                # live) ends PARTICIPATION, both halves: a standing
                # throttle lifts (never stuck forever because the
                # escalation loop stopped looking), AND every edge
                # touching the pod unwinds — a non-participant must
                # not keep borrowed quota while exempt from
                # enforcement, nor keep its units lent out.
                with self._lock:
                    was_throttled = self._throttles.pop(key, None)
                    touching = [
                        e for e in self._edges
                        if key in (e["donor"], e["borrower"])
                    ]
                self._over_streak.pop(key, None)
                for edge in touching:
                    self._drop_edge(
                        edge, edge["core_units"], f"{key} opted out",
                        dirty, moves, result, meta=meta,
                    )
                if was_throttled is not None:
                    dirty.add(key)
                    self._emit_throttle(key, "unthrottle")
                    logger.info(
                        "repartition: %s opted out while throttled; "
                        "clamp lifted", key,
                    )
                continue
            if not fresh:
                # The sampler view has not advanced: one frozen
                # measurement must not accrue streaks, lift a clamp,
                # or — worst — reach an evict deadline re-counted.
                continue
            with self._lock:
                throttled = key in self._throttles
                deadline = (
                    self._throttles[key]["deadline_ts"] if throttled
                    else None
                )
                throttle_since = (
                    self._throttles[key]["since_ts"] if throttled
                    else None
                )
            if throttled:
                # A standing throttle lifts ONLY on positive evidence
                # of compliance: a fresh self-report within quota.
                # Ceasing to report is not an escape hatch — the pod
                # opted into the reporting contract, was clamped on its
                # own measured overcommit, and silence at the deadline
                # reads as non-compliance (the pod controls the file;
                # reporting honest within-quota usage is the way out).
                compliant = (
                    m["reported"] and m["used"] is not None
                    and m["used"] <= m["eff"] + self.overcommit_margin
                )
                if compliant:
                    with self._lock:
                        self._throttles.pop(key, None)
                    self._over_streak.pop(key, None)
                    dirty.add(key)
                    self._emit_throttle(key, "unthrottle")
                    logger.info(
                        "repartition: %s back within quota; throttle "
                        "lifted", key,
                    )
                    continue
                # Migration gate: a still-over-quota pod that answered
                # the throttle signal with a durable checkpoint ack has
                # accepted the move — evict NOW with the work preserved
                # instead of burning the rest of the grace deadline.
                acked_early = (
                    self.migration is not None
                    and self.migration.acked_since(key, throttle_since)
                )
                if now >= deadline or acked_early:
                    self._evict(
                        key, m.get("uid", ""), dirty, result,
                        acked=acked_early,
                    )
                continue
            if m["used"] is None:
                # Coverage lost (no telemetry, no fresh report): no
                # evidence either way — the streak resets
                # (conservative: never punishes on absence).
                self._over_streak.pop(key, None)
                continue
            # Enforcement needs MEASURED evidence: only a pod's own
            # self-report can throttle it. Remainder-attributed usage
            # is an assumption (an under-reporting co-tenant shifts
            # phantom duty onto whoever doesn't report) — it still
            # raises the sampler's overcommit ALARM, but never the
            # clamp. An under-reporter gains nothing either: its own
            # idle-looking report makes it a DONOR.
            over = m["reported"] and (
                m["used"] > m["eff"] + self.overcommit_margin
            )
            if over:
                self._over_streak[key] = self._over_streak.get(key, 0) + 1
            else:
                self._over_streak[key] = 0
            if (
                self._over_streak.get(key, 0)
                >= self.throttle_after_ticks
            ):
                # Escalate alarm -> throttle: revoke borrowed growth and
                # clamp the quota back to the base grant, deadline armed.
                with self._lock:
                    edges = [
                        e for e in self._edges if e["borrower"] == key
                    ]
                for edge in edges:
                    self._drop_edge(
                        edge, edge["core_units"], "throttled",
                        dirty, moves, result, meta=meta,
                    )
                deadline_ts = now + self.evict_after_s
                with self._lock:
                    self._throttles[key] = {
                        "since_ts": now,
                        "deadline_ts": deadline_ts,
                        "reason": "overcommit",
                        # pinned to THIS pod instance: a re-created pod
                        # under the same name starts clean
                        "uid": m.get("uid", ""),
                    }
                    self._throttles_total += 1
                dirty.add(key)
                result["throttled"] += 1
                if self._metrics is not None and hasattr(
                    self._metrics, "throttles"
                ):
                    try:
                        self._metrics.throttles.inc()
                    except Exception:  # noqa: BLE001
                        pass
                self._emit_throttle(key, "throttle", deadline_ts)
                logger.warning(
                    "repartition: %s sustained overcommit (used %.1f%% "
                    "of %d units); quota clamped, reclaim at %d",
                    key, m["used"], m["eff"], int(deadline_ts),
                )

    def _evict(
        self, key: str, uid: str, dirty: set, result: dict,
        acked: bool = False,
    ) -> None:
        """Deadline expired (or the pod acked a post-throttle
        checkpoint) with the pod still over quota: reclaim its bindings
        through the reconciler's reclaimed_pod repair class. When the
        migration coordinator holds a durable ack, a MigrationRecord is
        published FIRST so the eviction preserves the work (the gated
        eviction of ISSUE 14). The evicted set is journaled BEFORE the
        teardown — a crash in between must leave replay suppression
        armed, or the boot reconcile would re-bind exactly what
        enforcement removed (the safe wrong way round merely re-runs
        the escalation)."""
        if self.migration is not None and (
            acked or self.migration.acked_since(key, None)
        ):
            # best-effort, never blocks the eviction: the record (and
            # its journal entry) outlives the reclaim either way
            self.migration.publish_record(key, uid, reason="qos_evict")
        with self._lock:
            self._throttles.pop(key, None)
            self._evicted[key] = uid
            self._evictions_total += 1
            self._journal_locked()
        faults.fire("repartition.pre_evict_reclaim")
        report = self._reconciler.reclaim_pods([key])
        self._over_streak.pop(key, None)
        dirty.discard(key)  # its specs are gone with the reclaim
        result["evicted"] += 1
        if self._metrics is not None and hasattr(
            self._metrics, "qos_evictions"
        ):
            try:
                self._metrics.qos_evictions.inc()
            except Exception:  # noqa: BLE001
                pass
        self._emit_throttle(key, "evict")
        if self._events is not None:
            from .kube.events import ReasonQoSEvicted

            ns, _, name = key.partition("/")
            try:
                self._events.pod_event(
                    ns, name, ReasonQoSEvicted,
                    "TPU bindings reclaimed: sustained overcommit past "
                    "the throttle deadline "
                    f"({report.get('reclaimed_pods', 0)} record(s))",
                    type_="Warning",
                )
            except Exception:  # noqa: BLE001
                pass
        logger.warning(
            "repartition: evicted %s (still over quota at the throttle "
            "deadline; %s)", key, report,
        )

    def _emit_throttle(
        self, pod_key: str, action: str,
        deadline_ts: Optional[float] = None,
    ) -> None:
        if self._timeline is not None:
            from .timeline import KIND_THROTTLE

            try:
                base = self._base_quotas(pod_key)
            except StorageError:  # chips keys are best-effort here
                base = None
            self._timeline.emit(
                KIND_THROTTLE,
                keys={
                    "pod": pod_key,
                    "chips": sorted(base["chips"]) if base else [],
                },
                action=action,
                deadline_ts=deadline_ts,
            )
        if action == "throttle" and self._events is not None:
            from .kube.events import ReasonThrottled

            ns, _, name = pod_key.partition("/")
            try:
                self._events.pod_event(
                    ns, name, ReasonThrottled,
                    "sustained overcommit: TPU quota clamped to the "
                    "base grant; bindings reclaimed at "
                    f"{int(deadline_ts or 0)} unless usage returns "
                    "within quota",
                    type_="Warning",
                )
            except Exception:  # noqa: BLE001
                pass

    def _grow_from_slack(
        self, meta: dict, now: float, dirty: set, moves: List[dict],
        result: dict,
    ) -> None:
        with self._lock:
            throttled = set(self._throttles)
            evicted = set(self._evicted)
        def eligible(key):
            m = meta[key]
            return (
                m["opted"] and m["used"] is not None
                and key not in throttled and key not in evicted
            )

        # A borrower must be HONESTLY hungry: at or near its quota
        # (busy_frac) but still respecting it (within the overcommit
        # margin). A pod already blowing past quota gets the
        # escalation path, never a reward.
        borrowers = [
            key for key in meta if eligible(key)
            and meta[key]["used"]
            >= self.busy_frac * max(1, meta[key]["eff"])
            and meta[key]["used"]
            <= meta[key]["eff"] + self.overcommit_margin
            # growth past the pod's own qos-core-units cap would move
            # ledger units its stamped env can never expose
            and (
                meta[key]["cap"] is None
                or meta[key]["eff"] < meta[key]["cap"]
            )
        ]
        donors = [
            key for key in meta if eligible(key)
            and meta[key]["used"]
            <= self.idle_frac * max(1, meta[key]["eff"])
            and meta[key]["eff"] - self.step_units >= self.min_keep_units
        ]
        if not borrowers or not donors:
            return
        # Most-starved borrowers first, high priority outranking low.
        borrowers.sort(key=lambda k: (
            0 if meta[k]["priority"] == "high" else 1,
            -(meta[k]["used"] / max(1, meta[k]["eff"])),
            k,
        ))
        for bkey in borrowers:
            b = meta[bkey]
            best: Optional[Tuple[str, int, int]] = None
            for dkey in donors:
                if dkey == bkey:
                    continue
                d = meta[dkey]
                # Donation precedence: high never donates to low.
                if d["priority"] == "high" and b["priority"] == "low":
                    continue
                with self._lock:
                    reverse = any(
                        e["donor"] == bkey and e["borrower"] == dkey
                        for e in self._edges
                    )
                if reverse:
                    # A standing edge the other way means the borrower
                    # is really reclaiming its own donation — that is
                    # the shrink path's job; stacking an offsetting
                    # edge would make the ledger unreadable.
                    continue
                shared = set(d["chips"]) & set(b["chips"])
                if not shared:
                    continue  # slack only moves between co-tenants
                slack = d["eff"] - self.min_keep_units
                if slack <= 0:
                    continue
                chip = min(shared)
                if best is None or slack > best[1]:
                    best = (dkey, slack, chip)
            if best is None:
                continue
            dkey, slack, chip = best
            units = min(self.step_units, slack)
            if b["cap"] is not None:
                units = min(units, b["cap"] - b["eff"])
            if units <= 0:
                continue
            d = meta[dkey]
            hbm = 0
            if d["base"]["hbm_bytes"] and b["base"]["hbm_bytes"]:
                # Ride the donor's own core:HBM ratio so its residual
                # quota keeps the shape its workload was sized for.
                with self._lock:
                    donor_hbm_eff = (
                        d["base"]["hbm_bytes"]
                        + self._hbm_delta_locked(dkey)
                    )
                hbm = min(
                    donor_hbm_eff,
                    int(
                        d["base"]["hbm_bytes"]
                        * units / max(1, d["base"]["core_units"])
                    ),
                )
            with self._lock:
                for e in self._edges:
                    if (
                        e["donor"] == dkey and e["borrower"] == bkey
                        and e["chip"] == chip
                    ):
                        e["core_units"] += units
                        e["hbm_bytes"] = e.get("hbm_bytes", 0) + hbm
                        break
                else:
                    self._edges.append({
                        "donor": dkey,
                        "borrower": bkey,
                        "chip": chip,
                        "core_units": units,
                        "hbm_bytes": hbm,
                    })
                self._repartitions["grow"] += 1
            # Keep this tick's bookkeeping coherent for later donors.
            d["eff"] -= units
            b["eff"] += units
            dirty.add(dkey)
            dirty.add(bkey)
            result["grown"] += 1
            moves.append({
                "direction": "grow",
                "donor": dkey,
                "borrower": bkey,
                "chip": chip,
                "core_units": units,
                "hbm_bytes": hbm,
            })

    def _sweep_departed_throttles(self, meta: dict, dirty: set) -> None:
        """A pod deleted while throttled must take its throttle (and
        expired deadline) with it: a later pod re-created under the
        same name would otherwise inherit the stale entry and be
        evicted on its first over-quota tick with zero grace. Two
        signals: the store record is GONE (pod left, keyed sweep), or
        the live pod's UID no longer matches the one the throttle was
        armed against (same name, different pod). A sitter blip with
        the binding still present keeps the throttle armed."""
        with self._lock:
            throttled = {
                k: v.get("uid", "") for k, v in self._throttles.items()
            }
        for key, armed_uid in throttled.items():
            departed = (
                key not in meta and self._peer_departed(key)
            )
            recreated = (
                key in meta and armed_uid
                and meta[key]["uid"] != armed_uid
            )
            if not departed and not recreated:
                continue
            with self._lock:
                self._throttles.pop(key, None)
            self._over_streak.pop(key, None)
            dirty.add(key)  # journals the drop; restamp heals/no-ops
            logger.info(
                "repartition: %s %s while throttled; throttle dropped",
                key, "was re-created" if recreated else "left the node",
            )

    def _sweep_evicted(self) -> None:
        """Evicted pods drop out of the suppression set once they are
        actually gone (sitter no longer sees them) OR once the live pod
        under that name carries a different UID (deleted and re-created
        between ticks) — a re-created pod starts clean either way."""
        with self._lock:
            evicted = dict(self._evicted)
        gone = []
        for key, armed_uid in evicted.items():
            ns, _, name = key.partition("/")
            pod = self._sitter.get_pod(ns, name)
            if pod is None:
                gone.append(key)
                continue
            live_uid = (pod.get("metadata") or {}).get("uid", "")
            if armed_uid and live_uid != armed_uid:
                gone.append(key)
        if gone:
            with self._lock:
                for key in gone:
                    self._evicted.pop(key, None)
                self._journal_locked()

    # -- the supervised loop ---------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Supervised loop (DEGRADED): resume the journaled ledger, then
        tick at a jittered period (0.75x-1.25x) — the drain/reconciler
        discipline, including the 3-strikes escalation."""
        self.resume()
        consecutive_failures = 0
        last_tick = 0.0
        while True:
            delay = self.period_s * (0.75 + 0.5 * self._rng.random())
            sub = self._event_sub
            if (
                sub is not None and self._bus.healthy()
                and not self._fractional()
            ):
                # Exclusive mode: the tick has no units to move, the
                # sweep is purely a safety net — stretch it.
                delay *= self.event_safety_net_factor
            if sub is None:
                if stop.wait(delay):
                    return
            else:
                trigger = sub.wait_trigger(stop, delay)
                if trigger == "stop":
                    return
                if trigger == "event":
                    # Coalesce the burst AND pace event ticks: a churn
                    # storm degrades to ~4 extra ticks per period, not
                    # one tick per event.
                    min_gap = min(1.0, self.period_s / 4.0)
                    pace = max(0.02, min_gap - (
                        time.monotonic() - last_tick
                    ))
                    if stop.wait(pace):
                        return
                    sub.drain()
                    self.event_ticks_total += 1
            try:
                last_tick = time.monotonic()
                self.tick()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001
                consecutive_failures += 1
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"
                if consecutive_failures >= 3:
                    raise
                logger.exception(
                    "repartition tick failed (%d consecutive; "
                    "escalating to the supervisor at 3)",
                    consecutive_failures,
                )

    # -- introspection ---------------------------------------------------------

    def status(self) -> dict:
        """The ``repartition`` block of /debug/allocations and the
        doctor bundle: the live donation ledger, throttle deadlines and
        lifetime totals — quota-drift triage must work from a bundle
        alone."""
        with self._lock:
            return {
                "enabled": self._fractional(),
                "period_s": self.period_s,
                "step_units": self.step_units,
                "edges": [dict(e) for e in self._edges],
                "throttled_pods": {
                    k: dict(v) for k, v in self._throttles.items()
                },
                "evicted_pods": sorted(self._evicted),
                "pending_restamp": sorted(self._pending_restamp),
                "repartitions_total": dict(self._repartitions),
                "throttles_total": self._throttles_total,
                "evictions_total": self._evictions_total,
                "last_tick_ts": self._last_tick_ts,
                "last_error": self._last_error,
            }
