"""Dependency-free allocation-lifecycle tracing.

The agent's observability so far answered *aggregate* questions
(histograms, gauges, Events) but not the one operators actually ask:
"walk me through what happened to THIS pod's allocation". This module
is the spine for that: every allocation-path entry point (Allocate,
PreStartContainer, GC sweep, restore) opens a **trace** — a correlation
id plus an ordered list of named, timed **spans** — and the layers it
crosses (locator, operator, storage) attach spans without any explicit
plumbing, via a contextvar. Completed traces land in a bounded ring
buffer served by the agent's debug endpoint (``/debug/traces``,
metrics.py) and the trace id rides along on the k8s Events, the
ElasticTPU CRD message, and the alloc-spec env
(``ELASTIC_TPU_TRACE_ID``) so the in-pod flight recorder
(workloads/telemetry.py) can tag its step records with the same id —
one string correlates `kubectl describe pod`, the agent's debug dump,
and the workload's own step telemetry.

Design constraints:
- **Zero dependencies** (stdlib only): the tracer must import in the
  agent container, the test rig, and workload images alike.
- **Never load-bearing**: tracing failures must not fail a bind. Spans
  opened with no active trace are recorded nowhere and cost two
  monotonic reads.
- **Thread-confined mutation**: a Trace is only ever mutated by the
  thread that opened it (contextvars are per-thread in the gRPC
  worker pool), so Trace/Span need no locks; only the shared ring
  append takes one.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

_current_trace: "ContextVar[Optional[Trace]]" = ContextVar(
    "elastic_tpu_trace", default=None
)

DEFAULT_CAPACITY = 256
# Spans slower than this are logged at WARNING with their trace id so a
# stalling layer (apiserver List, wedged /dev) is visible in the agent
# log even before anyone pulls /debug/traces.
DEFAULT_SLOW_SPAN_S = 0.25


def new_trace_id() -> str:
    """16 hex chars; collision odds are irrelevant at ring-buffer scale."""
    return os.urandom(8).hex()


class Span:
    """One named, timed section inside a trace."""

    __slots__ = ("name", "attrs", "error", "_t0", "offset_s", "duration_s")

    def __init__(self, name: str, offset_s: float, **attrs) -> None:
        self.name = name
        self.attrs: Dict[str, object] = dict(attrs)
        self.error: Optional[str] = None
        self._t0 = time.monotonic()
        self.offset_s = offset_s
        self.duration_s = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def _finish(self) -> None:
        self.duration_s = time.monotonic() - self._t0

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "offset_ms": round(self.offset_s * 1000, 3),
            "duration_ms": round(self.duration_s * 1000, 3),
            "attrs": dict(self.attrs),
        }
        if self.error is not None:
            out["error"] = self.error
        return out


class Trace:
    """A correlation id plus the ordered spans recorded under it."""

    __slots__ = (
        "trace_id", "name", "attrs", "spans", "error",
        "start_ts", "_t0", "duration_s", "_discarded",
    )

    def __init__(self, name: str, **attrs) -> None:
        self.trace_id = new_trace_id()
        self.name = name
        self.attrs: Dict[str, object] = dict(attrs)
        self.spans: List[Span] = []
        self.error: Optional[str] = None
        self.start_ts = time.time()
        self._t0 = time.monotonic()
        self.duration_s = 0.0
        self._discarded = False

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def discard(self) -> None:
        """Drop this trace at finish instead of recording it — used by
        periodic sweeps (GC tick) whose no-op passes would otherwise
        churn useful traces out of the bounded ring."""
        self._discarded = True

    def elapsed_s(self) -> float:
        return time.monotonic() - self._t0

    def to_dict(self) -> dict:
        out = {
            "trace_id": self.trace_id,
            "name": self.name,
            "start_ts": self.start_ts,
            "duration_ms": round(self.duration_s * 1000, 3),
            "attrs": dict(self.attrs),
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.error is not None:
            out["error"] = self.error
        return out


class Tracer:
    """Ring buffer of completed traces + the contextvar plumbing."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        slow_span_s: float = DEFAULT_SLOW_SPAN_S,
    ) -> None:
        self.capacity = capacity
        self.slow_span_s = slow_span_s
        self._ring: "deque[Trace]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.completed = 0  # lifetime count (ring only keeps the newest)
        # Completed-trace listeners (latency observatory) and slow-span
        # listeners (timeline slow_span events). Both fire on the
        # recording thread and must never break it — failures are
        # swallowed at WARNING. Lists, not sets: registration order is
        # deterministic and callables need not be hashable.
        self._listeners: List = []
        self._slow_span_listeners: List = []

    def add_listener(self, fn) -> None:
        """Register ``fn(trace)``, called after every non-discarded
        trace lands in the ring."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def add_slow_span_listener(self, fn) -> None:
        """Register ``fn(trace, span)``, called when a span under an
        active trace exceeds ``slow_span_s``."""
        with self._lock:
            if fn not in self._slow_span_listeners:
                self._slow_span_listeners.append(fn)

    def remove_slow_span_listener(self, fn) -> None:
        with self._lock:
            if fn in self._slow_span_listeners:
                self._slow_span_listeners.remove(fn)

    def ring_bytes(self, sample: int = 16) -> int:
        """Approximate bytes held by the trace ring: the JSON-encoded
        size of the newest ``sample`` traces extrapolated over the ring
        length. An estimate by design — exact accounting would
        serialize every trace on every scrape; this is the bounded-
        memory gauge (elastic_tpu_trace_ring_bytes) the scale harness
        asserts a ceiling against, not a byte-exact ledger."""
        import json

        with self._lock:
            n = len(self._ring)
            if n == 0:
                return 0
            newest = [self._ring[-1 - i] for i in range(min(n, sample))]
        sampled = 0
        counted = 0
        for tr in newest:
            try:
                sampled += len(json.dumps(tr.to_dict(), default=str))
                counted += 1
            except Exception:  # noqa: BLE001 - estimate must not raise
                continue
        if not counted:
            return 0
        return int(sampled / counted * n)

    # -- recording ------------------------------------------------------------

    @contextlib.contextmanager
    def trace(self, name: str, **attrs):
        """Open a trace for the duration of the block; it becomes the
        thread's current trace (span()/annotate() attach to it). An
        exception is recorded on the trace and re-raised; the trace is
        kept — a FAILED bind is exactly the trace someone will want."""
        tr = Trace(name, **attrs)
        token = _current_trace.set(tr)
        try:
            yield tr
        except BaseException as e:
            tr.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            _current_trace.reset(token)
            tr.duration_s = tr.elapsed_s()
            if not tr._discarded:
                with self._lock:
                    self._ring.append(tr)
                    self.completed += 1
                    listeners = list(self._listeners)
                for fn in listeners:
                    try:
                        fn(tr)
                    except Exception:  # noqa: BLE001 - never load-bearing
                        logger.warning(
                            "trace listener %r failed", fn, exc_info=True
                        )

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Record a named span under the current trace; a no-op (but
        still yields a settable Span) when no trace is active, so
        instrumented layers never need to know whether they are inside
        a traced request."""
        tr = _current_trace.get()
        sp = Span(name, tr.elapsed_s() if tr is not None else 0.0, **attrs)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp._finish()
            if tr is not None:
                tr.spans.append(sp)
                if sp.duration_s >= self.slow_span_s:
                    logger.warning(
                        "slow span %s (%.1f ms) in trace %s (%s)%s",
                        sp.name, sp.duration_s * 1000, tr.trace_id,
                        tr.name,
                        f": {sp.error}" if sp.error else "",
                    )
                    with self._lock:
                        listeners = list(self._slow_span_listeners)
                    for fn in listeners:
                        try:
                            fn(tr, sp)
                        except Exception:  # noqa: BLE001
                            logger.warning(
                                "slow-span listener %r failed",
                                fn, exc_info=True,
                            )

    def current(self) -> Optional[Trace]:
        return _current_trace.get()

    def current_id(self) -> str:
        tr = _current_trace.get()
        return tr.trace_id if tr is not None else ""

    def annotate(self, **attrs) -> None:
        """Attach attributes to the current trace, if any."""
        tr = _current_trace.get()
        if tr is not None:
            tr.set(**attrs)

    def annotate_pod(self, pod: str) -> None:
        """Mark the current trace as involving ``pod``. Unlike a plain
        annotate(pod=...), repeat calls ACCUMULATE — a GC sweep that
        reclaims several pods must be findable under each of them."""
        tr = _current_trace.get()
        if tr is None:
            return
        pods = tr.attrs.setdefault("pods", [])
        if pod not in pods:
            pods.append(pod)

    def adopt_id(self, trace_id: str) -> None:
        """Adopt an externally-assigned correlation id for the current
        trace (cross-component continuity: the scheduler/admission side
        stamps ``elasticgpu.io/trace-id`` on the pod, and the agent that
        ends up binding it continues under the SAME id, so one string
        follows the pod from apiserver admission to whichever node bound
        it). The locally-generated id is preserved as an attribute for
        log-line correlation."""
        tr = _current_trace.get()
        if tr is None or not trace_id or tr.trace_id == trace_id:
            return
        tr.attrs.setdefault("local_trace_id", tr.trace_id)
        tr.trace_id = trace_id

    # -- reading --------------------------------------------------------------

    def dump(
        self,
        pod: Optional[str] = None,
        limit: Optional[int] = None,
        trace_id: Optional[str] = None,
    ) -> List[dict]:
        """Completed traces, newest first; ``pod`` filters on the
        trace's pod attribute (exact "ns/name" or bare pod name);
        ``trace_id`` filters on the exact correlation id (the fleet
        aggregator's continuity lookup)."""
        with self._lock:
            traces = list(self._ring)
        traces.reverse()
        out = []
        for tr in traces:
            if limit is not None and len(out) >= limit:
                break
            if trace_id and tr.trace_id != trace_id:
                continue
            if pod:
                candidates = [str(tr.attrs.get("pod", ""))]
                candidates.extend(
                    str(p) for p in tr.attrs.get("pods", []) or []
                )
                if not any(
                    c == pod or c.rpartition("/")[2] == pod
                    for c in candidates if c
                ):
                    continue
            out.append(tr.to_dict())
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def _default_tracer() -> Tracer:
    slow_ms = os.environ.get("ELASTIC_TPU_SLOW_SPAN_MS", "")
    try:
        slow_s = float(slow_ms) / 1000 if slow_ms else DEFAULT_SLOW_SPAN_S
    except ValueError:
        slow_s = DEFAULT_SLOW_SPAN_S
    return Tracer(slow_span_s=slow_s)


_tracer = _default_tracer()


def get_tracer() -> Tracer:
    """The process-wide tracer every layer records into. One agent
    process serves one node, so a single ring is the right scope; tests
    swap it with set_tracer() for isolation."""
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    global _tracer
    prev, _tracer = _tracer, tracer
    return prev
