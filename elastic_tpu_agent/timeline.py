"""Black-box lifecycle timeline: a durable, causally-ordered event journal.

PRs 5-8 made the agent deeply stateful — WAL-journaled binds, nine
reconciler divergence classes, slice epochs, a drain state machine — but
triage stayed point-in-time: metrics are aggregates, the trace ring and
``/debug/traces`` die with the process, and the doctor bundle is a
snapshot that cannot answer "*why* did slice S land at epoch 3?" or
"what sequence of events reclaimed pod P?". Arax (PAPERS.md) argues the
mapping layer must own placement *and its history* to stay debuggable
once applications are decoupled from accelerators; the edge-accelerator
characterization work makes the same point for per-container behavior —
observations only explain anything when they are *attributed over
time*, not sampled.

This module is that history. Every state transition the agent already
makes calls :meth:`Timeline.emit` with the join keys the transition
already has in hand:

- bind transaction phases: ``bind_intent`` / ``bind_commit`` /
  ``bind_rollback`` / ``bind_replay`` (plugins/tpushare.py);
- every reconciler repair, one ``reconcile_repair`` event per repair
  with the divergence class as an attribute (reconciler.py);
- drain state-machine transitions (``drain_transition``, drain.py);
- slice formation stamps and reforms with their epoch
  (``slice_formed`` / ``slice_reformed``, slices/);
- health and cordon flips (``chip_health`` / ``cordon``), GC reclaims
  (``pod_reclaimed``);
- supervisor restarts and circuit-breaker trips
  (``subsystem_restart`` / ``subsystem_crash_loop``), and one
  ``agent_started`` per boot (version + boot id), so restarts are
  visible *inside* histories instead of explaining their gaps.

Events land in a ring-capped Storage table (``timeline``): restart
durable (same SQLite file as the checkpoint store — one fsync domain,
one hostPath mount), monotonic per-agent seq numbers that survive both
the ring trim and agent restarts, and a durable eviction counter so
bounded growth is itself observable. Reads never require a live agent —
``node-doctor timeline`` reconstructs a history straight from the db of
a dead agent, exactly like the open-intent reader.

The journal is observability, never load-bearing: :meth:`Timeline.emit`
swallows every failure (a full disk must not fail a bind), and every
call site treats the timeline as optional.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Iterable, List, Optional

from .common import SYSTEM_CLOCK

logger = logging.getLogger(__name__)

# Ring cap: bounds the table under pod churn. ~4k events keeps weeks of
# steady-state lifecycle on a quiet node and the full story of a busy
# incident; at ~300 bytes/row the table stays under ~1.5 MB.
DEFAULT_CAP = 4096

# -- event kinds --------------------------------------------------------------

KIND_AGENT_STARTED = "agent_started"
# bind transaction phases (plugins/tpushare.py)
KIND_BIND_INTENT = "bind_intent"
KIND_BIND_COMMIT = "bind_commit"
KIND_BIND_ROLLBACK = "bind_rollback"
KIND_BIND_REPLAY = "bind_replay"
# one per reconciler repair; the divergence class rides in attrs["class"]
KIND_RECONCILE_REPAIR = "reconcile_repair"
# drain lifecycle (drain.py): attrs carry state/trigger/deadline
KIND_DRAIN_TRANSITION = "drain_transition"
# slice orchestration (slices/): epoch in attrs
KIND_SLICE_FORMED = "slice_formed"
KIND_SLICE_REFORMED = "slice_reformed"
# health & schedulability
KIND_CHIP_HEALTH = "chip_health"
KIND_CORDON = "cordon"
# GC reclaim of a deleted pod's bindings (the reconciler's reclaims are
# reconcile_repair events with class=reclaimed_pod)
KIND_POD_RECLAIMED = "pod_reclaimed"
# dynamic fractional re-partitioning (repartition.py): one event per
# executed quota move (attrs: direction grow|shrink, donor, borrower,
# core_units, hbm_bytes) keyed by pod + chip, so a grant's growth/shrink
# history reconstructs causally next to its binds and drains
KIND_REPARTITION = "repartition"
# sustained-overcommit escalation (repartition.py): attrs.action is
# throttle | unthrottle | evict, with the evict deadline where relevant
KIND_THROTTLE = "throttle"
# migration handshake (migration.py): attrs.action walks the record's
# life — recorded | record_published | early_reclaim (source side),
# restore_stamped | completed | verify_failed (destination side) — all
# keyed pod + the SOURCE bind's trace id, so one id links the drain,
# the checkpoint ack and the verified resume across nodes
KIND_MIGRATION = "migration"
# supervision (supervisor.py)
KIND_SUBSYSTEM_RESTART = "subsystem_restart"
KIND_SUBSYSTEM_CRASH_LOOP = "subsystem_crash_loop"
# latency outliers (tracing.py slow-span listener via manager.py):
# keyed pod + trace so a stall lands in the causal journal next to the
# bind or drain it delayed
KIND_SLOW_SPAN = "slow_span"


class Timeline:
    """The agent's append-only lifecycle journal (one per agent/node).

    Join keys are a small, fixed vocabulary — ``pod`` ("ns/name"),
    ``container``, ``slice``, ``chips`` (list of ints), ``hash``,
    ``trace``, ``node`` — the ids the rest of the system already
    stamps everywhere, so per-entity histories are reconstructable by
    key equality alone. ``node`` is auto-filled from the agent's
    identity and ``trace`` from the thread's active trace (tracing.py),
    so call sites only name what the generic plumbing cannot know.
    """

    def __init__(
        self,
        storage,
        node_name: str = "",
        metrics=None,
        cap: int = DEFAULT_CAP,
        clock=None,
    ) -> None:
        self._storage = storage
        self._node = node_name
        self._metrics = metrics
        self.cap = max(1, cap)
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        # One boot id per Timeline (== per manager instance): stamped on
        # agent_started and into the doctor bundle, so two histories
        # from the same node are attributable to the right process.
        self.boot_id = os.urandom(4).hex()
        self._lock = threading.Lock()
        self.emitted_total = 0
        self.dropped_total = 0  # emits the journal write lost

    # -- writing --------------------------------------------------------------

    def emit(
        self, kind: str, keys: Optional[dict] = None, **attrs
    ) -> Optional[int]:
        """Journal one lifecycle event; returns its seq, or None when
        the write failed (never raises — the journal is observability).
        """
        try:
            event_keys: Dict[str, object] = dict(keys or {})
            event_keys.setdefault("node", self._node)
            if "trace" not in event_keys:
                from .tracing import get_tracer

                trace_id = get_tracer().current_id()
                if trace_id:
                    event_keys["trace"] = trace_id
            seq = self._storage.timeline_append(
                self._clock.time(), kind, event_keys, attrs, self.cap
            )
            if kind == KIND_AGENT_STARTED:
                # Boot identity also lands in the never-evicted meta
                # table: the doctor bundle must answer "did it restart
                # mid-incident" even after churn has trimmed the
                # agent_started ROW out of the ring.
                self._storage.timeline_set_meta(
                    "timeline_boot_id", str(attrs.get("boot_id", ""))
                )
                self._storage.timeline_set_meta(
                    "timeline_agent_version",
                    str(attrs.get("version", "")),
                )
            with self._lock:
                self.emitted_total += 1
            m = self._metrics
            if m is not None and hasattr(m, "timeline_events"):
                try:
                    m.timeline_events.inc()
                except Exception:  # noqa: BLE001
                    pass
            return seq
        except Exception as e:  # noqa: BLE001 - never load-bearing
            with self._lock:
                self.dropped_total += 1
            logger.warning("timeline emit %s dropped: %s", kind, e)
            return None

    # -- reading --------------------------------------------------------------

    def events(
        self,
        pod: Optional[str] = None,
        slice_id: Optional[str] = None,
        chip: Optional[int] = None,
        node: Optional[str] = None,
        trace: Optional[str] = None,
        kinds: Optional[Iterable[str]] = None,
        since: Optional[float] = None,
        limit: Optional[int] = None,
        causal: bool = True,
    ) -> List[dict]:
        """The journal filtered to one entity's history, seq-ordered.
        With ``causal=True`` (the default) the direct matches are
        expanded along their join keys — see :func:`select_events`."""
        rows = self._storage.timeline_rows(since_ts=since)
        return select_events(
            rows, pod=pod, slice_id=slice_id, chip=chip, node=node,
            trace=trace, kinds=kinds, limit=limit, causal=causal,
        )

    def status(self) -> dict:
        """The ``timeline`` block shared by /debug/timeline, the doctor
        bundle and tests: durable counters + this boot's identity."""
        try:
            count = self._storage.timeline_count()
            evicted = self._storage.timeline_evicted_total()
        except Exception:  # noqa: BLE001 - storage may be closed
            count, evicted = None, None
        with self._lock:
            return {
                "cap": self.cap,
                "total_events": count,
                "evicted_total": evicted,
                "emitted_this_boot": self.emitted_total,
                "dropped_this_boot": self.dropped_total,
                "boot_id": self.boot_id,
                "node": self._node,
            }


# -- pure selection / reconstruction helpers ----------------------------------
#
# Module-level so the fleet aggregator can run the SAME entity filter
# over a merged multi-node event list that Timeline.events runs over one
# node's journal — one matching semantics, wherever the rows came from.


def _direct_match(
    event: dict,
    pod: Optional[str],
    slice_id: Optional[str],
    chip: Optional[int],
    node: Optional[str],
    trace: Optional[str],
) -> bool:
    keys = event.get("keys", {})
    if pod is not None:
        cand = str(keys.get("pod", ""))
        if cand != pod and cand.rpartition("/")[2] != pod:
            return False
    if slice_id is not None and keys.get("slice") != slice_id:
        return False
    if chip is not None and chip not in (keys.get("chips") or []):
        return False
    if node is not None and keys.get("node") != node:
        return False
    if trace is not None and keys.get("trace") != trace:
        return False
    return True


# Node-scoped lifecycle context: events with no pod/slice/trace of
# their own that are nonetheless part of every co-located entity's
# story — a pod's history that omits "the agent restarted" or "the
# node started draining" explains its reclaim with a gap where the
# cause goes.
CONTEXT_KINDS = frozenset({
    KIND_AGENT_STARTED,
    KIND_DRAIN_TRANSITION,
    KIND_CORDON,
    KIND_SUBSYSTEM_CRASH_LOOP,
})


def select_events(
    rows: List[dict],
    pod: Optional[str] = None,
    slice_id: Optional[str] = None,
    chip: Optional[int] = None,
    node: Optional[str] = None,
    trace: Optional[str] = None,
    kinds: Optional[Iterable[str]] = None,
    limit: Optional[int] = None,
    causal: bool = True,
) -> List[dict]:
    """Filter a seq-ordered event list down to one entity's history.

    Two passes. First, **direct** matches by join-key equality (pod
    accepts bare names like the trace dump does). Second, when
    ``causal=True`` and an entity filter was given, the history is
    expanded along causal links, each expansion flagged
    ``"related": True``:

    - events sharing a *trace id* or a *slice id* with a direct match
      — so a pod's history includes the reform that restamped it
      (emitted under its slice, possibly on another node) and the
      reconciler repair that rolled its crashed bind back (emitted
      under the reconcile pass's trace);
    - node-scoped lifecycle context (:data:`CONTEXT_KINDS` — agent
      boots, drain transitions, cordons, breaker trips) on any node a
      direct match lives on, plus ``chip_health`` flips touching the
      entity's chips — the "why" behind a reclaim is usually one of
      these.

    With no entity filter the journal is returned as-is (kind/limit
    still applied)."""
    entity_filtered = any(
        v is not None for v in (pod, slice_id, chip, node, trace)
    )
    if not entity_filtered:
        selected = list(rows)
    else:
        direct = [
            e for e in rows
            if _direct_match(e, pod, slice_id, chip, node, trace)
        ]
        if causal:
            traces = {
                e["keys"].get("trace") for e in direct
                if e["keys"].get("trace")
            }
            slices = {
                e["keys"].get("slice") for e in direct
                if e["keys"].get("slice")
            }
            nodes = {
                e["keys"].get("node") for e in direct
                if e["keys"].get("node")
            }
            chips: set = set()
            for e in direct:
                chips.update(e["keys"].get("chips") or [])
            direct_seqs = {
                (e["keys"].get("node"), e["seq"]) for e in direct
            }
            selected = []
            for e in rows:
                key = (e["keys"].get("node"), e["seq"])
                if key in direct_seqs:
                    selected.append(e)
                    continue
                linked = (
                    e["keys"].get("trace") in traces
                    or (slices and e["keys"].get("slice") in slices)
                    or (
                        e["kind"] in CONTEXT_KINDS
                        and e["keys"].get("node") in nodes
                    )
                    or (
                        e["kind"] == KIND_CHIP_HEALTH
                        and chips
                        and chips & set(e["keys"].get("chips") or [])
                    )
                )
                if linked:
                    related = dict(e)
                    related["related"] = True
                    selected.append(related)
        else:
            selected = direct
    if kinds is not None:
        kind_set = set(kinds)
        selected = [e for e in selected if e["kind"] in kind_set]
    if limit is not None and limit >= 0:
        selected = selected[-limit:] if limit else []
    return selected


def event_by_ref(
    rows: List[dict], node: str, seq,
) -> Optional[dict]:
    """Resolve a ``(node, seq)`` cause reference (the id every
    non-productive goodput interval carries — goodput.py) back to its
    journal event, or None when the ring has since evicted it."""
    for e in rows:
        if e.get("seq") == seq and (
            e.get("keys", {}).get("node", "") == node
        ):
            return e
    return None


def merge_node_events(per_node: Dict[str, List[dict]]) -> List[dict]:
    """Interleave per-node journals into one fleet-ordered causal view.

    K-way merge by wall time that NEVER reorders one node's events
    against each other: within a node, seq order is the causal order
    (the emitting thread journaled before the next transition ran), so
    the merge only chooses *between* nodes by ts — adopted trace ids
    then stitch the cross-node story (admission → bind → reform) that
    no single clock could. Ties break by node name for determinism."""
    heads = {
        node: 0 for node, events in per_node.items() if events
    }
    out: List[dict] = []
    while heads:
        best_node = min(
            heads,
            key=lambda n: (per_node[n][heads[n]].get("ts", 0.0), n),
        )
        out.append(per_node[best_node][heads[best_node]])
        heads[best_node] += 1
        if heads[best_node] >= len(per_node[best_node]):
            del heads[best_node]
    return out


def verify_bind_story(events: List[dict]) -> List[str]:
    """Consistency check over a (single- or merged-) journal's bind
    events; returns problems (empty = the story holds). The crash-replay
    suite runs this after every kill-at-a-failpoint replay:

    - **no phantom commits**: every ``bind_commit`` that names an
      intent id must be preceded (per node) by the matching
      ``bind_intent``. Claimed only for nodes whose journal still
      starts at seq 1: once the ring has evicted rows, a missing
      intent event is indistinguishable from an evicted one (eviction
      drops oldest-first, so the commit can outlive its intent — but
      never the other way around, which is why the dangling check
      below stays valid under eviction);
    - **no dangling intents**: every ``bind_intent`` must be resolved —
      a later commit, an explicit ``bind_rollback``, or a reconciler
      ``reconcile_repair`` whose class names the intent's fate
      (``intent_rolled_back`` / ``intent_committed``) — once the system
      has converged (callers run this only after convergence).
    """
    problems: List[str] = []
    open_intents: Dict[tuple, dict] = {}
    min_seq: Dict[str, int] = {}
    for e in events:
        node = e.get("keys", {}).get("node", "")
        seq = e.get("seq")
        if isinstance(seq, int):
            min_seq[node] = min(min_seq.get(node, seq), seq)
    for e in events:
        node = e.get("keys", {}).get("node", "")
        kind = e.get("kind")
        attrs = e.get("attrs", {})
        intent_id = attrs.get("intent_id")
        if kind == KIND_BIND_INTENT and intent_id is not None:
            open_intents[(node, intent_id)] = e
        elif kind == KIND_BIND_COMMIT:
            if intent_id is not None:
                if (
                    (node, intent_id) not in open_intents
                    and min_seq.get(node) == 1
                ):
                    problems.append(
                        f"phantom commit: seq {e.get('seq')} on "
                        f"{node or '?'} commits intent {intent_id} with "
                        "no preceding bind_intent event"
                    )
                open_intents.pop((node, intent_id), None)
        elif kind == KIND_BIND_ROLLBACK and intent_id is not None:
            open_intents.pop((node, intent_id), None)
        elif kind == KIND_RECONCILE_REPAIR and attrs.get("class") in (
            "intent_rolled_back", "intent_committed",
        ):
            if intent_id is not None:
                open_intents.pop((node, intent_id), None)
    for (node, intent_id), e in sorted(
        open_intents.items(), key=lambda kv: kv[1].get("seq", 0)
    ):
        problems.append(
            f"dangling intent: seq {e.get('seq')} on {node or '?'} "
            f"journaled bind_intent {intent_id} for "
            f"{e.get('keys', {}).get('pod')} and no surviving event "
            "resolves it"
        )
    return problems
