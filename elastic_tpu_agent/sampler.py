"""Utilization & health accounting: who is using which chip, right now?

PR 1's tracing answers *what happened* to an allocation; nothing so far
answers *what is happening* on the chips. The agent advertises
fractional resources (tpu-core in percent, tpu-memory in MiB) but a
fractional grant without utilization attribution is an honor system
nobody can audit (docs/operations.md "honest QoS boundary"). This
module closes that gap:

- ``UtilizationSampler`` periodically pulls per-chip duty cycle and HBM
  usage from the operator (``TPUOperator.utilization()`` — sysfs-backed
  on TPU-VMs, injectable on the stub), joins each sample against the
  allocation store to attribute usage to pods, and maintains rolling
  1m/5m windows per chip and per pod.
- Per-pod *used* core percent is attributed proportionally to each
  pod's granted share of its chips (TPUs expose no per-process duty
  counters, so chip-level duty split by grant share is the honest
  attribution — a sole tenant's used == the chip's duty cycle).
- A pod whose attributed usage stays above its grant for
  ``overcommit_sustain_samples`` consecutive samples is a detected
  **overcommit**: the ``elastic_tpu_overcommit_detected_total`` counter
  increments once per episode and a structured JSON log record
  (``"kind": "tpu_overcommit"``, carrying the bind's trace id) is
  emitted so log pipelines can join it with /debug/traces.
- A chip whose telemetry read *fails* ``unhealthy_after_failures``
  times in a row is flagged; the plugin health poll folds that flag
  into the ListAndWatch stream (tpushare.health_once), so a chip the
  sampler can no longer read degrades to Unhealthy in kubelet's view.

Everything is observable three ways: labeled Prometheus gauges
(metrics.py), the live ``/debug/allocations`` table on the agent
endpoint, and the ``node-doctor`` diagnostics bundle
(build_diagnostics_bundle / validate_bundle, cli.py).

Like tracing.py, this module is dependency-free and never
load-bearing: a sampler failure must not affect binding.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from . import faults
from .common import (
    BytesPerMemoryUnit,
    FlightSummarySubdir,
    ResourceTPUCore,
    TPUPercentEachChip,
    UsageReportSubdir,
)

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 10.0
# Rolling windows served per chip and per pod; keys are the public names
# used in /debug/allocations and the doctor bundle.
WINDOWS = {"1m": 60.0, "5m": 300.0}
# A pod is overcommitting when attributed usage exceeds grant by this
# margin (percentage points) — duty-cycle counters jitter; a pod at
# 31% of a 30% grant is noise, not theft.
DEFAULT_OVERCOMMIT_MARGIN = 5.0
# ... for this many consecutive samples ("sustained").
DEFAULT_OVERCOMMIT_SUSTAIN = 3
# Telemetry-read failures before a chip is flagged unhealthy.
DEFAULT_UNHEALTHY_AFTER_FAILURES = 3

# Window deques are pruned by horizon on write; the maxlen is only a
# backstop against a clock that never advances.
_MAX_WINDOW_SAMPLES = 720

# How long a pod's self-reported usage file stays fresh. TPUs expose no
# per-process duty counters, so chip duty split by grant share is the
# best EXTERNAL attribution — but a pod that opted into live
# re-partitioning (repartition.py) can do better: its runtime writes
# {"ts", "duty_cycle_percent"} to <alloc_spec_dir>/usage/<TPU hash>.json
# (the same agent<->pod surface the env file rides), and the sampler
# takes that as the pod's measured usage, attributing only the REMAINING
# chip duty to the non-reporting co-tenants. Stale reports (a wedged or
# exited workload) fall back to proportional attribution.
USAGE_REPORT_TTL_S = 30.0
USAGE_REPORT_SUBDIR = UsageReportSubdir
# A report stamped FROM THE FUTURE (skewed workload clock, bad ts
# argument) must not stay "fresh" forever and defeat the TTL fallback.
USAGE_REPORT_FUTURE_SLACK_S = 5.0


def _window_stats(samples, horizon_s: float, now: float) -> dict:
    """{"samples", "mean", "max", "last"} over (ts, value) pairs within
    ``horizon_s`` of ``now``."""
    vals = [v for ts, v in samples if now - ts <= horizon_s]
    if not vals:
        return {"samples": 0, "mean": None, "max": None, "last": None}
    return {
        "samples": len(vals),
        "mean": round(sum(vals) / len(vals), 3),
        "max": round(max(vals), 3),
        "last": round(vals[-1], 3),
    }


class UtilizationSampler:
    """Continuous per-chip / per-pod utilization accounting daemon."""

    def __init__(
        self,
        operator,
        storage=None,
        metrics=None,
        alloc_spec_dir: Optional[str] = None,
        period_s: float = DEFAULT_PERIOD_S,
        overcommit_margin_percent: float = DEFAULT_OVERCOMMIT_MARGIN,
        overcommit_sustain_samples: int = DEFAULT_OVERCOMMIT_SUSTAIN,
        unhealthy_after_failures: int = DEFAULT_UNHEALTHY_AFTER_FAILURES,
        lag_tracker=None,
        bus=None,
    ) -> None:
        self._operator = operator
        self._storage = storage
        self._metrics = metrics
        self._alloc_spec_dir = alloc_spec_dir
        self.period_s = period_s
        # Event bus (events.py): assignment/bind deltas trigger an
        # EARLY sample so the pod<->allocation join reflects a new or
        # departed tenant immediately. Telemetry cadence itself stays
        # at period_s — the sampling period is the product here, so
        # the sweep is never stretched for this loop.
        self._event_sub = None
        if bus is not None:
            from . import events as bus_events

            self._event_sub = bus.subscribe(
                "sampler",
                (bus_events.ASSIGNMENT_DELTA, bus_events.STORE_BIND),
            )
        self.event_samples_total = 0
        self.overcommit_margin = overcommit_margin_percent
        self.overcommit_sustain = max(1, overcommit_sustain_samples)
        self.unhealthy_after = max(1, unhealthy_after_failures)
        # Set by the manager once the plugin exists: () -> {resource:
        # {cache_entries, ...}} — locator cache introspection for the
        # debug table and the doctor bundle.
        self.locator_stats_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> bind-pipeline stats (in-flight binds,
        # gRPC pool size, bind-lock contention) from the plugin's
        # bind_stats(); rides into /debug/allocations and the bundle.
        self.bind_stats_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> reconciler status (last run, per-class
        # repair totals, open bind intents with age) from
        # Reconciler.status(); rides into /debug/allocations and the
        # doctor bundle so a stuck intent is diagnosable from either.
        self.reconcile_status_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> slice-registry status (per-slice world,
        # epoch, local member pods, reform count, validation verdicts)
        # from SliceRegistry.status(); the `slices` block of
        # /debug/allocations and the doctor bundle.
        self.slice_status_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> drain-orchestrator status (lifecycle
        # state, trigger, deadline, signalled/reclaimed pods) from
        # DrainOrchestrator.status(); the `drain` block of
        # /debug/allocations and the doctor bundle — drain-stuck triage
        # must work from a bundle alone.
        self.drain_status_fn: Optional[Callable[[], dict]] = None
        # Optional: () -> serving-engine stats (ServingEngine.stats():
        # block-pool occupancy, prefix-cache hit/miss/eviction
        # counters) — the `serving` block of /debug/allocations and
        # the doctor bundle. NO agent subsystem wires this today (the
        # agent hosts no engine): a process that embeds an engine next
        # to a sampler assigns it directly, same as
        # AgentMetrics.attach_serving. Absent -> no serving block.
        self.serving_status_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> repartition-controller status (edges,
        # throttles, evict deadlines) from RepartitionController.status();
        # the `repartition` block of /debug/allocations and the bundle.
        self.repartition_status_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> migration-coordinator status (per-pod
        # ack freshness, outbound MigrationRecords, inbound resume
        # verifications) from MigrationCoordinator.status(); the
        # `migration` block of /debug/allocations and the doctor bundle
        # — "are we actually checkpointing?" from one scrape.
        self.migration_status_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: () -> event-bus stats (EventBus.stats():
        # published-by-topic, per-subscriber depth/drops, degraded
        # sources) — the `event_bus` block of /debug/allocations and
        # the doctor bundle. A dropped-event gap is triaged from this
        # plus the detection-lag trigger split (docs/operations.md).
        self.event_bus_stats_fn: Optional[Callable[[], dict]] = None
        # Also manager-set: (pod_key) -> signed core-percent delta the
        # repartition controller currently applies on top of the pod's
        # base grant. The overcommit detector judges usage against the
        # EFFECTIVE grant — without this, growing a borrower's quota
        # would immediately trip the very alarm the growth authorized.
        self.grant_adjust_fn: Optional[Callable[[str], float]] = None
        # Staleness bound on self-reported usage files (test seam).
        self.usage_report_ttl_s = USAGE_REPORT_TTL_S
        # Manager-set: (pod_key) -> whether this pod's self-reports are
        # trusted (the repartition opt-in check). Self-reports feed the
        # throttle->evict ENFORCEMENT path: without the gate, any pod
        # could under-report and shift phantom duty onto a co-tenant the
        # controller then punishes. None (standalone samplers, tests)
        # accepts all reports — nothing enforces there.
        self.usage_report_allowed_fn: Optional[
            Callable[[str], bool]
        ] = None
        # Also manager-set: () -> set of unhealthy chip indexes, the
        # plugin's APPLIED health view. Snapshots must read this (a
        # plain set copy) instead of re-probing the operator:
        # TPUVMOperator.healthy_indexes() mutates sticky state with no
        # lock and is owned by the single health-poll thread — calling
        # it from ThreadingHTTPServer handler threads would race it.
        self.unhealthy_view_fn: Optional[Callable[[], set]] = None

        self._lock = threading.Lock()
        # chip index -> deque[(ts, duty_percent)] / deque[(ts, hbm_bytes)]
        self._chip_duty: Dict[int, deque] = {}
        self._chip_hbm: Dict[int, deque] = {}
        # pod key ("ns/name") -> deque[(ts, used_percent)]
        self._pod_used: Dict[str, deque] = {}
        self._fail_streak: Dict[int, int] = {}
        self._flagged: Dict[int, str] = {}      # chip -> unhealthy reason
        self._overcommit_streak: Dict[str, int] = {}
        self._overcommit_active: set = set()
        self._trace_ids: Dict[str, str] = {}    # alloc hash -> trace id
        self._last_pods: Dict[str, dict] = {}   # last join, keyed by pod
        self._last_chips: Dict[int, dict] = {}  # last sample, keyed by chip
        self._last_sample_ts: Optional[float] = None
        self.samples_total = 0
        self.overcommit_episodes = 0
        # DetectionLagTracker (latency.py): chip-health flags report
        # lag from the injected telemetry-failure origin; usage reports
        # report lag from the file's own "ts" stamp (written by the
        # workload) — both only when the origin is strictly new.
        self._lag = lag_tracker
        self._report_ts: Dict[str, float] = {}  # pod key -> newest "ts"

    # -- the periodic loop ----------------------------------------------------

    def start(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.run, args=(stop,), daemon=True, name="tpu-sampler"
        )
        t.start()
        return t

    def run(self, stop: threading.Event) -> None:
        """Blocking sample loop until ``stop`` (supervised entry point).
        With an event bus, assignment/bind deltas cut the wait short so
        the join pass runs immediately (coalesced behind a short
        debounce); the cadence otherwise stays period_s."""
        while not stop.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampling must never wedge
                logger.exception("utilization sample failed")
            last = time.monotonic()
            sub = self._event_sub
            if sub is None:
                if stop.wait(self.period_s):
                    return
                continue
            trigger = sub.wait_trigger(stop, self.period_s)
            if trigger == "stop":
                return
            if trigger == "event":
                # Pace event-triggered samples: a churn storm of bind
                # deltas coalesces to at most one extra join pass per
                # min_gap, never a sample per event.
                min_gap = min(0.5, self.period_s / 4.0)
                gap = min_gap - (time.monotonic() - last)
                if gap > 0 and stop.wait(gap):
                    return
                sub.drain()
                self.event_samples_total += 1

    # -- one sample -----------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> dict:
        """Take one sample; returns the join result (also kept for
        snapshot/debug readers). ``now`` is a test seam."""
        faults.fire("sampler.sample")
        now = time.time() if now is None else now
        try:
            util = self._operator.utilization() or {}
        except Exception as e:  # noqa: BLE001 - backend failure != crash
            logger.warning("operator utilization read failed: %s", e)
            util = {}
        try:
            chips = {c.index: c for c in self._operator.devices()}
        except Exception:  # noqa: BLE001
            chips = {}
        grants = self._join_allocations()
        reports = self._read_usage_reports(grants, now)
        self._read_flight_summaries(grants, now)
        with self._lock:
            self._record_chip_samples(util, chips, now)
            self._attribute_pods(util, grants, now, reports)
            self._last_pods = grants
            self._last_sample_ts = now
            self.samples_total += 1
        self._export_metrics(util, grants)
        return {"chips": dict(self._last_chips), "pods": grants}

    def _record_chip_samples(self, util: dict, chips: dict, now: float) -> None:
        """(lock held) Fold the raw backend samples into the chip windows
        and the telemetry-failure streaks."""
        self._last_chips = {}
        for idx in chips:
            entry = util.get(idx)
            if entry is None:
                # No telemetry for this chip (backend unsupported or
                # silent): not a failure signal — never flag on absence,
                # and RELEASE any standing flag (a driver reload that
                # removes the telemetry file must not leave the chip
                # Unhealthy until agent restart).
                self._fail_streak.pop(idx, None)
                if self._flagged.pop(idx, None) is not None:
                    logger.info(
                        "chip %d: telemetry gone; clearing sampler "
                        "health flag", idx,
                    )
                continue
            if entry.get("error"):
                streak = self._fail_streak.get(idx, 0) + 1
                self._fail_streak[idx] = streak
                if streak >= self.unhealthy_after and idx not in self._flagged:
                    reason = (
                        f"utilization telemetry failing "
                        f"({streak} consecutive samples): {entry['error']}"
                    )
                    self._flagged[idx] = reason
                    logger.warning("chip %d: %s", idx, reason)
                    if self._lag is not None:
                        origin = None
                        fn = getattr(self._operator, "origin_ts", None)
                        if fn is not None:
                            try:
                                origin = fn("utilization")
                            except Exception:  # noqa: BLE001
                                origin = None
                        # Flagging IS the sampler's repair: downstream
                        # (reconciler/plugin) acts on the flag.
                        self._lag.handled(
                            "sampler", "chip_unhealthy", key=str(idx),
                            origin_ts=origin,
                        )
                self._last_chips[idx] = {"error": entry["error"]}
                continue
            if self._fail_streak.pop(idx, 0) and idx in self._flagged:
                logger.info(
                    "chip %d: utilization telemetry recovered", idx
                )
            self._flagged.pop(idx, None)
            duty = float(entry.get("duty_cycle_percent", 0.0))
            hbm = int(entry.get("hbm_used_bytes", 0))
            self._chip_duty.setdefault(
                idx, deque(maxlen=_MAX_WINDOW_SAMPLES)
            ).append((now, duty))
            self._chip_hbm.setdefault(
                idx, deque(maxlen=_MAX_WINDOW_SAMPLES)
            ).append((now, hbm))
            self._prune(self._chip_duty[idx], now)
            self._prune(self._chip_hbm[idx], now)
            self._last_chips[idx] = {
                "duty_cycle_percent": duty, "hbm_used_bytes": hbm,
            }

    @staticmethod
    def _prune(samples: deque, now: float) -> None:
        horizon = max(WINDOWS.values())
        while samples and now - samples[0][0] > horizon:
            samples.popleft()

    # -- allocation join ------------------------------------------------------

    def _join_allocations(self) -> Dict[str, dict]:
        """Snapshot the allocation store into
        pod key -> {containers, chips: {chip: core grant %}, granted_percent,
        hbm_granted_bytes, resources, hashes, last_trace_id}."""
        out: Dict[str, dict] = {}
        if self._storage is None:
            return out
        whole_chip = not getattr(self._operator, "virtual_nodes", True)
        try:
            items = list(self._storage.items())
        except Exception:  # noqa: BLE001 - storage trouble is not ours
            logger.exception("sampler: allocation-store snapshot failed")
            return out
        for key, info in items:
            pod = out.setdefault(key, {
                "containers": [], "chips": {}, "granted_percent": 0.0,
                "hbm_granted_bytes": 0, "resources": [], "hashes": [],
                "last_trace_id": "",
            })
            for container, by_resource in info.allocations.items():
                if container not in pod["containers"]:
                    pod["containers"].append(container)
                for resource, rec in by_resource.items():
                    if resource not in pod["resources"]:
                        pod["resources"].append(resource)
                    pod["hashes"].append(rec.device.hash)
                    trace_id = self._trace_id_for(rec.device.hash)
                    if trace_id:
                        pod["last_trace_id"] = trace_id
                    if resource == ResourceTPUCore:
                        if whole_chip:
                            granted = TPUPercentEachChip * max(
                                1, len(rec.chip_indexes)
                            )
                        else:
                            granted = float(len(rec.device.ids))
                        pod["granted_percent"] += granted
                        n = max(1, len(rec.chip_indexes))
                        for chip in rec.chip_indexes:
                            pod["chips"][chip] = (
                                pod["chips"].get(chip, 0.0) + granted / n
                            )
                    else:
                        pod["hbm_granted_bytes"] += (
                            len(rec.device.ids) * BytesPerMemoryUnit
                        )
                        for chip in rec.chip_indexes:
                            pod["chips"].setdefault(chip, 0.0)
        return out

    def _trace_id_for(self, alloc_hash: str) -> str:
        """The trace id of the bind that produced this allocation, read
        (once) from its alloc-spec env — the same id that names the
        /debug/traces entry and the pod's TPUBound event."""
        if alloc_hash in self._trace_ids:
            return self._trace_ids[alloc_hash]
        trace_id = ""
        if self._alloc_spec_dir:
            path = os.path.join(self._alloc_spec_dir, f"{alloc_hash}.json")
            try:
                with open(path) as f:
                    spec = json.load(f)
                trace_id = str(
                    spec.get("env", {}).get("ELASTIC_TPU_TRACE_ID", "")
                )
            except (OSError, ValueError):
                # Spec not written yet (bind in flight): retry next sample.
                return ""
        self._trace_ids[alloc_hash] = trace_id
        return trace_id

    def _read_usage_reports(
        self, grants: Dict[str, dict], now: float
    ) -> Dict[str, float]:
        """pod key -> self-reported duty percent, for pods with a FRESH
        usage file under <alloc_spec_dir>/usage/<hash>.json (the
        cooperative half of the repartition contract — see
        USAGE_REPORT_TTL_S above). Reads happen outside the sampler
        lock; a malformed or stale file simply falls back to
        proportional attribution."""
        out: Dict[str, float] = {}
        if not self._alloc_spec_dir:
            return out
        usage_dir = os.path.join(self._alloc_spec_dir, USAGE_REPORT_SUBDIR)
        if not os.path.isdir(usage_dir):
            return out
        for key, pod in grants.items():
            if self.usage_report_allowed_fn is not None:
                try:
                    if not self.usage_report_allowed_fn(key):
                        continue  # not opted in: report untrusted
                except Exception:  # noqa: BLE001 - fail closed
                    continue
            best_ts = None
            best_duty = None
            for alloc_hash in pod["hashes"]:
                path = os.path.join(usage_dir, f"{alloc_hash}.json")
                try:
                    with open(path) as f:
                        report = json.load(f)
                    ts = float(report["ts"])
                    duty = float(report["duty_cycle_percent"])
                except (OSError, ValueError, KeyError, TypeError):
                    continue
                if (
                    now - ts > self.usage_report_ttl_s
                    or ts - now > USAGE_REPORT_FUTURE_SLACK_S
                    or duty < 0
                ):
                    continue
                if best_ts is None or ts > best_ts:
                    best_ts, best_duty = ts, duty
            if best_duty is not None:
                out[key] = best_duty
                if (
                    self._lag is not None
                    and best_ts is not None
                    and best_ts > self._report_ts.get(key, float("-inf"))
                ):
                    # Only a strictly NEWER report counts: re-reading a
                    # still-on-disk file next pass is not a new event.
                    self._report_ts[key] = best_ts
                    self._lag.handled(
                        "sampler", "usage_report", key=key,
                        origin_ts=best_ts,
                    )
        return out

    def _read_flight_summaries(
        self, grants: Dict[str, dict], now: float
    ) -> None:
        """Fold fresh flight-recorder sidecar summaries
        (<alloc_spec_dir>/flight/<hash>.json, written by
        telemetry.write_flight_summary) into the join: the pod's
        ACHIEVED tokens/s rides /debug/allocations and the
        elastic_tpu_workload_tokens_per_second{pod} gauge. Display
        only — never an enforcement signal — so no trust gate; the
        same TTL/future-slack staleness rules as usage reports."""
        if not self._alloc_spec_dir:
            return
        flight_dir = os.path.join(self._alloc_spec_dir, FlightSummarySubdir)
        if not os.path.isdir(flight_dir):
            return
        for key, pod in grants.items():
            best_ts = None
            best = None
            best_ttft = None
            for alloc_hash in pod["hashes"]:
                path = os.path.join(flight_dir, f"{alloc_hash}.json")
                try:
                    with open(path) as f:
                        summary = json.load(f)
                    ts = float(summary["ts"])
                    rate = float(summary["tokens_per_s"])
                except (OSError, ValueError, KeyError, TypeError):
                    continue
                if (
                    now - ts > self.usage_report_ttl_s
                    or ts - now > USAGE_REPORT_FUTURE_SLACK_S
                    or rate < 0
                ):
                    continue
                if best_ts is None or ts > best_ts:
                    best_ts, best = ts, rate
                    # serving pods ride their median TTFT along; it
                    # inherits the SAME freshness verdict as the rate
                    ttft = summary.get("ttft_p50_s")
                    try:
                        best_ttft = (
                            float(ttft)
                            if ttft is not None and float(ttft) >= 0
                            else None
                        )
                    except (ValueError, TypeError):
                        best_ttft = None
            if best is not None:
                pod["tokens_per_s"] = best
                if best_ttft is not None:
                    pod["ttft_p50_s"] = best_ttft

    # -- attribution + overcommit ---------------------------------------------

    def _attribute_pods(
        self, util: dict, grants: dict, now: float,
        reports: Optional[Dict[str, float]] = None,
    ) -> None:
        """(lock held) Attribute each chip's duty cycle to its pods —
        self-reported usage verbatim where a fresh report exists, the
        REMAINING duty split across non-reporting pods proportionally to
        their grant share — then run the sustained overcommit
        detector."""
        reports = reports or {}
        chip_total_grant: Dict[int, float] = {}
        pod_total_share: Dict[str, float] = {}
        for key, pod in grants.items():
            pod_total_share[key] = sum(pod["chips"].values())
            if key in reports:
                continue  # reporters don't compete for the remainder
            for chip, share in pod["chips"].items():
                chip_total_grant[chip] = (
                    chip_total_grant.get(chip, 0.0) + share
                )
        # Reported duty pinned to chips (a multi-chip reporter's duty is
        # split by its own grant-share proportions) so the remainder the
        # non-reporters divide is what the reporters did NOT claim.
        reported_chip_duty: Dict[int, float] = {}
        for key, duty in reports.items():
            pod = grants.get(key)
            if pod is None or not pod["chips"]:
                continue
            own_total = pod_total_share.get(key, 0.0)
            for chip, share in pod["chips"].items():
                frac = (
                    share / own_total if own_total > 0
                    else 1.0 / len(pod["chips"])
                )
                reported_chip_duty[chip] = (
                    reported_chip_duty.get(chip, 0.0) + duty * frac
                )
        for key, pod in grants.items():
            used = 0.0
            covered = False
            if key in reports:
                # Measured, not assumed: the pod's own runtime telemetry
                # is current evidence even when chip counters lag.
                used = reports[key]
                covered = True
                pod["self_reported"] = True
            else:
                for chip, share in pod["chips"].items():
                    sample = self._last_chips.get(chip)
                    if not sample or "duty_cycle_percent" not in sample:
                        continue
                    covered = True
                    duty = max(
                        0.0,
                        sample["duty_cycle_percent"]
                        - reported_chip_duty.get(chip, 0.0),
                    )
                    total = chip_total_grant.get(chip, 0.0)
                    if total > 0:
                        used += duty * (share / total)
                    elif len(
                        [p for p in grants.values() if chip in p["chips"]]
                    ) == 1:
                        # Memory-only sole tenant: the whole duty is its.
                        used += duty
            pod["used_percent"] = round(used, 3) if covered else None
            pod["granted_percent"] = round(pod["granted_percent"], 3)
            if covered:
                self._pod_used.setdefault(
                    key, deque(maxlen=_MAX_WINDOW_SAMPLES)
                ).append((now, used))
                self._prune(self._pod_used[key], now)
                self._detect_overcommit(key, pod, used, now)
            else:
                # Coverage lost (telemetry failing/gone): there is no
                # current evidence, so stop asserting overcommit rather
                # than freezing a stale flag in /debug/allocations.
                self._overcommit_streak.pop(key, None)
                if key in self._overcommit_active:
                    self._overcommit_active.discard(key)
                    logger.info(
                        "pod %s: chip telemetry lost; clearing "
                        "overcommit flag", key,
                    )
            pod["overcommit"] = key in self._overcommit_active
        # Forget pods that left the store: windows, streaks, metric series.
        for gone in set(self._pod_used) - set(grants):
            self._pod_used.pop(gone, None)
            self._overcommit_streak.pop(gone, None)
            self._overcommit_active.discard(gone)
            self._drop_pod_series(gone)
        live_hashes = {
            h for pod in grants.values() for h in pod["hashes"]
        }
        for stale in set(self._trace_ids) - live_hashes:
            del self._trace_ids[stale]

    def _detect_overcommit(
        self, key: str, pod: dict, used: float, now: float
    ) -> None:
        granted = pod["granted_percent"]
        if self.grant_adjust_fn is not None:
            # The repartition controller may have grown (or shrunk) this
            # pod's quota on top of the store-derived base grant; the
            # alarm must judge usage against the EFFECTIVE grant.
            try:
                adjust = float(self.grant_adjust_fn(key))
            except Exception:  # noqa: BLE001 - never load-bearing
                adjust = 0.0
            if adjust:
                granted = max(0.0, granted + adjust)
                pod["effective_granted_percent"] = round(granted, 3)
        if granted <= 0 or used <= granted + self.overcommit_margin:
            self._overcommit_streak[key] = 0
            if key in self._overcommit_active:
                self._overcommit_active.discard(key)
                logger.info(
                    "pod %s back within its core grant "
                    "(used %.1f%% of %.1f%%)", key, used, granted,
                )
            return
        streak = self._overcommit_streak.get(key, 0) + 1
        self._overcommit_streak[key] = streak
        if streak < self.overcommit_sustain or key in self._overcommit_active:
            return
        self._overcommit_active.add(key)
        self.overcommit_episodes += 1
        if self._metrics is not None and hasattr(
            self._metrics, "overcommit_detected"
        ):
            self._metrics.overcommit_detected.inc()
        # Structured record (not prose): log pipelines join this with
        # /debug/traces on trace_id and with the flight recorder's JSONL.
        logger.warning("%s", json.dumps({
            "kind": "tpu_overcommit",
            "ts": now,
            "pod": key,
            "granted_core_percent": granted,
            "used_core_percent": round(used, 3),
            "chips": sorted(pod["chips"]),
            "sustained_samples": streak,
            "trace_id": pod.get("last_trace_id", ""),
        }, sort_keys=True))

    # -- metrics export -------------------------------------------------------

    def _export_metrics(self, util: dict, grants: dict) -> None:
        m = self._metrics
        if m is None:
            return
        try:
            for idx, sample in self._last_chips.items():
                if "duty_cycle_percent" in sample:
                    m.chip_duty_cycle.labels(chip=str(idx)).set(
                        sample["duty_cycle_percent"]
                    )
                    m.chip_hbm_used.labels(chip=str(idx)).set(
                        sample["hbm_used_bytes"]
                    )
            for key, pod in grants.items():
                m.pod_core_granted.set(pod["granted_percent"], pod=key)
                if pod.get("used_percent") is not None:
                    m.pod_core_used.set(pod["used_percent"], pod=key)
                if hasattr(m, "workload_tokens_per_s"):
                    if pod.get("tokens_per_s") is not None:
                        m.workload_tokens_per_s.set(
                            pod["tokens_per_s"], pod=key
                        )
                    elif hasattr(m.workload_tokens_per_s, "remove"):
                        # no FRESH summary this sample: the series goes
                        # away rather than freezing a dead workload's
                        # last rate on the scrape
                        m.workload_tokens_per_s.remove(pod=key)
                if hasattr(m, "workload_ttft"):
                    # same stale-summary drop rule as tokens/s: the
                    # TTFT series exists only while summaries are fresh
                    if pod.get("ttft_p50_s") is not None:
                        m.workload_ttft.set(pod["ttft_p50_s"], pod=key)
                    elif hasattr(m.workload_ttft, "remove"):
                        m.workload_ttft.remove(pod=key)
        except Exception:  # noqa: BLE001 - metrics must never break sampling
            logger.exception("sampler metrics export failed")

    def _drop_pod_series(self, key: str) -> None:
        m = self._metrics
        if m is None:
            return
        for gauge_name in (
            "pod_core_granted", "pod_core_used", "workload_tokens_per_s",
            "workload_ttft",
        ):
            gauge = getattr(m, gauge_name, None)
            if gauge is not None and hasattr(gauge, "remove"):
                try:
                    gauge.remove(pod=key)
                except Exception:  # noqa: BLE001 - absent series is fine
                    pass

    # -- health view (consumed by tpushare.health_once) -----------------------

    def unhealthy_chips(self) -> set:
        """Chips the sampler currently flags (telemetry failing)."""
        with self._lock:
            return set(self._flagged)

    def health_reasons(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._flagged)

    # -- read surfaces --------------------------------------------------------

    @property
    def last_sample_ts(self) -> Optional[float]:
        with self._lock:
            return self._last_sample_ts

    def chip_windows(self, now: Optional[float] = None) -> Dict[int, dict]:
        """chip -> {"1m": stats, "5m": stats} over duty cycle, plus HBM."""
        now = time.time() if now is None else now
        with self._lock:
            out = {}
            for idx, samples in self._chip_duty.items():
                out[idx] = {
                    name: _window_stats(samples, horizon, now)
                    for name, horizon in WINDOWS.items()
                }
                hbm = self._chip_hbm.get(idx)
                if hbm:
                    out[idx]["hbm"] = {
                        name: _window_stats(hbm, horizon, now)
                        for name, horizon in WINDOWS.items()
                    }
            return out

    def utilization_view(self) -> dict:
        """Copies of the last join — the repartition controller's input:
        ``pods`` (pod -> chips/granted/used/self_reported/overcommit),
        ``chips`` (chip -> last raw sample) and the sample's timestamp.
        Safe from any thread; never blocks on sampling."""
        with self._lock:
            return {
                "pods": {
                    k: {**v, "chips": dict(v["chips"])}
                    for k, v in self._last_pods.items()
                },
                "chips": {k: dict(v) for k, v in self._last_chips.items()},
                "ts": self._last_sample_ts,
            }

    def pod_windows(self, now: Optional[float] = None) -> Dict[str, dict]:
        now = time.time() if now is None else now
        with self._lock:
            return {
                key: {
                    name: _window_stats(samples, horizon, now)
                    for name, horizon in WINDOWS.items()
                }
                for key, samples in self._pod_used.items()
            }

    def allocations_snapshot(self) -> dict:
        """The live chip->pod binding table served at /debug/allocations
        and embedded in the node-doctor bundle."""
        try:
            devices = self._operator.devices()
        except Exception:  # noqa: BLE001
            devices = []
        healthy = None
        if self.unhealthy_view_fn is not None:
            # Live agent: the plugin's applied view (a set copy — safe
            # from any thread, and already includes our own flags).
            try:
                healthy = (
                    {c.index for c in devices} - self.unhealthy_view_fn()
                )
            except Exception:  # noqa: BLE001
                healthy = None
        if healthy is None:
            # Standalone (node-doctor without a running agent): probe the
            # operator directly — single-threaded there, so the mutation
            # inside healthy_indexes() is unshared.
            try:
                healthy = set(self._operator.healthy_indexes())
            except Exception:  # noqa: BLE001
                healthy = set()
        try:
            op_reasons = dict(self._operator.health_reasons())
        except Exception:  # noqa: BLE001
            op_reasons = {}
        # Windows are computed relative to the last sample's clock so a
        # snapshot taken long after sampling stopped (doctor on a wedged
        # agent) still shows the final windows instead of empty ones.
        with self._lock:
            snapshot_now = self._last_sample_ts
        pod_windows = self.pod_windows(now=snapshot_now)
        with self._lock:
            flagged = dict(self._flagged)
            pods = {k: dict(v) for k, v in self._last_pods.items()}
            chips_last = {k: dict(v) for k, v in self._last_chips.items()}
            last_ts = self._last_sample_ts
            samples_total = self.samples_total
        chip_rows: List[dict] = []
        for chip in devices:
            idx = chip.index
            reason = flagged.get(idx) or op_reasons.get(idx)
            bound = sorted(k for k, p in pods.items() if idx in p["chips"])
            sample = chips_last.get(idx, {})
            chip_rows.append({
                "chip": idx,
                "healthy": idx in healthy and idx not in flagged,
                "health_reason": reason,
                "duty_cycle_percent": sample.get("duty_cycle_percent"),
                "hbm_used_bytes": sample.get("hbm_used_bytes"),
                "hbm_total_bytes": chip.hbm_bytes,
                "granted_core_percent": round(sum(
                    p["chips"][idx] for p in pods.values()
                    if idx in p["chips"]
                ), 3),
                "pods": bound,
            })
        pod_rows: List[dict] = []
        for key in sorted(pods):
            pod = pods[key]
            pod_rows.append({
                "pod": key,
                "containers": pod["containers"],
                "chips": sorted(pod["chips"]),
                "resources": sorted(pod["resources"]),
                "granted_core_percent": pod["granted_percent"],
                "used_core_percent": pod.get("used_percent"),
                "tokens_per_s": pod.get("tokens_per_s"),
                "ttft_p50_s": pod.get("ttft_p50_s"),
                "hbm_granted_bytes": pod["hbm_granted_bytes"],
                "overcommit": pod.get("overcommit", False),
                "last_trace_id": pod.get("last_trace_id", ""),
                "windows": pod_windows.get(key, {}),
            })
        out = {
            "chips": chip_rows,
            "pods": pod_rows,
            "sampler": {
                "period_s": self.period_s,
                "samples_total": samples_total,
                "last_sample_ts": last_ts,
                "overcommit_episodes": self.overcommit_episodes,
                "overcommit_margin_percent": self.overcommit_margin,
                "flagged_chips": sorted(flagged),
            },
        }
        if self.locator_stats_fn is not None:
            try:
                out["locator"] = self.locator_stats_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.bind_stats_fn is not None:
            try:
                out["bind"] = self.bind_stats_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.reconcile_status_fn is not None:
            try:
                out["reconcile"] = self.reconcile_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.slice_status_fn is not None:
            try:
                out["slices"] = self.slice_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.drain_status_fn is not None:
            try:
                out["drain"] = self.drain_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.repartition_status_fn is not None:
            try:
                out["repartition"] = self.repartition_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.migration_status_fn is not None:
            try:
                out["migration"] = self.migration_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.serving_status_fn is not None:
            try:
                out["serving"] = self.serving_status_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        if self.event_bus_stats_fn is not None:
            try:
                out["event_bus"] = self.event_bus_stats_fn()
            except Exception:  # noqa: BLE001 - introspection only
                pass
        return out


# -- node-doctor diagnostics bundle -------------------------------------------

BUNDLE_KIND = "elastic-tpu-node-doctor"
BUNDLE_VERSION = 1


def _fetch_json(url: str, timeout_s: float) -> dict:
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        # The agent endpoint replies JSON on every status — a 503
        # /healthz (critical subsystem circuit-broken) is exactly the
        # bundle a support escalation needs, not a fetch failure.
        return json.loads(e.read())


def build_diagnostics_bundle(
    operator,
    sampler: Optional[UtilizationSampler] = None,
    tracer=None,
    node_name: str = "",
    agent_url: str = "",
    trace_limit: int = 50,
    http_timeout_s: float = 3.0,
    storage=None,
    timeline_limit: int = 200,
) -> dict:
    """One JSON document with everything a support escalation needs:
    devices, health + reasons, raw error counters, the live allocation
    table with per-pod usage, sampler windows, and recent traces (pulled
    from the running agent when ``agent_url`` is given, else from the
    in-process ring)."""
    try:
        devices = [
            {
                "uuid": c.uuid, "index": c.index,
                "device_path": c.device_path, "hbm_bytes": c.hbm_bytes,
                "cores": c.cores, "extra_paths": list(c.extra_paths),
            }
            for c in operator.devices()
        ]
    except Exception as e:  # noqa: BLE001 - partial bundles beat no bundle
        devices = []
        logger.warning("doctor: device enumeration failed: %s", e)
    try:
        healthy = sorted(operator.healthy_indexes())
    except Exception:  # noqa: BLE001
        healthy = []
    try:
        reasons = {
            str(i): r for i, r in operator.health_reasons().items()
        }
    except Exception:  # noqa: BLE001
        reasons = {}
    try:
        counters = {
            str(i): dict(v) for i, v in operator.error_counters().items()
        }
    except Exception:  # noqa: BLE001
        counters = {}
    if sampler is not None:
        for i, r in sampler.health_reasons().items():
            reasons.setdefault(str(i), r)
    bundle = {
        "kind": BUNDLE_KIND,
        "version": BUNDLE_VERSION,
        "generated_ts": time.time(),
        "node": node_name,
        "devices": devices,
        "healthy_indexes": healthy,
        "health_reasons": reasons,
        "error_counters": counters,
        "allocations": (
            sampler.allocations_snapshot() if sampler is not None
            else {"chips": [], "pods": [], "sampler": {}}
        ),
        "sampler_windows": {
            "chips": {
                str(i): w for i, w in (
                    sampler.chip_windows(now=sampler.last_sample_ts)
                    if sampler is not None else {}
                ).items()
            },
            "pods": (
                sampler.pod_windows(now=sampler.last_sample_ts)
                if sampler is not None else {}
            ),
        },
        "traces": [],
        "subsystems": {},
        "reconcile": {},
        "agent": {"url": agent_url, "reachable": None},
    }
    # Self-memory: the doctor's process RSS (statm-backed, 0 where /proc
    # is unavailable) and the trace ring's approximate footprint — the
    # memory-ceiling numbers the scale harness asserts, observable from
    # a bundle too.
    try:
        from .common import read_rss_bytes
        from .tracing import get_tracer

        bundle["memory"] = {
            "rss_bytes": read_rss_bytes(),
            "trace_ring_bytes": get_tracer().ring_bytes(),
        }
    except Exception as e:  # noqa: BLE001 - partial bundles beat none
        logger.warning("doctor: memory accounting failed: %s", e)
        bundle["memory"] = {"rss_bytes": 0, "trace_ring_bytes": 0}
    # Lifecycle timeline: read straight from the checkpoint db (never
    # from the live agent) — the history must be attachable to an
    # escalation even when the agent is a corpse, and the db IS the
    # journal either way. The newest agent_started event stamps the
    # agent version + boot id into the bundle, so "did it restart mid-
    # incident" is answerable from the bundle alone.
    if storage is not None:
        try:
            rows = storage.timeline_rows()
            # Boot identity from the never-evicted meta side channel
            # (written by every agent_started emit); the event row
            # itself is the fallback for journals written before the
            # meta keys existed.
            boots = [e for e in rows if e["kind"] == "agent_started"]
            last_boot = boots[-1]["attrs"] if boots else {}
            bundle["timeline"] = {
                "events": rows[-timeline_limit:] if timeline_limit
                else rows,
                "total_events": storage.timeline_count(),
                "evicted_total": storage.timeline_evicted_total(),
                "agent_version": str(
                    storage.timeline_meta_value("timeline_agent_version")
                    or last_boot.get("version", "")
                ),
                "boot_id": str(
                    storage.timeline_meta_value("timeline_boot_id")
                    or last_boot.get("boot_id", "")
                ),
            }
        except Exception as e:  # noqa: BLE001 - partial bundles beat none
            logger.warning("doctor: timeline read failed: %s", e)
        # Goodput ledger: replayed straight from the db's journal +
        # journaled anchors (goodput.build_goodput_block) — downtime
        # attribution must be readable from a DEAD agent's db, and the
        # db IS the ledger's entire input either way.
        try:
            from .goodput import build_goodput_block

            bundle["goodput"] = build_goodput_block(storage)
        except Exception as e:  # noqa: BLE001 - partial bundles beat none
            logger.warning("doctor: goodput replay failed: %s", e)
    # Journal/reconciler state: from the live sampler hook when attached,
    # else straight from the checkpoint db — open intents must be
    # readable from a bundle even when the agent is down (that IS the
    # crashed-mid-bind case the journal exists for).
    live_reconcile = bundle["allocations"].get("reconcile")
    if isinstance(live_reconcile, dict):
        bundle["reconcile"] = live_reconcile
    elif storage is not None:
        try:
            bundle["reconcile"] = {
                "open_intents": storage.open_intents_brief(),
            }
        except Exception as e:  # noqa: BLE001 - partial bundles beat none
            logger.warning("doctor: journal read failed: %s", e)
    if agent_url:
        base = agent_url.rstrip("/")
        try:
            payload = _fetch_json(
                f"{base}/debug/traces?limit={trace_limit}", http_timeout_s
            )
            bundle["traces"] = payload.get("traces", [])
            bundle["agent"]["reachable"] = True
            try:
                healthz = _fetch_json(f"{base}/healthz", http_timeout_s)
                bundle["agent"]["healthz"] = healthz
                # Lift supervision state to the top level: "which loop is
                # dead" is the first question a support escalation asks.
                bundle["subsystems"] = healthz.get("subsystems", {})
                live = _fetch_json(
                    f"{base}/debug/allocations", http_timeout_s
                )
                bundle["agent"]["allocations"] = live
                if isinstance(live.get("reconcile"), dict):
                    # Same top-level lift as subsystems: "is a bind
                    # stuck?" is a first-page question.
                    bundle["reconcile"] = live["reconcile"]
            except Exception:  # noqa: BLE001 - traces were the hard part
                pass
            # Critical-path breakdown + self-profile: where the bind
            # milliseconds went (per-phase p50/p99, slowest traces with
            # their dominant phase) and what the agent itself was doing.
            # Each is optional — a pre-observatory agent 404s/503s here
            # and the bundle stays valid without the block.
            for key, path in (
                ("latency", "/debug/latency"),
                ("profile", "/debug/profile"),
                ("requests", "/debug/requests"),
            ):
                try:
                    bundle[key] = _fetch_json(
                        f"{base}{path}", http_timeout_s
                    )
                except Exception:  # noqa: BLE001 - optional block
                    pass
        except Exception as e:  # noqa: BLE001
            bundle["agent"]["reachable"] = False
            bundle["agent"]["error"] = str(e)
    elif tracer is not None:
        bundle["traces"] = tracer.dump(limit=trace_limit)
    return bundle


def validate_bundle(bundle: dict) -> List[str]:
    """Schema check for a doctor bundle; returns problems (empty = valid).
    Consumed by `make doctor-smoke` and by support tooling that refuses
    malformed escalation attachments."""
    problems: List[str] = []

    def expect(cond: bool, msg: str) -> None:
        if not cond:
            problems.append(msg)

    expect(isinstance(bundle, dict), "bundle is not an object")
    if not isinstance(bundle, dict):
        return problems
    expect(bundle.get("kind") == BUNDLE_KIND,
           f"kind must be {BUNDLE_KIND!r}, got {bundle.get('kind')!r}")
    expect(isinstance(bundle.get("version"), int) and bundle["version"] >= 1,
           "version must be an int >= 1")
    expect(isinstance(bundle.get("generated_ts"), (int, float)),
           "generated_ts must be a number")
    expect(isinstance(bundle.get("node"), str), "node must be a string")
    devices = bundle.get("devices")
    expect(isinstance(devices, list), "devices must be a list")
    for i, dev in enumerate(devices if isinstance(devices, list) else []):
        if not isinstance(dev, dict):
            problems.append(f"devices[{i}] must be an object")
            continue
        for field in ("index", "device_path", "hbm_bytes", "cores"):
            expect(field in dev, f"devices[{i}] missing {field!r}")
    expect(
        isinstance(bundle.get("healthy_indexes"), list)
        and all(isinstance(i, int) for i in bundle.get("healthy_indexes", [])),
        "healthy_indexes must be a list of ints",
    )
    for field in ("health_reasons", "error_counters"):
        expect(isinstance(bundle.get(field), dict),
               f"{field} must be an object")
    allocations = bundle.get("allocations")
    expect(isinstance(allocations, dict), "allocations must be an object")
    if isinstance(allocations, dict):
        expect(isinstance(allocations.get("chips"), list),
               "allocations.chips must be a list")
        expect(isinstance(allocations.get("pods"), list),
               "allocations.pods must be a list")
        for i, pod in enumerate(
            allocations.get("pods")
            if isinstance(allocations.get("pods"), list) else []
        ):
            if not isinstance(pod, dict):
                problems.append(f"allocations.pods[{i}] must be an object")
                continue
            for field in ("pod", "granted_core_percent", "overcommit"):
                expect(field in pod, f"allocations.pods[{i}] missing {field!r}")
    if isinstance(allocations, dict) and "slices" in allocations:
        # absent in pre-slice-orchestrator bundles and when no slice
        # registry is attached (standalone node-doctor)
        slices = allocations["slices"]
        expect(isinstance(slices, dict), "allocations.slices must be an "
                                         "object")
        for name, sl in (
            slices.items() if isinstance(slices, dict) else []
        ):
            if not isinstance(sl, dict):
                problems.append(
                    f"allocations.slices[{name!r}] must be an object"
                )
                continue
            for field in ("hosts", "world_size", "epoch", "reforms_total"):
                expect(field in sl,
                       f"allocations.slices[{name!r}] missing {field!r}")
    if isinstance(allocations, dict) and "drain" in allocations:
        # absent in pre-drain-orchestrator bundles and when no drain
        # status hook is attached (standalone node-doctor)
        drain = allocations["drain"]
        expect(isinstance(drain, dict), "allocations.drain must be an object")
        if isinstance(drain, dict):
            for field in ("state", "trigger", "drains_total"):
                expect(field in drain,
                       f"allocations.drain missing {field!r}")
            expect(
                drain.get("state") in (
                    "active", "cordoned", "draining", "drained", "reclaimed",
                ),
                f"allocations.drain.state {drain.get('state')!r} is not a "
                "lifecycle state",
            )
            for field in ("stamped_pods", "reclaimed_pods"):
                expect(isinstance(drain.get(field, []), list),
                       f"allocations.drain.{field} must be a list")
    if isinstance(allocations, dict) and "migration" in allocations:
        # absent in pre-migration-coordinator bundles and when no
        # migration status hook is attached (standalone node-doctor)
        migration = allocations["migration"]
        expect(isinstance(migration, dict),
               "allocations.migration must be an object")
        if isinstance(migration, dict):
            for field in ("early_reclaims_total",
                          "records_published_total", "completed_total"):
                expect(
                    isinstance(migration.get(field), int),
                    f"allocations.migration.{field} must be an int",
                )
            for field in ("acked_pods", "records", "inbound"):
                expect(isinstance(migration.get(field, {}), dict),
                       f"allocations.migration.{field} must be an object")
            expect(
                isinstance(migration.get("suppressed_pods", []), list),
                "allocations.migration.suppressed_pods must be a list",
            )
    if isinstance(allocations, dict) and "serving" in allocations:
        # absent unless a serving engine's stats hook is attached
        # (runner serve mode / tests); agent-only nodes have none
        serving = allocations["serving"]
        expect(isinstance(serving, dict),
               "allocations.serving must be an object")
        if isinstance(serving, dict):
            for field in ("pool_blocks", "used_blocks",
                          "pool_occupancy", "prefilled_tokens_total"):
                expect(field in serving,
                       f"allocations.serving missing {field!r}")
            if "prefix_cache" in serving:
                pc = serving["prefix_cache"]
                expect(isinstance(pc, dict),
                       "allocations.serving.prefix_cache must be an "
                       "object")
                if isinstance(pc, dict):
                    for field in ("hits", "misses", "evictions",
                                  "cached_blocks"):
                        expect(field in pc,
                               "allocations.serving.prefix_cache "
                               f"missing {field!r}")
            if "roles" in serving:
                # disaggregated prefill/decode engines over a shared
                # pool (serving.disaggregated_status); absent for a
                # unified engine
                roles = serving["roles"]
                expect(isinstance(roles, dict),
                       "allocations.serving.roles must be an object")
                for rname, rstat in (
                    roles.items() if isinstance(roles, dict) else []
                ):
                    if not isinstance(rstat, dict):
                        problems.append(
                            f"allocations.serving.roles[{rname!r}] must "
                            "be an object"
                        )
                        continue
                    for field in ("role", "queue_depth"):
                        expect(field in rstat,
                               f"allocations.serving.roles[{rname!r}] "
                               f"missing {field!r}")
            if "shared_pool" in serving:
                sp = serving["shared_pool"]
                expect(isinstance(sp, dict),
                       "allocations.serving.shared_pool must be an "
                       "object")
                if isinstance(sp, dict):
                    for field in ("adoptions", "adopted_tokens"):
                        expect(field in sp,
                               "allocations.serving.shared_pool "
                               f"missing {field!r}")
            if "speculative" in serving:
                # present only when the engine runs a draft model
                spec = serving["speculative"]
                expect(isinstance(spec, dict),
                       "allocations.serving.speculative must be an "
                       "object")
                if isinstance(spec, dict):
                    for field in ("rounds", "drafted_tokens",
                                  "accepted_tokens", "rejected_tokens"):
                        expect(field in spec,
                               "allocations.serving.speculative "
                               f"missing {field!r}")
            if "moe" in serving:
                # present only when MoE routing stats are attached
                moe = serving["moe"]
                expect(isinstance(moe, dict),
                       "allocations.serving.moe must be an object")
                if isinstance(moe, dict):
                    for field in ("tokens_routed", "dropped_tokens",
                                  "imbalance"):
                        expect(field in moe,
                               "allocations.serving.moe "
                               f"missing {field!r}")
    if isinstance(allocations, dict) and "repartition" in allocations:
        # absent in pre-repartition bundles and when no controller is
        # attached (sampler disabled / standalone node-doctor)
        rep = allocations["repartition"]
        expect(isinstance(rep, dict),
               "allocations.repartition must be an object")
        if isinstance(rep, dict):
            for field in ("enabled", "edges", "throttled_pods",
                          "repartitions_total"):
                expect(field in rep,
                       f"allocations.repartition missing {field!r}")
            expect(isinstance(rep.get("edges", []), list),
                   "allocations.repartition.edges must be a list")
            for i, edge in enumerate(
                rep.get("edges")
                if isinstance(rep.get("edges"), list) else []
            ):
                if not isinstance(edge, dict):
                    problems.append(
                        f"allocations.repartition.edges[{i}] must be an "
                        "object"
                    )
                    continue
                for field in ("donor", "borrower", "chip", "core_units"):
                    expect(field in edge,
                           f"allocations.repartition.edges[{i}] missing "
                           f"{field!r}")
            expect(isinstance(rep.get("throttled_pods", {}), dict),
                   "allocations.repartition.throttled_pods must be an "
                   "object")
    windows = bundle.get("sampler_windows")
    expect(isinstance(windows, dict), "sampler_windows must be an object")
    if isinstance(windows, dict):
        for field in ("chips", "pods"):
            expect(isinstance(windows.get(field), dict),
                   f"sampler_windows.{field} must be an object")
    expect(isinstance(bundle.get("traces"), list), "traces must be a list")
    expect(isinstance(bundle.get("agent"), dict), "agent must be an object")
    if "memory" in bundle:  # absent only in pre-scale-harness bundles
        memory = bundle["memory"]
        expect(isinstance(memory, dict), "memory must be an object")
        if isinstance(memory, dict):
            for field in ("rss_bytes", "trace_ring_bytes"):
                expect(
                    isinstance(memory.get(field), (int, float)),
                    f"memory.{field} must be a number",
                )
    if "reconcile" in bundle:  # absent only in pre-reconciler bundles
        reconcile = bundle["reconcile"]
        expect(isinstance(reconcile, dict), "reconcile must be an object")
        if isinstance(reconcile, dict) and "open_intents" in reconcile:
            intents = reconcile["open_intents"]
            expect(isinstance(intents, list),
                   "reconcile.open_intents must be a list")
            for i, intent in enumerate(
                intents if isinstance(intents, list) else []
            ):
                if not isinstance(intent, dict):
                    problems.append(
                        f"reconcile.open_intents[{i}] must be an object"
                    )
                    continue
                for field in ("pod", "resource", "hash", "age_s"):
                    expect(field in intent,
                           f"reconcile.open_intents[{i}] missing {field!r}")
    if "timeline" in bundle:  # absent only without a checkpoint db
        timeline = bundle["timeline"]
        expect(isinstance(timeline, dict), "timeline must be an object")
        if isinstance(timeline, dict):
            for field in ("events", "total_events", "evicted_total",
                          "agent_version", "boot_id"):
                expect(field in timeline, f"timeline missing {field!r}")
            events = timeline.get("events")
            expect(isinstance(events, list), "timeline.events must be a "
                                             "list")
            prev_seq = None
            for i, event in enumerate(
                events if isinstance(events, list) else []
            ):
                if not isinstance(event, dict):
                    problems.append(f"timeline.events[{i}] must be an "
                                    "object")
                    continue
                for field in ("seq", "ts", "kind", "keys", "attrs"):
                    expect(field in event,
                           f"timeline.events[{i}] missing {field!r}")
                seq = event.get("seq")
                if isinstance(seq, int):
                    expect(
                        prev_seq is None or seq > prev_seq,
                        f"timeline.events[{i}] seq {seq} not "
                        "monotonically increasing",
                    )
                    prev_seq = seq
            for field in ("total_events", "evicted_total"):
                expect(isinstance(timeline.get(field), int),
                       f"timeline.{field} must be an int")
    if "goodput" in bundle:  # absent only without a checkpoint db
        from .goodput import validate_goodput_block

        problems.extend(validate_goodput_block(bundle["goodput"]))
    if "latency" in bundle:  # absent in pre-observatory bundles
        latency = bundle["latency"]
        expect(isinstance(latency, dict), "latency must be an object")
        # A 503 from a just-started agent is captured verbatim as
        # {"error": ...} — a valid (if empty-handed) block.
        if isinstance(latency, dict) and "bind" in latency:
            bind = latency["bind"]
            expect(isinstance(bind, dict), "latency.bind must be an object")
            if isinstance(bind, dict):
                for field in ("observed_total", "phases", "slowest"):
                    expect(field in bind, f"latency.bind missing {field!r}")
                phases = bind.get("phases")
                expect(isinstance(phases, dict),
                       "latency.bind.phases must be an object")
                for pname, ph in (
                    phases.items() if isinstance(phases, dict) else []
                ):
                    if not isinstance(ph, dict):
                        problems.append(
                            f"latency.bind.phases[{pname!r}] must be an "
                            "object"
                        )
                        continue
                    for field in ("count", "p50_ms", "p99_ms"):
                        expect(field in ph,
                               f"latency.bind.phases[{pname!r}] missing "
                               f"{field!r}")
    if "requests" in bundle:  # absent in pre-request-observatory bundles
        requests = bundle["requests"]
        expect(isinstance(requests, dict), "requests must be an object")
        # A 503 from a just-started agent is captured verbatim as
        # {"error": ...} — a valid (if empty-handed) block.
        if isinstance(requests, dict) and "classes" in requests:
            for field in ("live", "finished", "classes", "phases",
                          "conservation", "steps"):
                expect(field in requests,
                       f"requests missing {field!r}")
            classes = requests.get("classes")
            expect(isinstance(classes, dict),
                   "requests.classes must be an object")
            for cname, cls in (
                classes.items() if isinstance(classes, dict) else []
            ):
                if not isinstance(cls, dict):
                    problems.append(
                        f"requests.classes[{cname!r}] must be an object"
                    )
                    continue
                for field in ("finished", "attained", "attainment"):
                    expect(field in cls,
                           f"requests.classes[{cname!r}] missing "
                           f"{field!r}")
            conservation = requests.get("conservation")
            if isinstance(conservation, dict):
                for field in ("checked", "worst_residual_ms"):
                    expect(field in conservation,
                           f"requests.conservation missing {field!r}")
            else:
                problems.append(
                    "requests.conservation must be an object"
                )
    if "profile" in bundle:  # absent in pre-profiler bundles
        profile = bundle["profile"]
        expect(isinstance(profile, dict), "profile must be an object")
        if isinstance(profile, dict) and "top" in profile:
            for field in ("enabled", "hz", "samples_total",
                          "overhead_ratio"):
                expect(field in profile, f"profile missing {field!r}")
            expect(isinstance(profile.get("top"), list),
                   "profile.top must be a list")
    if "subsystems" in bundle:  # absent only in pre-supervision bundles
        subsystems = bundle["subsystems"]
        expect(isinstance(subsystems, dict), "subsystems must be an object")
        for name, sub in (
            subsystems.items() if isinstance(subsystems, dict) else []
        ):
            if not isinstance(sub, dict):
                problems.append(f"subsystems[{name!r}] must be an object")
                continue
            for field in ("criticality", "state", "restarts"):
                expect(field in sub, f"subsystems[{name!r}] missing {field!r}")
    return problems
