"""Migration coordinator: close the checkpoint handshake.

Until now the agent *signalled* checkpoint-restore everywhere —
``ELASTIC_TPU_DRAIN``/``_DEADLINE`` restamped into alloc specs on a
drain, ``TPUSliceReformed`` + an epoch bump on reform, throttle/evict
deadlines on QoS escalation — and the workload side had an orbax
``TrainCheckpointer``, but the two halves never shook hands: drain.py
waited for residents to *exit* and reclaimed blind at the deadline, and
no component ever verified that a workload checkpointed before losing
its chips or resumed at the new world size after. Funky's cloud-native
FPGA orchestration (PAPERS.md) makes the cordon→checkpoint→migrate→
reclaim sequence a runtime-owned lifecycle; Arax argues the mapping
layer — this agent — should own placement *and recovery* end to end.

This module is the agent's half of that handshake (the pod's half is
``workloads/lifecycle.py``). The coordinator consumes the atomic ack
files workloads write (``<alloc dir>/ack/<TPU hash>.json``: checkpoint
step, directory digest, wall time) to:

- **complete drains early** — a DRAINING resident whose ack is durable
  is reclaimed the moment its checkpoint lands instead of at the
  deadline, freeing chips minutes sooner; un-acked residents still get
  the full deadline (nothing about their safety changed);
- **gate QoS eviction** — a throttled pod that answers the throttle
  signal with a durable checkpoint is evicted with its work preserved
  (the repartition controller consults :meth:`acked_since` and calls
  :meth:`publish_record` before its reclaim);
- **publish a MigrationRecord** (pod, checkpoint location, step, digest,
  last topology env, trace id) through the CRD sink so the replacement
  pod — wherever the external scheduler lands it — restores from the
  record at admission;
- **verify the resume** on the destination: the agent that binds the
  replacement restamps ``ELASTIC_TPU_RESTORE_DIR``/``_RESTORE_STEP``
  into its specs, waits for the workload's ``kind="resume"`` ack, checks
  step ≥ acked step AND world size == the pod's CURRENT stamped slice
  world, then emits ``TPUMigrationCompleted`` and a timeline
  ``migration`` event keyed to the same trace id as the source bind.

Crash consistency follows the drain orchestrator's discipline: records,
the replay-suppression set and inbound verification state are journaled
in the Storage ``agent_state`` table BEFORE side effects (failpoints
``migration.pre_ack`` / ``migration.post_record`` name the crash
windows), :meth:`resume` re-arms everything before the boot reconcile,
and every step is idempotent — a record is re-published until confirmed
at the apiserver, a restamp is re-asserted until the spec carries it.

Supervised DEGRADED: losing the coordinator must not take binding down;
drains then simply run to their deadline as before this module existed.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict, List, Optional, Tuple

from . import faults
from .common import (
    SYSTEM_CLOCK,
    AckSubdir,
    EnvCutover,
    EnvRestoreDir,
    EnvRestoreStep,
    EnvRestoreTrace,
    EnvSliceEpoch,
    EnvSliceName,
)
from .types import PodContainer

logger = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 2.0
# Pre-copy round cap: a workload whose delta never converges (every
# step dirties everything) must still cut over well before the drain
# deadline — the cap bounds wasted streaming, the deadline margin
# below bounds wall time.
DEFAULT_PRECOPY_MAX_ROUNDS = 16
# Fraction of the drain budget reserved for the cutover itself (pause
# + final delta + reclaim): when now crosses deadline - margin the
# coordinator stops waiting for convergence and cuts over.
DEFAULT_PRECOPY_CUTOVER_MARGIN_FRAC = 0.25
# A round whose delta shrank by less than this vs the previous round
# means pre-copy has converged — further rounds just re-ship the same
# working set, so cut over now while the delta is small.
PRECOPY_CONVERGED_RATIO = 0.9
# How long a locally-bound pod's "is there a record for me?" apiserver
# miss stays cached: a record published AFTER the replacement bound is
# still found, without per-tick GETs for every ordinary pod.
DEFAULT_RECORD_RECHECK_S = 15.0
# Consumed acks kept for outcome classification / the age gauge after
# their files are reclaimed with the spec; pruned oldest-first past this.
MAX_RETAINED_ACKS = 1024

_STATE_KEY = "migration"

# Topology env keys a MigrationRecord snapshots from the source spec —
# what the destination (and an operator reading the record) needs to
# judge "did it come back at a sane world".
_TOPOLOGY_KEYS = (
    "TPU_WORKER_ID",
    "TPU_WORKER_HOSTNAMES",
    EnvSliceName,
    EnvSliceEpoch,
)


def migration_object_name(namespace: str, name: str) -> str:
    """Deterministic CRD object name for one workload identity — the
    SAME function on source and destination is the rendezvous. The crc
    of the UNAMBIGUOUS "ns/name" key is always appended: ns and name
    may themselves contain '-', so the readable prefix alone would
    collide ("team-a"/"x" vs "team"/"a-x"); the prefix is also
    truncated under the apiserver's 253-char name cap."""
    import zlib

    key = f"{namespace}/{name}"
    crc = zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF
    return f"{f'mig-{namespace}-{name}'[:240]}-{crc:08x}"


class MigrationCoordinator:
    """Per-node migration handshake driver (one instance per agent);
    both the SOURCE role (consume acks, publish records, reclaim early)
    and the DESTINATION role (restamp restore env, verify resume) run
    on the same supervised tick."""

    def __init__(
        self,
        storage,
        plugin,
        sitter,
        reconciler,
        drain=None,
        kube_client=None,
        crd_recorder=None,
        events=None,
        metrics=None,
        node_name: str = "",
        alloc_spec_dir: str = "",
        period_s: float = DEFAULT_PERIOD_S,
        record_recheck_s: float = DEFAULT_RECORD_RECHECK_S,
        precopy_max_rounds: int = DEFAULT_PRECOPY_MAX_ROUNDS,
        precopy_cutover_margin_frac: float = (
            DEFAULT_PRECOPY_CUTOVER_MARGIN_FRAC
        ),
        rng=None,
        timeline=None,
        clock=None,
        lag_tracker=None,
        bus=None,
        event_safety_net_factor: float = 1.0,
    ) -> None:
        self._storage = storage
        self._plugin = plugin
        self._sitter = sitter
        self._reconciler = reconciler
        self._drain = drain
        self._client = kube_client
        self._crd_recorder = crd_recorder
        self._crd = None
        if kube_client is not None:
            from .crd import ElasticTPUClient

            self._crd = ElasticTPUClient(kube_client)
        self._events = events
        self._metrics = metrics
        self._node = node_name
        self._alloc_dir = alloc_spec_dir
        self.period_s = period_s
        self.record_recheck_s = record_recheck_s
        self.precopy_max_rounds = max(1, int(precopy_max_rounds))
        self.precopy_cutover_margin_frac = max(
            0.0, min(0.9, float(precopy_cutover_margin_frac))
        )
        self._rng = rng if rng is not None else random.Random()
        self._timeline = timeline
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        # DetectionLagTracker (latency.py): a NEW checkpoint ack's file
        # "ts" is its origin; consuming it is detection+repair in one.
        self._lag = lag_tracker
        self._lock = threading.Lock()
        # pod_key -> MigrationRecord dict (source role), journaled.
        self._records: Dict[str, dict] = {}
        # pod_key -> uid: early-reclaimed pods whose kubelet assignments
        # must NOT be replayed back until the pod is really gone.
        self._migrated: Dict[str, str] = {}
        # pod_key -> newest consumed ack ts (retained past file reclaim
        # so drain outcome classification survives the early reclaim).
        self._acked: Dict[str, float] = {}
        # pod_key -> latest consumed ack payload (for the status block).
        self._last_acks: Dict[str, dict] = {}
        # pod_key -> inbound verification state (destination role),
        # journaled: {"record", "stage": restamped|verified,
        # "restamp_ts"}.
        self._inbound: Dict[str, dict] = {}
        # pod_key -> pre-copy round journal (source role), journaled:
        # {"rounds": [{round, step, delta_bytes, total_bytes, ts,
        # chain}], "stage": streaming|cutover, "started_ts",
        # "cutover_ts", "cutover_reason"}. A crash mid-pre-copy resumes
        # exactly where the journal left off — a streaming entry keeps
        # consuming round acks, a cutover entry re-stamps the cutover
        # signal until the final checkpoint ack lands.
        self._precopy: Dict[str, dict] = {}
        # Destination-role record discovery is ONE apiserver LIST (all
        # Migrated-phase objects), refreshed at most once per tick and
        # only while an unresolved resident needs a snapshot FRESHER
        # than its own first sighting — per-pod GETs would multiply
        # apiserver traffic by the fleet's pod count. A record always
        # exists BEFORE its replacement pod can be scheduled (publish
        # precedes reclaim precedes eviction precedes re-admission), so
        # one fresh snapshot per pod resolves it; a bounded second look
        # after record_recheck_s covers sink stragglers.
        self._records_snapshot: Dict[tuple, tuple] = {}
        self._records_snapshot_ts: Optional[float] = None
        self._first_seen: Dict[str, float] = {}
        self._resolve_attempts: Dict[str, tuple] = {}  # (attempts, next_ts)
        self._early_reclaims_total = 0
        self._records_published_total = 0
        self._completed_total = 0
        self._precopy_rounds_total = 0
        self._cutovers_total = 0
        self._verify_failures_total = 0
        self._completed: List[dict] = []  # bounded recent completions
        self._last_error: Optional[str] = None
        self._resumed = False
        # Event bus (events.py): pod deltas, bind commits and drain
        # agent_state writes wake a tick early (a drain starting is a
        # STORE_STATE event, so ack consumption begins on the
        # transition, not the next period). The sweep stretches only
        # while the handshake is completely quiet — no records, no
        # consumed acks, no inbound verifications — because checkpoint
        # acks arrive as FILES, which no bus event can carry.
        self._bus = bus
        self.event_safety_net_factor = max(1.0, float(
            event_safety_net_factor
        ))
        self._event_sub = None
        if bus is not None:
            from . import events as bus_events

            self._event_sub = bus.subscribe(
                "migration",
                (bus_events.POD_DELTA, bus_events.STORE_BIND,
                 bus_events.STORE_STATE),
            )
        self.event_ticks_total = 0

    # -- journaled state ------------------------------------------------------

    def _journal_locked(self) -> None:
        self._storage.save_state(_STATE_KEY, {
            "records": {k: dict(v) for k, v in self._records.items()},
            "migrated": dict(self._migrated),
            "acked": dict(self._acked),
            "inbound": {k: dict(v) for k, v in self._inbound.items()},
            "precopy": {k: dict(v) for k, v in self._precopy.items()},
            "early_reclaims_total": self._early_reclaims_total,
            "records_published_total": self._records_published_total,
            "completed_total": self._completed_total,
            "precopy_rounds_total": self._precopy_rounds_total,
            "cutovers_total": self._cutovers_total,
        })

    def resume(self) -> None:
        """Re-arm the journaled handshake state after a restart, BEFORE
        the boot reconcile: replay suppression for early-reclaimed pods
        must be up before restore() walks kubelet's still-listed
        assignments, and half-published records must finish publishing.
        Idempotent."""
        try:
            st = self._storage.load_state(_STATE_KEY)
        except Exception:  # noqa: BLE001 - unreadable journal: start clean
            logger.exception("migration: state journal unreadable; "
                             "starting clean")
            st = None
        if st:
            with self._lock:
                self._records = {
                    k: dict(v) for k, v in (st.get("records") or {}).items()
                }
                self._migrated = dict(st.get("migrated") or {})
                self._acked = {
                    k: float(v) for k, v in (st.get("acked") or {}).items()
                }
                self._inbound = {
                    k: dict(v) for k, v in (st.get("inbound") or {}).items()
                }
                self._precopy = {
                    k: dict(v) for k, v in (st.get("precopy") or {}).items()
                }
                self._early_reclaims_total = int(
                    st.get("early_reclaims_total", 0)
                )
                self._records_published_total = int(
                    st.get("records_published_total", 0)
                )
                self._completed_total = int(st.get("completed_total", 0))
                self._precopy_rounds_total = int(
                    st.get("precopy_rounds_total", 0)
                )
                self._cutovers_total = int(st.get("cutovers_total", 0))
            if self._records or self._migrated or self._inbound:
                logger.warning(
                    "migration: resumed %d record(s), %d suppressed "
                    "pod(s), %d inbound verification(s)",
                    len(self._records), len(self._migrated),
                    len(self._inbound),
                )
        self._resumed = True

    # -- hooks consulted by the reconciler / drain / repartition --------------

    def replay_suppressed(self, pod_key: str) -> bool:
        """True while ``pod_key``'s early-reclaimed bindings must STAY
        reclaimed (kubelet still lists the assignment until eviction)."""
        with self._lock:
            return pod_key in self._migrated

    def acked_since(self, pod_key: str, since_ts: Optional[float]) -> bool:
        """Whether this pod acknowledged a durable checkpoint at/after
        ``since_ts`` (None = any ack ever) — the drain's outcome
        classifier and the repartition controller's eviction gate."""
        with self._lock:
            ts = self._acked.get(pod_key)
        if ts is None:
            return False
        return since_ts is None or ts >= since_ts

    def acked_pods(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._acked)

    # -- ack consumption (source role) ----------------------------------------

    def _residents(self) -> Optional[List[Tuple[str, dict]]]:
        """[(pod_key, {"containers": {container: records}, "hashes":
        [...]})] for every pod this node holds bindings for; None when
        storage cannot answer."""
        out: List[Tuple[str, dict]] = []
        try:
            items = list(self._storage.items())
        except Exception:  # noqa: BLE001 - storage blip: retry next tick
            logger.exception("migration: resident enumeration failed")
            return None
        for _key, info in items:
            hashes = [rec.device.hash for rec in info.records()]
            if not hashes:
                continue
            out.append((info.key, {
                "namespace": info.namespace,
                "name": info.name,
                "containers": {
                    c: dict(r) for c, r in info.allocations.items() if r
                },
                "hashes": hashes,
            }))
        return out

    def _spec_plugin(self):
        return getattr(self._plugin, "core", None)

    def _spec_env(self, hashes: List[str]) -> Dict[str, str]:
        plugin = self._spec_plugin()
        if plugin is None:
            return {}
        for h in hashes:
            spec = plugin.read_alloc_spec(h)
            if spec and isinstance(spec.get("env"), dict):
                return dict(spec["env"])
        return {}

    def _consume_acks(self, residents) -> Dict[str, dict]:
        """Read every resident's ack file; update the retained ack map
        and the per-pod checkpoint-age gauge. Returns pod_key -> ack."""
        from .workloads.lifecycle import read_checkpoint_ack

        now = self._clock.time()
        acks: Dict[str, dict] = {}
        for pod_key, res in residents:
            ack = None
            for h in res["hashes"]:
                ack = read_checkpoint_ack(self._alloc_dir, h)
                if ack is not None:
                    break
            if ack is None:
                continue
            try:
                ts = float(ack.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if ts > now + 60.0:
                # future-stamped acks rejected, like usage reports: a
                # skewed clock must not pin "just checkpointed" forever.
                continue
            acks[pod_key] = ack
            with self._lock:
                if ack.get("kind") == "precopy":
                    # A pre-copy ROUND is streaming progress, not a
                    # restorable cutover point: it must never feed
                    # _acked, or the early-reclaim pass and the drain's
                    # outcome classifier would treat a still-training
                    # workload as checkpoint-complete.
                    self._last_acks[pod_key] = ack
                    fresh = False
                else:
                    fresh = ts > self._acked.get(pod_key, 0.0)
                    self._acked[pod_key] = max(
                        ts, self._acked.get(pod_key, 0.0)
                    )
                    self._last_acks[pod_key] = ack
                while len(self._acked) > MAX_RETAINED_ACKS:
                    oldest = min(self._acked, key=self._acked.get)
                    self._acked.pop(oldest, None)
                    self._last_acks.pop(oldest, None)
            if self._lag is not None and fresh:
                # Only a strictly newer ack ts is a new event; the same
                # file re-read next tick records nothing.
                self._lag.handled(
                    "migration", "checkpoint_ack", key=pod_key,
                    origin_ts=ts,
                )
            m = self._metrics
            if m is not None and hasattr(m, "workload_checkpoint_age"):
                try:
                    m.workload_checkpoint_age.set(
                        max(0.0, now - ts), pod=pod_key
                    )
                except Exception:  # noqa: BLE001 - observability only
                    pass
        return acks

    # -- MigrationRecord construction / publication ---------------------------

    def _build_record(
        self, pod_key: str, res: dict, ack: dict, reason: str
    ) -> dict:
        env = self._spec_env(res["hashes"])
        pod = self._sitter.get_pod(res["namespace"], res["name"])
        uid = str(((pod or {}).get("metadata") or {}).get("uid", ""))
        record = {
            "name": migration_object_name(res["namespace"], res["name"]),
            "pod": pod_key,
            "uid": uid,
            "source_node": self._node,
            "reason": reason,
            "step": ack.get("step"),
            "checkpoint_dir": ack.get("checkpoint_dir", ""),
            "digest": ack.get("digest", ""),
            "ack_kind": ack.get("kind", "checkpoint"),
            "ack_ts": ack.get("ts"),
            "trace": env.get("ELASTIC_TPU_TRACE_ID", ""),
            "topology_env": {
                k: env[k] for k in _TOPOLOGY_KEYS if k in env
            },
            "recorded_ts": self._clock.time(),
            "published": False,
            "reclaimed": False,
        }
        with self._lock:
            pc = self._precopy.get(pod_key)
        if pc is not None:
            # The cutover ack closed a pre-copy stream: the record
            # carries the chain contract (digest = the delta chain the
            # destination must reassemble and verify) plus the round
            # stats the bench and the goodput ledger price with.
            record["mode"] = "precopy"
            record["precopy"] = {
                "rounds": len(pc.get("rounds") or []),
                "started_ts": pc.get("started_ts"),
                "cutover_ts": pc.get("cutover_ts"),
                "cutover_reason": pc.get("cutover_reason"),
                "final_delta_bytes": ack.get("delta_bytes"),
                "full_bytes": ack.get("full_bytes")
                or ack.get("total_bytes"),
                "cutover_ms": ack.get("cutover_ms"),
            }
        return record

    def _record_manifest(self, record: dict):
        from .crd import ElasticTPU, PhaseMigrated

        ns, _, name = record["pod"].partition("/")
        return ElasticTPU(
            name=record["name"],
            # node_name stays EMPTY on purpose: the CRD recorder's
            # restore-time reconcile sweeps objects labeled with this
            # node that aren't live allocations — a migration record
            # must survive exactly that sweep (its whole point is to
            # outlive the source's bindings). The source node rides in
            # the migration payload instead.
            node_name="",
            claim_namespace=ns,
            claim_name=name,
            phase=PhaseMigrated,
            message=(
                f"checkpoint step {record['step']} at "
                f"{record['checkpoint_dir'] or '<unset>'} "
                f"(from {record['source_node']}, {record['reason']})"
            ),
            migration={
                k: record[k] for k in (
                    "pod", "uid", "source_node", "reason", "step",
                    "checkpoint_dir", "digest", "ack_kind", "ack_ts",
                    "trace", "topology_env", "recorded_ts", "mode",
                    "precopy",
                ) if k in record
            },
        )

    def _publish_pending(self) -> None:
        """Publish every journaled record not yet CONFIRMED at the
        apiserver — re-submitted each tick until a read-back sees it, so
        a sink drop or a crash between journal and publish can never
        lose the record (the journal is the durable copy)."""
        if self._crd is None:
            return
        with self._lock:
            pending = [
                dict(r) for r in self._records.values()
                if not r.get("published")
            ]
        for record in pending:
            try:
                existing = self._crd.get(record["name"])
            except Exception:  # noqa: BLE001 - apiserver blip: next tick
                continue
            if existing is not None and (
                (existing.migration or {}).get("ack_ts") == record["ack_ts"]
            ):
                confirmed = True
            else:
                obj = self._record_manifest(record)
                if self._crd_recorder is not None and hasattr(
                    self._crd_recorder, "record_migration"
                ):
                    # the async CRD sink (coalesced, keyed per object);
                    # confirmation happens by read-back next tick
                    self._crd_recorder.record_migration(obj)
                    confirmed = False
                else:
                    try:
                        self._crd.create(obj, update_existing=True)
                        confirmed = True
                    except Exception:  # noqa: BLE001 - retried next tick
                        logger.warning(
                            "migration: record publish for %s failed "
                            "(retried)", record["pod"],
                        )
                        continue
            if confirmed:
                with self._lock:
                    rec = self._records.get(record["pod"])
                    if rec is not None and not rec.get("published"):
                        rec["published"] = True
                        self._records_published_total += 1
                        self._journal_locked()
                m = self._metrics
                if m is not None and hasattr(m, "migration_records"):
                    try:
                        m.migration_records.inc()
                    except Exception:  # noqa: BLE001
                        pass
                if self._timeline is not None:
                    from .timeline import KIND_MIGRATION

                    self._timeline.emit(
                        KIND_MIGRATION,
                        keys={"pod": record["pod"],
                              "trace": record["trace"] or None},
                        action="record_published",
                        step=record["step"],
                        checkpoint_dir=record["checkpoint_dir"],
                        reason=record["reason"],
                    )

    def publish_record(
        self, pod_key: str, uid: str = "", reason: str = "qos_evict"
    ) -> bool:
        """Journal + queue a MigrationRecord for ``pod_key`` from its
        newest consumed ack, WITHOUT reclaiming (the caller owns the
        teardown — the repartition controller's eviction gate). Returns
        True when a record exists afterwards. Never raises."""
        try:
            with self._lock:
                if pod_key in self._records:
                    return True
                ack = self._last_acks.get(pod_key)
            if ack is None:
                return False
            residents = self._residents() or []
            res = dict(residents).get(pod_key)
            if res is None:
                return False
            record = self._build_record(pod_key, res, ack, reason)
            if uid and not record["uid"]:
                record["uid"] = uid
            with self._lock:
                self._records[pod_key] = record
                self._journal_locked()
            self._emit_recorded(record)
            self._publish_pending()
            return True
        except Exception:  # noqa: BLE001 - a gate must never break eviction
            logger.exception("migration: publish_record(%s) failed", pod_key)
            return False

    def _emit_recorded(self, record: dict) -> None:
        if self._timeline is not None:
            from .timeline import KIND_MIGRATION

            self._timeline.emit(
                KIND_MIGRATION,
                keys={"pod": record["pod"],
                      "trace": record["trace"] or None},
                action="recorded",
                step=record["step"],
                checkpoint_dir=record["checkpoint_dir"],
                digest=record["digest"],
                reason=record["reason"],
                mode=record.get("mode", "full"),
                cutover_ts=(record.get("precopy") or {}).get("cutover_ts"),
            )
        if self._events is not None:
            from .kube.events import ReasonMigrationRecorded

            ns, _, name = record["pod"].partition("/")
            try:
                self._events.pod_event(
                    ns, name, ReasonMigrationRecorded,
                    f"checkpoint verified durable at step "
                    f"{record['step']} ({record['reason']}); migration "
                    "record published for the replacement pod",
                    trace_id=record["trace"],
                )
            except Exception:  # noqa: BLE001 - observability only
                pass

    # -- pipelined pre-copy (source role) --------------------------------------

    def _cutover_reason(self, pc: dict, now: float) -> Optional[str]:
        """Why this pre-copy stream should cut over NOW, or None to
        keep streaming: the round cap, delta convergence (the delta
        stopped shrinking — more rounds just re-ship the working set),
        or deadline pressure (the reserved cutover margin of the drain
        budget has arrived; Funky's pre-copy semantics — bounded
        rounds, guaranteed cutover before the host goes away)."""
        rounds = pc.get("rounds") or []
        if len(rounds) >= self.precopy_max_rounds:
            return "rounds"
        if len(rounds) >= 3:
            # round 0 ships the full baseline; convergence is judged on
            # delta-vs-delta only, so at least two true delta rounds.
            try:
                last = float(rounds[-1].get("delta_bytes") or 0.0)
                prev = float(rounds[-2].get("delta_bytes") or 0.0)
            except (TypeError, ValueError):
                last = prev = 0.0
            if prev > 0.0 and last >= PRECOPY_CONVERGED_RATIO * prev:
                return "converged"
        drain = self._drain
        deadline_ts = getattr(drain, "deadline_ts", None)
        if deadline_ts:
            started = drain.started_ts()
            budget = max(0.0, deadline_ts - (
                started if started is not None else now
            ))
            margin = self.precopy_cutover_margin_frac * budget
            if now >= deadline_ts - margin:
                return "deadline"
        return None

    def _stamp_cutover(self, pod_key: str, res: dict, pc: dict) -> bool:
        """Restamp ``ELASTIC_TPU_CUTOVER`` into the pod's alloc specs —
        the signal that ends streaming: pause, final delta, ack. The
        token encodes reason+round so a fresh drain (new pre-copy
        stream) produces a NEW edge on the workload side. Re-asserted
        every tick until the final ack lands, like every other stamp."""
        from .plugins import restamp_owner_env

        plugin = self._spec_plugin()
        if plugin is None:
            return False
        token = (
            f"{pc.get('cutover_reason', 'cutover')}:"
            f"{len(pc.get('rounds') or [])}:"
            f"{pc.get('cutover_ts') or 0:.3f}"
        )
        ok = False
        for container, records in res["containers"].items():
            owner = PodContainer(res["namespace"], res["name"], container)
            try:
                if restamp_owner_env(
                    plugin, owner, records, {EnvCutover: token}
                ):
                    ok = True
            except Exception:  # noqa: BLE001 - retried next tick
                logger.exception(
                    "migration: cutover stamp for %s failed", pod_key
                )
        return ok

    def _precopy_pass(self, residents, acks: Dict[str, dict]) -> None:
        """Drive pipelined pre-copy while the node is DRAINING: journal
        every round ack a workload streams (training CONTINUES under
        it), decide cutover (convergence / round cap / deadline
        margin), then stamp the cutover signal until the final
        checkpoint ack arrives and the normal early-reclaim pass takes
        over. A workload that never acks pre-copy is simply never in
        this map — the full-checkpoint handshake runs unchanged."""
        from .drain import DRAINING

        drain = self._drain
        if drain is None or drain.state != DRAINING:
            with self._lock:
                if self._precopy:
                    # a cancelled/finished drain invalidates in-flight
                    # streams; the next drain starts a fresh chain
                    self._precopy.clear()
                    self._journal_locked()
            return
        started = drain.started_ts()
        now = self._clock.time()
        by_key = dict(residents)
        for pod_key, ack in acks.items():
            if ack.get("kind") != "precopy":
                continue
            res = by_key.get(pod_key)
            if res is None:
                continue
            try:
                ts = float(ack.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if started is not None and ts < started:
                continue  # a stale stream from a previous drain
            try:
                round_ = int(ack.get("round", 0))
            except (TypeError, ValueError):
                round_ = 0
            with self._lock:
                pc = self._precopy.get(pod_key)
                pc = dict(pc) if pc is not None else {
                    "rounds": [],
                    "stage": "streaming",
                    "started_ts": ts,
                    "trigger": drain.trigger,
                    "cutover_ts": None,
                    "cutover_reason": None,
                }
            if pc["stage"] != "cutover" and round_ not in {
                r.get("round") for r in pc["rounds"]
            }:
                faults.fire("migration.pre_copy_round")
                pc["rounds"] = (pc["rounds"] + [{
                    "round": round_,
                    "step": ack.get("step"),
                    "delta_bytes": ack.get("delta_bytes"),
                    "total_bytes": ack.get("total_bytes"),
                    "chain": ack.get("digest", ""),
                    "ts": ts,
                }])[-64:]
                with self._lock:
                    self._precopy[pod_key] = pc
                    self._precopy_rounds_total += 1
                    self._journal_locked()  # round durable BEFORE effects
                faults.fire("migration.pre_copy_journal")
                if self._timeline is not None:
                    from .timeline import KIND_MIGRATION

                    self._timeline.emit(
                        KIND_MIGRATION,
                        keys={"pod": pod_key},
                        action="precopy_round",
                        round=round_,
                        step=ack.get("step"),
                        delta_bytes=ack.get("delta_bytes"),
                        total_bytes=ack.get("total_bytes"),
                    )
                logger.warning(
                    "migration: %s pre-copy round %d durable (step %s, "
                    "%s delta bytes); training continues",
                    pod_key, round_, ack.get("step"),
                    ack.get("delta_bytes"),
                )
            if pc["stage"] != "cutover":
                reason = self._cutover_reason(pc, now)
                if reason is not None:
                    pc["stage"] = "cutover"
                    pc["cutover_ts"] = now
                    pc["cutover_reason"] = reason
                    with self._lock:
                        self._precopy[pod_key] = pc
                        self._cutovers_total += 1
                        self._journal_locked()  # BEFORE the stamp effect
                    faults.fire("migration.pre_copy_cutover")
                    if self._timeline is not None:
                        from .timeline import KIND_MIGRATION

                        self._timeline.emit(
                            KIND_MIGRATION,
                            keys={"pod": pod_key},
                            action="cutover_signaled",
                            reason=reason,
                            rounds=len(pc["rounds"]),
                            deadline_ts=drain.deadline_ts,
                        )
                    logger.warning(
                        "migration: %s pre-copy cutover (%s) after %d "
                        "round(s); pause + final delta requested",
                        pod_key, reason, len(pc["rounds"]),
                    )
        # Re-assert the cutover stamp for every stream already in the
        # cutover stage — idempotent, survives drift rebinds AND the
        # crash window between the cutover journal and the first stamp.
        with self._lock:
            cutting = [
                k for k, v in self._precopy.items()
                if v.get("stage") == "cutover"
            ]
        for pod_key in cutting:
            res = by_key.get(pod_key)
            if res is None:
                continue
            with self._lock:
                pc = self._precopy.get(pod_key)
            if pc is not None:
                self._stamp_cutover(pod_key, res, pc)

    # -- early drain completion (source role) ---------------------------------

    def _drain_early_pass(self, residents, acks: Dict[str, dict]) -> None:
        """While the node is DRAINING, reclaim every resident whose ack
        is durable AND fresh (at/after the drain's cordon anchor) — the
        handshake's headline: chips free the moment the checkpoint
        lands, not at the deadline. Un-acked residents are untouched."""
        from .drain import DRAINING

        drain = self._drain
        if drain is None or drain.state != DRAINING:
            return
        started = drain.started_ts()
        trigger = drain.trigger
        by_key = dict(residents)
        for pod_key, ack in acks.items():
            if ack.get("kind") == "precopy":
                continue  # still streaming: reclaim only on the final ack
            res = by_key.get(pod_key)
            if res is None:
                continue
            try:
                ts = float(ack.get("ts", 0.0))
            except (TypeError, ValueError):
                continue
            if started is not None and ts < started:
                continue  # a stale pre-drain ack saves nothing here
            with self._lock:
                prior = self._records.get(pod_key)
                if (
                    pod_key in self._migrated
                    and prior is not None and prior.get("reclaimed")
                ):
                    continue  # fully handled
            if prior is not None and pod_key in self._migrated:
                # a crash landed between the record journal and the
                # reclaim: the journaled record stands, finish the
                # teardown (reclaim_pods is idempotent)
                record = prior
            else:
                record = self._build_record(
                    pod_key, res, ack, f"drain:{trigger.split(':', 1)[0]}"
                )
                with self._lock:
                    self._records[pod_key] = record
                    self._migrated[pod_key] = record["uid"]
                    # the record absorbed the pre-copy stats; the live
                    # stream entry's job is done
                    self._precopy.pop(pod_key, None)
                    self._early_reclaims_total += 1
                    self._journal_locked()  # BEFORE the reclaim side effect
                faults.fire("migration.post_record")
                self._emit_recorded(record)
            report = self._reconciler.reclaim_pods([pod_key])
            with self._lock:
                rec = self._records.get(pod_key)
                if rec is not None:
                    rec["reclaimed"] = True
                    self._journal_locked()
            m = self._metrics
            if m is not None and hasattr(m, "drain_early_reclaims"):
                try:
                    m.drain_early_reclaims.inc()
                except Exception:  # noqa: BLE001
                    pass
            if self._timeline is not None:
                from .timeline import KIND_MIGRATION

                self._timeline.emit(
                    KIND_MIGRATION,
                    keys={"pod": pod_key,
                          "trace": record["trace"] or None},
                    action="early_reclaim",
                    step=record["step"],
                    deadline_ts=drain.deadline_ts,
                )
            logger.warning(
                "migration: %s acked step %s; bindings reclaimed %s "
                "early of the drain deadline (%s)",
                pod_key, record["step"],
                (f"{drain.deadline_ts - self._clock.time():.0f}s"
                 if drain.deadline_ts else "ahead"),
                report.get("reclaimed_pods"),
            )
        self._publish_pending()

    # -- destination role: restamp + verify -----------------------------------

    def _refresh_records_snapshot(self) -> bool:
        """One LIST of every Migrated-phase object -> {(ns, name):
        (object name, payload)}. Returns False when the apiserver could
        not answer (the stale snapshot stands)."""
        from .crd import PhaseMigrated

        if self._crd is None:
            return False
        try:
            # labelSelector-scoped: records only, never the fleet's
            # whole per-allocation collection
            objs = self._crd.list_migrations()
        except Exception:  # noqa: BLE001 - apiserver blip: stale stands
            return False
        snap: Dict[tuple, tuple] = {}
        for obj in objs:
            if obj.phase == PhaseMigrated and obj.migration:
                snap[(obj.claim_namespace, obj.claim_name)] = (
                    obj.name, dict(obj.migration)
                )
        self._records_snapshot = snap
        self._records_snapshot_ts = self._clock.monotonic()
        return True

    def _inbound_pass(self, residents) -> None:
        """For every locally-bound pod: adopt a published record
        (restamp restore env), then verify the workload's resume ack."""
        now_mono = self._clock.monotonic()
        # Which pods still need a record lookup, and how FRESH a
        # snapshot each attempt needs: the first look needs one newer
        # than the pod's first sighting (a record always predates its
        # replacement's bind); the delayed second look — the
        # sink-straggler net — needs one STRICTLY newer than the
        # snapshot its first look consumed, or it would just re-read
        # the stale snapshot that missed.
        pending: List[Tuple[str, dict, float]] = []
        for pod_key, res in residents:
            with self._lock:
                if pod_key in self._inbound or pod_key in self._records:
                    continue
            first = self._first_seen.setdefault(pod_key, now_mono)
            attempts, next_ts, used_snap = self._resolve_attempts.get(
                pod_key, (0, first, None)
            )
            if attempts >= 2 or now_mono < next_ts:
                continue
            pending.append((
                pod_key, res, first if used_snap is None else used_snap,
            ))
        if pending and (
            self._records_snapshot_ts is None
            or self._records_snapshot_ts
            <= max(need for _, _, need in pending)
        ):
            self._refresh_records_snapshot()
        for pod_key, res, need_after in pending:
            if (
                self._records_snapshot_ts is None
                or self._records_snapshot_ts <= need_after
            ):
                continue  # no fresh-enough snapshot yet; retry next tick
            attempts, _, _ = self._resolve_attempts.get(
                pod_key, (0, 0.0, None)
            )
            self._resolve_attempts[pod_key] = (
                attempts + 1, now_mono + self.record_recheck_s,
                self._records_snapshot_ts,
            )
            entry = self._records_snapshot.get(
                (res["namespace"], res["name"])
            )
            if entry is None:
                continue  # no record; one delayed recheck then final
            _, record = entry
            inbound = {
                "record": record,
                "stage": "restamped",
                "restamp_ts": self._clock.time(),
            }
            if not self._restamp_restore(pod_key, res, record):
                # retried next tick (nothing journaled yet)
                self._resolve_attempts.pop(pod_key, None)
                continue
            with self._lock:
                self._inbound[pod_key] = inbound
                self._journal_locked()
            if self._timeline is not None:
                from .timeline import KIND_MIGRATION

                self._timeline.emit(
                    KIND_MIGRATION,
                    keys={"pod": pod_key,
                          "trace": record.get("trace") or None},
                    action="restore_stamped",
                    step=record.get("step"),
                    source_node=record.get("source_node"),
                    mode=record.get("mode", "full"),
                )
            logger.warning(
                "migration: %s has a published record (step %s from "
                "%s); restore env stamped", pod_key,
                record.get("step"), record.get("source_node"),
            )
        for pod_key, res in residents:
            with self._lock:
                inbound = self._inbound.get(pod_key)
            if inbound is not None and inbound.get("stage") == "restamped":
                # re-assert the stamp (a drift rebind may have rebuilt
                # the spec without it), then look for the resume ack
                self._restamp_restore(pod_key, res, inbound["record"])
                self._verify_resume(pod_key, res, inbound)

    def _restamp_restore(self, pod_key, res, record) -> bool:
        from .plugins import restamp_owner_env

        plugin = self._spec_plugin()
        if plugin is None:
            return False
        env = {
            EnvRestoreDir: str(record.get("checkpoint_dir", "")),
            EnvRestoreStep: str(record.get("step", "")),
        }
        if record.get("trace"):
            env[EnvRestoreTrace] = str(record["trace"])
        ok = False
        for container, records in res["containers"].items():
            owner = PodContainer(res["namespace"], res["name"], container)
            try:
                if restamp_owner_env(plugin, owner, records, env):
                    ok = True
            except Exception:  # noqa: BLE001 - retried next tick
                logger.exception(
                    "migration: restore restamp for %s failed", pod_key
                )
        return ok

    def _verify_resume(self, pod_key: str, res: dict, inbound: dict) -> None:
        from .workloads.lifecycle import read_checkpoint_ack, world_size_of

        record = inbound["record"]
        ack = None
        for h in res["hashes"]:
            ack = read_checkpoint_ack(self._alloc_dir, h)
            if ack is not None:
                break
        if ack is None or ack.get("kind") != "resume":
            return
        problems = []
        acked_step = record.get("step")
        try:
            resumed_step = int(ack.get("step"))
        except (TypeError, ValueError):
            resumed_step = None
        if acked_step is not None and (
            resumed_step is None or resumed_step < int(acked_step)
        ):
            problems.append(
                f"resumed at step {resumed_step} < acked step {acked_step}"
            )
        expected_world = world_size_of(self._spec_env(res["hashes"]))
        got_world = ack.get("world_size")
        if got_world is not None and int(got_world) != expected_world:
            problems.append(
                f"resumed at world size {got_world}, current slice "
                f"world is {expected_world}"
            )
        if record.get("mode") == "precopy":
            # A pre-copy record's digest IS the delta chain contract:
            # before the record may be deleted, the destination proves
            # it reassembled exactly the blocks the source shipped —
            # every manifest block present, every block's content
            # digest intact, and the chain over them equal to what the
            # source acked at cutover. A torn final delta fails here
            # and the record (the durable copy) stays for the retry.
            want_chain = str(record.get("digest") or "")
            try:
                from .workloads.checkpointing import DeltaCheckpointer

                report = DeltaCheckpointer(
                    str(record.get("checkpoint_dir") or "")
                ).verify()
            except Exception as e:  # noqa: BLE001 - storage blip
                report = {
                    "ok": False, "chain": "",
                    "problems": [f"chain verify unreadable: {e}"],
                }
            if not report.get("ok"):
                problems.append(
                    "delta chain verification failed: "
                    + "; ".join(report.get("problems") or ["unknown"])
                )
            elif want_chain and report.get("chain") != want_chain:
                problems.append(
                    f"delta chain {report.get('chain')} != recorded "
                    f"{want_chain}"
                )
        if problems:
            # One failing ack is ONE incident: the same unchanged ack is
            # re-read every tick, and without this dedup the failure
            # counter/timeline/log would grow by one per tick for the
            # whole life of the stuck migration.
            failed_id = (ack.get("ts"), resumed_step, got_world)
            with self._lock:
                if inbound.get("last_failed") == list(failed_id):
                    return
                inbound["last_failed"] = list(failed_id)
                self._verify_failures_total += 1
                self._journal_locked()
            if self._timeline is not None:
                from .timeline import KIND_MIGRATION

                self._timeline.emit(
                    KIND_MIGRATION,
                    keys={"pod": pod_key,
                          "trace": record.get("trace") or None},
                    action="verify_failed", problems=problems,
                )
            logger.warning(
                "migration: %s resume verification FAILED: %s",
                pod_key, "; ".join(problems),
            )
            return
        completion = {
            "pod": pod_key,
            "step": resumed_step,
            "world_size": expected_world,
            "source_node": record.get("source_node"),
            "trace": record.get("trace", ""),
            "mode": record.get("mode", "full"),
            "precopy": record.get("precopy"),
            "verified_ts": self._clock.time(),
            "downtime_s": (
                round(self._clock.time() - float(record["ack_ts"]), 3)
                if record.get("ack_ts") else None
            ),
        }
        with self._lock:
            self._inbound.pop(pod_key, None)
            self._completed_total += 1
            self._completed = (self._completed + [completion])[-32:]
            self._journal_locked()
        m = self._metrics
        if m is not None and hasattr(m, "migrations_completed"):
            try:
                m.migrations_completed.inc()
            except Exception:  # noqa: BLE001
                pass
        if self._timeline is not None:
            from .timeline import KIND_MIGRATION

            self._timeline.emit(
                KIND_MIGRATION,
                keys={"pod": pod_key,
                      "trace": record.get("trace") or None},
                action="completed",
                step=resumed_step,
                world_size=expected_world,
                source_node=record.get("source_node"),
                downtime_s=completion["downtime_s"],
                mode=record.get("mode", "full"),
                precopy=record.get("precopy"),
            )
        if self._events is not None:
            from .kube.events import ReasonMigrationCompleted

            try:
                self._events.pod_event(
                    res["namespace"], res["name"],
                    ReasonMigrationCompleted,
                    f"resume verified at step {resumed_step}, world size "
                    f"{expected_world} (migrated from "
                    f"{record.get('source_node', '?')})",
                    trace_id=record.get("trace", ""),
                )
            except Exception:  # noqa: BLE001
                pass
        if self._crd is not None:
            # the record's job is done; a stale record left behind would
            # make the NEXT pod under this identity "restore" old state
            try:
                self._crd.delete(record.get("name") or
                                 migration_object_name(
                                     res["namespace"], res["name"]))
            except Exception:  # noqa: BLE001 - retried never: reclaimed
                logger.warning(
                    "migration: completed record delete for %s failed",
                    pod_key,
                )
        logger.warning(
            "migration: %s resume VERIFIED (step %s, world %s, "
            "downtime %ss)", pod_key, resumed_step, expected_world,
            completion["downtime_s"],
        )

    # -- sweeping -------------------------------------------------------------

    def _pod_gone(self, pod_key: str, armed_uid: str) -> bool:
        ns, _, name = pod_key.partition("/")
        pod = self._sitter.get_pod(ns, name)
        if pod is None:
            if self._client is not None:
                try:
                    pod = self._client.get_pod(ns, name)
                except Exception:  # noqa: BLE001 - unknowable: keep armed
                    return False
            if pod is None:
                return True
        uid = str(((pod or {}).get("metadata") or {}).get("uid", ""))
        return bool(armed_uid) and uid != armed_uid

    def _sweep(self, residents) -> None:
        """Suppression and records drop once their pod generation is
        really gone; retained acks for pods with no bindings and no
        record age out with them (the gauge series is removed so a
        reclaimed pod doesn't report a frozen age forever)."""
        with self._lock:
            migrated = dict(self._migrated)
            # records WITHOUT a suppression entry (the QoS-evict path's
            # publish_record never arms one) must sweep by their own
            # recorded uid, or they leak in the journal forever and —
            # worse — block a same-node re-admission from ADOPTING the
            # record (_inbound_pass skips pods in _records).
            record_only = {
                k: r.get("uid", "") for k, r in self._records.items()
                if k not in migrated
            }
        dropped = False
        for pod_key, uid in migrated.items():
            if self._pod_gone(pod_key, uid):
                with self._lock:
                    self._migrated.pop(pod_key, None)
                    # the record is dropped with the suppression ONLY
                    # once it provably reached the apiserver — an
                    # unpublished record for a gone pod is exactly the
                    # record that still matters (the replacement is
                    # about to go looking for it)
                    rec = self._records.get(pod_key)
                    if rec is not None and rec.get("published"):
                        self._records.pop(pod_key, None)
                    dropped = True
        for pod_key, uid in record_only.items():
            if self._pod_gone(pod_key, uid):
                with self._lock:
                    rec = self._records.get(pod_key)
                    if rec is not None and rec.get("published"):
                        self._records.pop(pod_key, None)
                        dropped = True
        resident_keys = {k for k, _ in residents}
        for k in [
            k for k in self._first_seen if k not in resident_keys
        ]:
            self._first_seen.pop(k, None)
            self._resolve_attempts.pop(k, None)
        with self._lock:
            stale = [
                k for k in self._acked
                if k not in resident_keys and k not in self._migrated
                and k not in self._records
            ]
            for k in stale:
                # keep the ack VALUE (drain outcome classification may
                # still need it this lifecycle) but stop aging it in the
                # gauge once the pod has no bindings here
                self._last_acks.pop(k, None)
            inbound_stale = [
                k for k in self._inbound if k not in resident_keys
            ]
            for k in inbound_stale:
                self._inbound.pop(k, None)
                dropped = True
            for k in [
                k for k in self._precopy if k not in resident_keys
            ]:
                self._precopy.pop(k, None)
                dropped = True
            if dropped:
                self._journal_locked()
        m = self._metrics
        if m is not None and hasattr(m, "workload_checkpoint_age"):
            for k in stale:
                try:
                    m.workload_checkpoint_age.remove(pod=k)
                except Exception:  # noqa: BLE001
                    pass

    # -- the tick -------------------------------------------------------------

    def tick(self) -> None:
        faults.fire("migration.pre_ack")
        residents = self._residents()
        if residents is None:
            return  # storage unanswerable: retry next tick
        acks = self._consume_acks(residents)
        self._precopy_pass(residents, acks)
        self._drain_early_pass(residents, acks)
        self._publish_pending()
        self._inbound_pass(residents)
        self._sweep(residents)

    def run(self, stop: threading.Event) -> None:
        """Supervised loop (DEGRADED): resume journaled state, then tick
        at a jittered period — same discipline as the drain loop."""
        if not self._resumed:
            self.resume()
        consecutive_failures = 0
        sub = self._event_sub
        while True:
            delay = self.period_s * (0.75 + 0.5 * self._rng.random())
            if sub is not None and self._bus.healthy():
                # Stretch only while the handshake is completely quiet:
                # checkpoint acks arrive as files, not events, so any
                # in-flight work keeps the base cadence.
                with self._lock:
                    quiet = (not self._records and not self._acked
                             and not self._inbound and not self._precopy)
                if quiet:
                    delay *= self.event_safety_net_factor
            if sub is None:
                if stop.wait(delay):
                    return
            else:
                trig = sub.wait_trigger(stop, delay)
                if trig == "stop":
                    return
                if trig == "event":
                    # Brief coalesce window so a burst (drain journal
                    # write + bind commit) costs one tick, not several.
                    if stop.wait(0.02):
                        return
                    sub.drain()
                    self.event_ticks_total += 1
            try:
                self.tick()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001
                consecutive_failures += 1
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"
                if consecutive_failures >= 3:
                    raise
                logger.exception(
                    "migration tick failed (%d consecutive; escalating "
                    "to the supervisor at 3)", consecutive_failures,
                )

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        """The ``migration`` block of /debug/allocations and the doctor
        bundle: per-pod ack freshness, outbound records, inbound
        verifications — "are we actually checkpointing?" (drain.py's
        open question) answerable from one scrape."""
        now = self._clock.time()
        with self._lock:
            return {
                "acked_pods": {
                    k: {
                        "ack_ts": ts,
                        "age_s": round(max(0.0, now - ts), 3),
                        "step": (self._last_acks.get(k) or {}).get("step"),
                        "kind": (self._last_acks.get(k) or {}).get(
                            "kind", "checkpoint"
                        ),
                    }
                    for k, ts in sorted(self._acked.items())
                },
                "records": {
                    k: {
                        f: r.get(f) for f in (
                            "step", "checkpoint_dir", "digest", "reason",
                            "published", "reclaimed", "trace",
                        )
                    }
                    for k, r in sorted(self._records.items())
                },
                "inbound": {
                    k: {
                        "stage": v.get("stage"),
                        "step": (v.get("record") or {}).get("step"),
                        "source_node": (v.get("record") or {}).get(
                            "source_node"
                        ),
                        "restamp_ts": v.get("restamp_ts"),
                    }
                    for k, v in sorted(self._inbound.items())
                },
                "precopy": {
                    k: {
                        "stage": v.get("stage"),
                        "rounds": len(v.get("rounds") or []),
                        "last_delta_bytes": (
                            (v.get("rounds") or [{}])[-1].get("delta_bytes")
                        ),
                        "cutover_ts": v.get("cutover_ts"),
                        "cutover_reason": v.get("cutover_reason"),
                    }
                    for k, v in sorted(self._precopy.items())
                },
                "suppressed_pods": sorted(self._migrated),
                "recent_completions": list(self._completed),
                "early_reclaims_total": self._early_reclaims_total,
                "records_published_total": self._records_published_total,
                "completed_total": self._completed_total,
                "precopy_rounds_total": self._precopy_rounds_total,
                "cutovers_total": self._cutovers_total,
                "verify_failures_total": self._verify_failures_total,
                "last_error": self._last_error,
            }
