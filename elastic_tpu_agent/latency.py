"""Critical-path latency observatory (ROADMAP item 3's measuring stick).

Two complementary ledgers, both dependency-free and bounded:

- :class:`BindLatencyObservatory` turns completed bind traces
  (tracing.py) into a **per-phase breakdown** of where the
  milliseconds go — lock wait, kubelet List/snapshot refresh, storage
  sync-flush wait, spec merge+write, sink enqueue, sidecar
  materialization — exported as ``elastic_tpu_bind_phase_seconds{phase}``
  histograms, with a per-phase-bucket **trace-id exemplar table** so a
  p99 bucket resolves to an actual trace in ``/debug/traces``. The
  breakdown is checkable: an ``unattributed`` residual phase absorbs
  whatever the instrumented spans did not cover, so
  ``sum(phases) + residual == measured total`` by construction and the
  residual's share is the bound the latency smoke asserts.
- :class:`DetectionLagTracker` accounts **origin -> detection ->
  repair** latency for every polled loop (reconciler, drain, sampler,
  repartition, migration, goodput). Origins come from injected fault
  timestamps (stub operator), file payload timestamps (usage reports,
  checkpoint acks), journal rows, or explicit :meth:`mark` calls from
  tests and the fleet sim. Surfaced as
  ``elastic_tpu_detection_lag_seconds{loop,stage,trigger}`` and rolled
  up per divergence class by the fleet aggregator; ``trigger`` says
  what woke the observing pass (``event`` = targeted event-bus pass,
  ``poll`` = periodic safety-net sweep), making the event-driven
  core's <50ms event-to-repair claim directly comparable against the
  ~0.7s poll baseline per loop.

Design constraints (same as tracing.py):
- stdlib only; importable everywhere the agent runs;
- never load-bearing: a broken observatory must not fail a bind or a
  repair — every public entry point swallows its own failures;
- bounded memory: deques and capped dicts throughout.
"""

from __future__ import annotations

import logging
import math
import threading
from collections import OrderedDict, deque
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# -- phase vocabulary ----------------------------------------------------------

# The closed phase vocabulary of the bind critical path. Order is the
# rough order phases occur in a bind; "unattributed" is the residual.
PHASE_LOCK_WAIT = "lock_wait"
PHASE_KUBELET_LIST = "kubelet_list"
PHASE_STORAGE_SYNC = "storage_sync"
PHASE_SPEC_WRITE = "spec_write"
PHASE_SINK_ENQUEUE = "sink_enqueue"
PHASE_SIDECAR = "sidecar"
PHASE_UNATTRIBUTED = "unattributed"

PHASES = (
    PHASE_LOCK_WAIT,
    PHASE_KUBELET_LIST,
    PHASE_STORAGE_SYNC,
    PHASE_SPEC_WRITE,
    PHASE_SINK_ENQUEUE,
    PHASE_SIDECAR,
)

# span name (tracing.py call sites) -> phase. Nested spans that map to
# the SAME phase (checkpoint wrapping storage_flush_wait) never double
# count: attribution claims time intervals innermost-first.
SPAN_PHASE = {
    "bind_lock_wait": PHASE_LOCK_WAIT,
    "pod_lookup": PHASE_KUBELET_LIST,
    "pod_resources_list": PHASE_KUBELET_LIST,
    "prefetch_locator": PHASE_KUBELET_LIST,
    # the locator's assignment lookup: kubelet pod-resources snapshot
    # reads + refresh waits — the dominant bind phase under churn
    "locator_locate": PHASE_KUBELET_LIST,
    "operator_create": PHASE_SIDECAR,
    "checkpoint": PHASE_STORAGE_SYNC,
    "storage_flush_wait": PHASE_STORAGE_SYNC,
    "write_alloc_spec": PHASE_SPEC_WRITE,
    "sink_enqueue": PHASE_SINK_ENQUEUE,
    "materialize_nodes": PHASE_SIDECAR,
}

# Exemplar bucket bounds — the same vocabulary as the
# elastic_tpu_bind_phase_seconds histogram (metrics._BUCKETS), kept
# here too so the observatory stays importable without prometheus.
EXEMPLAR_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, math.inf,
)

DEFAULT_RECENT_CAP = 512
DEFAULT_SLOW_CAP = 32


def _quantile(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank quantile over a small sample (no interpolation —
    these windows are a few hundred points, exactness is not the
    point)."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(q * (len(ordered) - 1)))))
    return ordered[idx]


def _bucket_le(seconds: float) -> float:
    for le in EXEMPLAR_BUCKETS:
        if seconds <= le:
            return le
    return math.inf


def attribute_spans(spans) -> Dict[str, float]:
    """Attribute a trace's span intervals to phases, innermost-first.

    Each span is an interval ``[offset, offset + duration)`` on the
    trace's own clock. Spans are processed shortest-first, and a span's
    contribution is its interval MINUS whatever shorter (nested) spans
    already claimed — so ``checkpoint`` wrapping ``storage_flush_wait``
    contributes only its unclaimed remainder, and the phase sums can
    never exceed wall time regardless of how call sites nest.

    ``spans`` is an iterable of objects with ``name``, ``offset_s`` and
    ``duration_s`` (tracing.Span) or dicts with ``name``/``offset_ms``/
    ``duration_ms`` (a serialized trace). Returns phase -> seconds for
    phases that claimed any time.
    """
    intervals = []
    for sp in spans:
        if isinstance(sp, dict):
            name = sp.get("name", "")
            start = float(sp.get("offset_ms", 0.0)) / 1000.0
            dur = float(sp.get("duration_ms", 0.0)) / 1000.0
        else:
            name = sp.name
            start = float(sp.offset_s)
            dur = float(sp.duration_s)
        phase = SPAN_PHASE.get(name)
        if phase is None or dur <= 0:
            continue
        intervals.append((dur, start, start + dur, phase))
    intervals.sort()  # shortest (innermost) first
    claimed: List[tuple] = []  # disjoint (start, end) already attributed
    out: Dict[str, float] = {}
    for dur, start, end, phase in intervals:
        remaining = [(start, end)]
        for c0, c1 in claimed:
            nxt = []
            for s0, s1 in remaining:
                if c1 <= s0 or c0 >= s1:  # no overlap
                    nxt.append((s0, s1))
                    continue
                if s0 < c0:
                    nxt.append((s0, c0))
                if c1 < s1:
                    nxt.append((c1, s1))
            remaining = nxt
            if not remaining:
                break
        got = sum(s1 - s0 for s0, s1 in remaining)
        if got > 0:
            out[phase] = out.get(phase, 0.0) + got
            claimed.extend(remaining)
            claimed.sort()
    return out


class BindLatencyObservatory:
    """Per-phase breakdown of completed bind traces, with bucket
    exemplars, a top-N slowest table and the unattributed residual.

    Registered as a tracer listener (tracing.Tracer.add_listener); in
    the fleet sim many agents share one process-wide tracer, so the
    observatory filters on the trace's ``node`` attribute when given a
    node name.
    """

    def __init__(
        self,
        metrics=None,
        node_name: str = "",
        trace_name: str = "PreStartContainer",
        recent_cap: int = DEFAULT_RECENT_CAP,
        slow_cap: int = DEFAULT_SLOW_CAP,
    ) -> None:
        self._metrics = metrics
        self._node = node_name
        self._trace_name = trace_name
        self._lock = threading.Lock()
        self._recent: "deque[dict]" = deque(maxlen=max(8, recent_cap))
        self._slow_cap = max(1, slow_cap)
        # phase -> le -> {"trace_id", "ms"}: the newest trace observed
        # in each bucket, so every populated bucket stays resolvable to
        # a concrete trace in /debug/traces.
        self._exemplars: Dict[str, Dict[float, dict]] = {}
        self.observed_total = 0

    # -- recording (tracer listener) ------------------------------------------

    def observe_trace(self, trace) -> None:
        """Tracer listener entry point: never raises."""
        try:
            self._observe(trace)
        except Exception:  # noqa: BLE001 - observatory never breaks a bind
            logger.exception("bind latency attribution failed")

    def _observe(self, trace) -> None:
        if trace.name != self._trace_name or trace.error is not None:
            return
        node = str(trace.attrs.get("node", ""))
        if self._node and node and node != self._node:
            return  # another sim agent's bind on the shared tracer
        total = float(trace.duration_s)
        if total <= 0:
            return
        phases = attribute_spans(trace.spans)
        residual = max(0.0, total - sum(phases.values()))
        pod = str(
            trace.attrs.get("pod", "")
            or ((trace.attrs.get("pods") or [""]) or [""])[0]
        )
        entry = {
            "trace_id": trace.trace_id,
            "ts": trace.start_ts,
            "pod": pod,
            "total_ms": round(total * 1000, 3),
            "phases_ms": {
                p: round(s * 1000, 3) for p, s in sorted(phases.items())
            },
            "residual_ms": round(residual * 1000, 3),
            "dominant_phase": (
                max(phases, key=phases.get) if phases
                and max(phases.values()) >= residual else PHASE_UNATTRIBUTED
            ),
        }
        with self._lock:
            self.observed_total += 1
            self._recent.append(entry)
            for phase, seconds in phases.items():
                self._exemplars.setdefault(phase, {})[
                    _bucket_le(seconds)
                ] = {"trace_id": trace.trace_id,
                     "ms": round(seconds * 1000, 3)}
            self._exemplars.setdefault(PHASE_UNATTRIBUTED, {})[
                _bucket_le(residual)
            ] = {"trace_id": trace.trace_id,
                 "ms": round(residual * 1000, 3)}
        m = self._metrics
        if m is not None and hasattr(m, "bind_phase_seconds"):
            try:
                for phase, seconds in phases.items():
                    m.bind_phase_seconds.labels(phase=phase).observe(seconds)
                m.bind_phase_seconds.labels(
                    phase=PHASE_UNATTRIBUTED
                ).observe(residual)
            except Exception:  # noqa: BLE001 - metrics never break a bind
                pass

    # -- reading --------------------------------------------------------------

    def status(self, top: Optional[int] = None) -> dict:
        """The /debug/latency "bind" block: per-phase p50/p99 + share of
        total, bucket exemplars, top-N slowest recent traces with their
        dominant phase, and the residual's share (the checkability
        contract: phase sums + residual == measured totals)."""
        top = self._slow_cap if top is None else max(1, top)
        with self._lock:
            recent = list(self._recent)
            exemplars = {
                phase: {
                    ("+Inf" if math.isinf(le) else le): dict(ex)
                    for le, ex in sorted(buckets.items())
                }
                for phase, buckets in self._exemplars.items()
            }
            observed = self.observed_total
        totals = [e["total_ms"] for e in recent]
        sum_total = sum(totals)
        phase_block: Dict[str, dict] = {}
        for phase in (*PHASES, PHASE_UNATTRIBUTED):
            values = [
                e["residual_ms"] if phase == PHASE_UNATTRIBUTED
                else e["phases_ms"].get(phase, 0.0)
                for e in recent
            ]
            nonzero = [v for v in values if v > 0]
            phase_sum = sum(values)
            phase_block[phase] = {
                "count": len(nonzero),
                "p50_ms": _quantile(values, 0.5),
                "p99_ms": _quantile(values, 0.99),
                "share_of_total": (
                    round(phase_sum / sum_total, 4) if sum_total else None
                ),
                "exemplars": exemplars.get(phase, {}),
            }
        slowest = sorted(
            recent, key=lambda e: e["total_ms"], reverse=True
        )[:top]
        return {
            "observed_total": observed,
            "window": len(recent),
            "total_p50_ms": _quantile(totals, 0.5),
            "total_p99_ms": _quantile(totals, 0.99),
            "phases": phase_block,
            "residual_share": phase_block[PHASE_UNATTRIBUTED][
                "share_of_total"
            ],
            "slowest": slowest,
        }


# -- detection-lag accounting --------------------------------------------------

STAGE_DETECT = "detect"
STAGE_REPAIR = "repair"

# Bound on stored origin marks and dedup entries: divergences are rare
# and repairs pop their marks, so hitting this means a test rig leaked.
DEFAULT_MAX_MARKS = 4096
DEFAULT_RECENT_PER_CLASS = 128


class DetectionLagTracker:
    """Origin -> detection -> repair latency, per polled loop.

    - :meth:`mark` stamps a divergence origin (``cls``/``key``) — the
      seam fault injectors, the fleet sim and tests use; loops whose
      origins ride in file payloads (usage reports, checkpoint acks)
      or operator injections pass ``origin_ts`` directly instead.
    - :meth:`detected` / :meth:`repaired` observe one stage each;
      :meth:`handled` observes both at once (loops whose detection IS
      the repair, e.g. a reconciler pass).

    Clock-skew and restart semantics (pinned by tests):
    - a negative lag (origin stamped by a skewed clock) is clamped to
      0 and counted in ``clamped_total`` — never exported negative;
    - an observation with no known origin returns None and records
      nothing: after an agent restart (fresh tracker) a re-detected
      pre-restart divergence contributes no bogus lag;
    - repairs pop their mark, and the same (loop, stage, class, key,
      origin) is observed at most once — re-reading a still-on-disk
      origin (ack file, usage report) cannot double count.
    """

    def __init__(
        self,
        metrics=None,
        clock=None,
        recent_per_class: int = DEFAULT_RECENT_PER_CLASS,
        max_marks: int = DEFAULT_MAX_MARKS,
    ) -> None:
        from .common import SYSTEM_CLOCK

        self._metrics = metrics
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._lock = threading.Lock()
        self._marks: "OrderedDict[tuple, float]" = OrderedDict()
        self._seen: "OrderedDict[tuple, None]" = OrderedDict()
        self._max = max(16, max_marks)
        self._recent_cap = max(8, recent_per_class)
        # class -> deque of {"lag_s", "loop", "ts"} (repair stage only:
        # the fleet rollup reports origin->repair per divergence class)
        self._recent: Dict[str, deque] = {}
        self.clamped_total = 0
        self.observations = {STAGE_DETECT: 0, STAGE_REPAIR: 0}

    # -- origin stamping ------------------------------------------------------

    def mark(self, cls: str, key: str = "", ts: Optional[float] = None) -> None:
        """Stamp a divergence origin. Idempotent per (cls, key): the
        FIRST stamp wins (re-asserting a still-unrepaired fault must
        not shrink its measured lag)."""
        try:
            with self._lock:
                k = (str(cls), str(key))
                if k not in self._marks:
                    self._marks[k] = (
                        self._clock.time() if ts is None else float(ts)
                    )
                    while len(self._marks) > self._max:
                        self._marks.popitem(last=False)
        except Exception:  # noqa: BLE001 - accounting never breaks a caller
            logger.exception("detection-lag mark failed")

    def unmark(self, cls: str, key: str = "") -> None:
        with self._lock:
            self._marks.pop((str(cls), str(key)), None)

    def origin(self, cls: str, key: str = "") -> Optional[float]:
        with self._lock:
            return self._marks.get((str(cls), str(key)))

    # -- observations ---------------------------------------------------------

    def detected(
        self, loop: str, cls: str, key: str = "",
        origin_ts: Optional[float] = None, trigger: str = "poll",
    ) -> Optional[float]:
        return self._observe(loop, STAGE_DETECT, cls, key, origin_ts,
                             clear=False, trigger=trigger)

    def repaired(
        self, loop: str, cls: str, key: str = "",
        origin_ts: Optional[float] = None, trigger: str = "poll",
    ) -> Optional[float]:
        return self._observe(loop, STAGE_REPAIR, cls, key, origin_ts,
                             clear=True, trigger=trigger)

    def handled(
        self, loop: str, cls: str, key: str = "",
        origin_ts: Optional[float] = None, trigger: str = "poll",
    ) -> Optional[float]:
        """Detection and repair collapsed into one call — for loops
        whose single pass both notices and resolves the divergence.
        ``trigger`` records what woke the pass ("event" = targeted
        event-bus pass, "poll" = the periodic sweep) so event-vs-poll
        lag is directly comparable per loop."""
        self._observe(loop, STAGE_DETECT, cls, key, origin_ts, clear=False,
                      trigger=trigger)
        return self._observe(loop, STAGE_REPAIR, cls, key, origin_ts,
                             clear=True, trigger=trigger)

    def _observe(
        self, loop: str, stage: str, cls: str, key: str,
        origin_ts: Optional[float], clear: bool, trigger: str = "poll",
    ) -> Optional[float]:
        try:
            cls, key = str(cls), str(key)
            now = self._clock.time()
            with self._lock:
                origin = (
                    float(origin_ts) if origin_ts is not None
                    else self._marks.get((cls, key))
                )
                if origin is None:
                    return None
                dedup = (str(loop), stage, cls, key, origin)
                if dedup in self._seen:
                    return None  # same origin already observed: no recount
                self._seen[dedup] = None
                while len(self._seen) > self._max:
                    self._seen.popitem(last=False)
                lag = now - origin
                if lag < 0:
                    lag = 0.0
                    self.clamped_total += 1
                self.observations[stage] = (
                    self.observations.get(stage, 0) + 1
                )
                if clear:
                    self._marks.pop((cls, key), None)
                if stage == STAGE_REPAIR:
                    self._recent.setdefault(
                        cls, deque(maxlen=self._recent_cap)
                    ).append({
                        "lag_s": round(lag, 6), "loop": str(loop), "ts": now,
                        "trigger": str(trigger),
                    })
            m = self._metrics
            if m is not None and hasattr(m, "detection_lag"):
                try:
                    m.detection_lag.labels(
                        loop=str(loop), stage=stage, trigger=str(trigger)
                    ).observe(lag)
                    if lag == 0.0 and origin > now and hasattr(
                        m, "detection_lag_clamped"
                    ):
                        m.detection_lag_clamped.inc()
                except Exception:  # noqa: BLE001 - metrics never break repair
                    pass
            return lag
        except Exception:  # noqa: BLE001 - accounting never breaks a caller
            logger.exception("detection-lag observation failed")
            return None

    # -- reading --------------------------------------------------------------

    def status(self) -> dict:
        """The /debug/latency "detection_lag" block: per-class recent
        origin->repair lags with p50/p99 (what the fleet aggregator
        merges), plus the clamp counter and open-mark gauge."""
        with self._lock:
            classes = {
                cls: list(entries) for cls, entries in self._recent.items()
            }
            open_marks = len(self._marks)
            clamped = self.clamped_total
            observations = dict(self.observations)
        block = {}
        for cls, entries in sorted(classes.items()):
            lags = [e["lag_s"] for e in entries]
            triggers: Dict[str, list] = {}
            for e in entries:
                triggers.setdefault(e.get("trigger", "poll"), []).append(
                    e["lag_s"]
                )
            block[cls] = {
                "count": len(lags),
                "p50_s": _quantile(lags, 0.5),
                "p99_s": _quantile(lags, 0.99),
                "max_s": max(lags) if lags else None,
                "loops": sorted({e["loop"] for e in entries}),
                # event-vs-poll comparability (satellite of the event
                # core): per-trigger count + p50 of the same class
                "triggers": {
                    t: {"count": len(ls), "p50_s": _quantile(ls, 0.5)}
                    for t, ls in sorted(triggers.items())
                },
                "recent": entries[-20:],
            }
        return {
            "classes": block,
            "open_marks": open_marks,
            "clamped_total": clamped,
            "observations": observations,
        }
