"""Continuous in-process sampling profiler (dependency-free).

A wedged agent is exactly the one nobody can attach py-spy to in time:
the pod image has no profiler, the incident is already live, and the
hot stack is gone by the time anyone gets a shell. This module keeps a
cheap statistical profile running INSIDE the agent — a supervised loop
that walks ``sys._current_frames()`` a few times a second, aggregates
the frames into a bounded stack table, and serves the result at
``/debug/profile`` (metrics HTTP threads keep answering even when the
main loops are wedged — that is the point) and through the doctor
bundle / ``node-doctor profile``.

Self-honesty contract: the profiler measures its own cost (cumulative
time inside :meth:`sample_once` over wall time) and exports it as
``elastic_tpu_profiler_overhead_ratio``; the latency smoke pins it
under 1% at the default rate. Off (``--profile-hz 0``) it costs
nothing at all.

Bounded by construction: at most ``max_stacks`` distinct aggregated
stacks (new stacks beyond the cap are counted dropped, never stored),
at most ``depth`` frames per stack.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

DEFAULT_MAX_STACKS = 256
DEFAULT_DEPTH = 24


class SamplingProfiler:
    """Supervised sampling profiler: ``run(stop)`` paces
    :meth:`sample_once` at ``hz``; ``status()`` is the read side."""

    def __init__(
        self,
        hz: float = 0.0,
        max_stacks: int = DEFAULT_MAX_STACKS,
        depth: int = DEFAULT_DEPTH,
    ) -> None:
        self.hz = max(0.0, float(hz))
        self.max_stacks = max(16, int(max_stacks))
        self.depth = max(2, int(depth))
        self._lock = threading.Lock()
        # (thread name, (frame, ...)) -> sample count, leaf-first frames
        # rendered "file.py:lineno:function"
        self._stacks: Dict[tuple, int] = {}
        self.samples_total = 0
        self.threads_seen = 0
        self.dropped_stacks = 0
        self._sampling_s = 0.0  # cumulative wall time spent sampling
        self._started_mono = time.monotonic()

    # -- sampling -------------------------------------------------------------

    def sample_once(self) -> int:
        """Walk every thread's current frame once; returns the number of
        threads sampled. The profiler's own thread is excluded (it
        would otherwise dominate its own profile with this walk)."""
        t0 = time.monotonic()
        own = threading.get_ident()
        try:
            frames = sys._current_frames()  # noqa: SLF001 - the whole point
            names = {
                t.ident: t.name for t in threading.enumerate()
                if t.ident is not None
            }
            sampled = 0
            aggregated = []
            for ident, frame in frames.items():
                if ident == own:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < self.depth:
                    code = f.f_code
                    stack.append(
                        f"{code.co_filename.rsplit('/', 1)[-1]}"
                        f":{f.f_lineno}:{code.co_name}"
                    )
                    f = f.f_back
                aggregated.append(
                    (names.get(ident, f"tid-{ident}"), tuple(stack))
                )
                sampled += 1
            with self._lock:
                for key in aggregated:
                    if key in self._stacks:
                        self._stacks[key] += 1
                    elif len(self._stacks) < self.max_stacks:
                        self._stacks[key] = 1
                    else:
                        self.dropped_stacks += 1
                self.samples_total += 1
                self.threads_seen = max(self.threads_seen, sampled)
            return sampled
        finally:
            with self._lock:
                self._sampling_s += time.monotonic() - t0

    def run(self, stop: threading.Event) -> None:
        """Supervised loop (DEGRADED): a crashed profiler restarts with
        its table intact on the same instance; hz <= 0 parks until
        stop (registered only behind --profile-hz, but defensive)."""
        if self.hz <= 0:
            stop.wait()
            return
        period = 1.0 / self.hz
        while not stop.wait(period):
            self.sample_once()

    # -- reading --------------------------------------------------------------

    def overhead_ratio(self) -> float:
        """Fraction of wall time spent inside sample_once() since this
        profiler was constructed — the measured self-overhead gauge
        (the <=1% contract the smoke pins)."""
        wall = time.monotonic() - self._started_mono
        if wall <= 0:
            return 0.0
        with self._lock:
            return self._sampling_s / wall

    def status(self, top: int = 30) -> dict:
        """The /debug/profile payload: hottest aggregated stacks
        (leaf-first frames), sample/drop counters, measured overhead."""
        with self._lock:
            items = sorted(
                self._stacks.items(), key=lambda kv: kv[1], reverse=True
            )[:max(1, top)]
            samples = self.samples_total
            dropped = self.dropped_stacks
            unique = len(self._stacks)
            threads = self.threads_seen
        return {
            "enabled": self.hz > 0,
            "hz": self.hz,
            "samples_total": samples,
            "unique_stacks": unique,
            "dropped_stacks": dropped,
            "max_stacks": self.max_stacks,
            "threads_seen": threads,
            "overhead_ratio": round(self.overhead_ratio(), 6),
            "top": [
                {
                    "count": count,
                    "share": round(count / samples, 4) if samples else None,
                    "thread": thread,
                    "stack": list(stack),
                }
                for (thread, stack), count in items
            ],
        }

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self.samples_total = 0
            self.dropped_stacks = 0
            self.threads_seen = 0
            self._sampling_s = 0.0
            self._started_mono = time.monotonic()


def render_profile(payload: dict, top: Optional[int] = None) -> str:
    """Human-readable rendering of a /debug/profile payload (the
    ``node-doctor profile`` output)."""
    lines = []
    if not payload.get("enabled"):
        lines.append(
            "profiler DISABLED (start the agent with --profile-hz > 0)"
        )
    lines.append(
        f"samples={payload.get('samples_total', 0)} "
        f"hz={payload.get('hz', 0)} "
        f"unique_stacks={payload.get('unique_stacks', 0)} "
        f"dropped={payload.get('dropped_stacks', 0)} "
        f"overhead={100.0 * (payload.get('overhead_ratio') or 0.0):.3f}%"
    )
    entries = payload.get("top", [])
    if top is not None:
        entries = entries[:max(1, top)]
    for entry in entries:
        share = entry.get("share")
        lines.append(
            f"{entry.get('count', 0):>7} "
            f"{('%5.1f%%' % (100 * share)) if share is not None else '    ?'} "
            f"[{entry.get('thread', '?')}]"
        )
        for frame in entry.get("stack", []):
            lines.append(f"          {frame}")
    return "\n".join(lines)
