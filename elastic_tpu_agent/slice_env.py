"""Multi-host TPU slice env (BASELINE config 5).

The reference's "distributed" surface was control-plane only (SURVEY.md
§2: no NCCL/MPI anywhere); on TPU the data plane (ICI within a slice, DCN
between slices) is wired by libtpu/XLA. The agent's multi-host job is
exactly this: every agent instance on a v5p-16 (or larger) pod-slice must
emit a *consistent* worker identity + topology env so ``jax.distributed``
can form the slice — derived from the metadata server and pod annotations
only, never from agent-to-agent coordination (SURVEY.md §7 hard parts).

Env contract (the names libtpu/JAX read on Cloud TPU VMs):
  TPU_WORKER_ID            this host's index within the slice
  TPU_WORKER_HOSTNAMES     comma-separated hosts, index-ordered
  TPU_CHIPS_PER_HOST_BOUNDS  x,y,z chips-per-host grid
  TPU_HOST_BOUNDS          x,y,z host grid
  TPU_ACCELERATOR_TYPE     e.g. v5p-16
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .common import (
    AnnotationSliceName,
    AnnotationSliceWorkerHosts,
    AnnotationSliceWorkerID,
)
from .tpu.topology import TopologyInfo, host_bounds, parse_accelerator_type


def split_hosts(value: str) -> List[str]:
    """The ONE host-list grammar: comma-separated hostnames, empty
    entries dropped. The annotation parse (registry), the PreStart
    stamp parse and the stamped-spec parse (recovery) all read it —
    they must never disagree about the same list."""
    return [h for h in (value or "").split(",") if h]


def ordered_worker_hostnames(
    hostnames: List[str], own_host: str = ""
) -> "tuple[List[str], int]":
    """Deterministic worker ordering for annotation-driven slices:
    hostnames de-duplicated and sorted lexicographically, plus the
    worker index of ``own_host`` in that order (-1 when absent).

    Every cooperating agent derives the slice env independently from the
    shared apiserver state (SURVEY.md §7: no agent-to-agent
    coordination), so the ordering must be a pure function of the host
    SET — any dependence on annotation write order or map iteration
    order would let two hosts disagree about who is worker 0 and the
    ``jax.distributed`` rendezvous would deadlock. The slices property
    test pins this: every permutation of the input yields the identical
    ordering and bounds.
    """
    ordered = sorted(set(h for h in hostnames if h))
    try:
        own_index = ordered.index(own_host)
    except ValueError:
        own_index = -1
    return ordered, own_index


def slice_env_from_topology(
    topo: TopologyInfo,
    worker_id: int,
    worker_hostnames: List[str],
) -> Dict[str, str]:
    chip_bounds, hbounds = host_bounds(topo)
    env = {
        "TPU_ACCELERATOR_TYPE": topo.accelerator_type,
        "TPU_CHIPS_PER_HOST_BOUNDS": chip_bounds,
        "TPU_HOST_BOUNDS": hbounds,
        "TPU_WORKER_ID": str(worker_id),
    }
    if worker_hostnames:
        env["TPU_WORKER_HOSTNAMES"] = ",".join(worker_hostnames)
    return env


def slice_env_for_pod(
    annotations: Dict[str, str],
    topo: Optional[TopologyInfo],
    host_worker_id: int = 0,
    host_worker_hostnames: Optional[List[str]] = None,
) -> Dict[str, str]:
    """Slice env for one pod binding.

    Pod annotations override host-level facts (a pod-slice scheduled by the
    elastic scheduler carries its own worker numbering); host metadata
    (``host_worker_id``/``hostnames`` from the TPU-VM metadata server) is
    the default for plain single-slice jobs. No slice annotation and a
    single-host topology -> empty (nothing to coordinate).
    """
    ann_type = annotations.get(AnnotationSliceName, "")
    ann_id = annotations.get(AnnotationSliceWorkerID, "")
    ann_hosts = annotations.get(AnnotationSliceWorkerHosts, "")

    topo_for_pod = topo
    if ann_type:
        parsed = parse_accelerator_type(ann_type)
        if parsed is not None:
            topo_for_pod = parsed
    if topo_for_pod is None:
        return {}

    worker_id = host_worker_id
    if ann_id:
        try:
            worker_id = int(ann_id)
        except ValueError:
            pass
    hostnames = (
        [h for h in ann_hosts.split(",") if h]
        if ann_hosts
        else list(host_worker_hostnames or [])
    )

    if not topo_for_pod.is_multi_host and not ann_type:
        return {}
    return slice_env_from_topology(topo_for_pod, worker_id, hostnames)
