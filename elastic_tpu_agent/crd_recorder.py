"""Async ElasticTPU lifecycle recorder: bind/release → CRD objects.

The reference *intended* this: its plugins carried a full CRD-writing path
(creating ElasticGPU objects per allocation) that was entirely commented
out (reference pkg/plugins/nvidia.go:28-137, manager.go:59-88), and its
RBAC still grants elasticgpu.io CRUD (deploy/elastic-gpu-agent.yaml). Here
the path is real: every bound allocation is published as an `ElasticTPU`
object (phase Bound, claimRef → pod/container, physical chip indexes),
released allocations are marked Released and removed, and restore()
reconciles cluster objects against the checkpoint store.

Design constraints (why writes go through async_sink.AsyncSink, not
inline calls):

- The bind path is the latency SLO (BASELINE.md: Allocate/PreStart p50);
  an apiserver round-trip there would add ~ms and couple the SLO to
  apiserver health. All writes are enqueued and applied asynchronously.
- CRD publication is *observability*, never load-bearing: failures are
  logged and dropped; after repeated consecutive failures (e.g. the CRD is
  not installed, or RBAC denies us) the sink disables itself so it cannot
  spam the apiserver forever.
"""

from __future__ import annotations

import logging
from typing import Iterable, List, Optional

from .async_sink import AsyncSink, drop_hook, register_sink_metrics
from .common import ResourceTPUCore, ResourceTPUMemory, TPUPercentEachChip
from .crd import (
    ElasticTPU,
    ElasticTPUClient,
    PhaseAvailable,
    PhaseBound,
    PhaseFailed,
    PhaseReleased,
)

logger = logging.getLogger(__name__)


class CRDRecorder:
    """Publishes allocation lifecycle to the ElasticTPU CRD, off the hot
    path. All public methods are non-blocking and never raise."""

    def __init__(
        self,
        client: ElasticTPUClient,
        node_name: str,
        accelerator_type: str = "",
        metrics=None,
        flush_window_s: float = 0.0,
    ) -> None:
        self._client = client
        self._node = node_name
        self._accelerator_type = accelerator_type
        self._sink = AsyncSink(
            "crd-recorder", on_drop=drop_hook(metrics),
            flush_window_s=flush_window_s,
        )
        register_sink_metrics(self._sink, metrics)

    # -- public API (called from plugin bind / GC / manager restore) ----------

    def object_name(self, alloc_hash: str) -> str:
        # DNS-1123: node names are already DNS labels, hash is lowercase hex.
        return f"{self._node}-{alloc_hash}"

    def inventory_name(self, chip_index: int) -> str:
        return f"{self._node}-chip{chip_index}"

    def publish_inventory(self, chips) -> None:
        """Publish one Available-phase ElasticTPU object per discovered
        chip, so CRD consumers (external schedulers, dashboards) see node
        CAPACITY and not just bindings — the reference CRD modeled exactly
        these phases and node-inventory objects but its agent never wrote
        them (reference vendor/elasticgpu.io types.go:49-78, writing path
        commented out). Called at boot and reconciled by restore()."""
        objs = [
            ElasticTPU(
                name=self.inventory_name(chip.index),
                node_name=self._node,
                capacity={
                    ResourceTPUCore: str(TPUPercentEachChip),
                    ResourceTPUMemory: str(chip.hbm_bytes // (1024 * 1024)),
                },
                chip_indexes=[chip.index],
                accelerator_type=self._accelerator_type,
                phase=PhaseAvailable,
                message=(
                    f"chip {chip.index} ({chip.uuid}): "
                    f"{chip.hbm_bytes // (1024 ** 3)} GiB HBM, "
                    f"{chip.cores} core(s)"
                ),
            )
            for chip in chips
        ]

        def publish() -> None:
            for obj in objs:
                self._client.create(obj, update_existing=True)

        # coalescing key: only the newest queued inventory snapshot matters
        self._submit(publish, key="inventory")

    def record_bound(
        self,
        alloc_hash: str,
        resource: str,
        amount: int,
        namespace: str,
        pod: str,
        container: str,
        chip_indexes: List[int],
        trace_id: str = "",
    ) -> None:
        message = f"bound by elastic-tpu-agent on {self._node}"
        if trace_id:
            # the CRD record carries the bind's allocation-trace id so a
            # consumer can jump to the agent's /debug/traces dump
            message += f" [trace {trace_id}]"
        obj = ElasticTPU(
            name=self.object_name(alloc_hash),
            node_name=self._node,
            capacity={resource: str(amount)},
            chip_indexes=list(chip_indexes),
            accelerator_type=self._accelerator_type,
            claim_namespace=namespace,
            claim_name=pod,
            claim_container=container,
            phase=PhaseBound,
            message=message,
        )
        # keyed per object: a queued-but-unwritten Bound for this hash is
        # superseded by a newer write (e.g. its Released) instead of both
        # hitting the apiserver
        self._submit(
            lambda: self._client.create(obj, update_existing=True),
            key=("obj", obj.name),
        )

    def record_chip_health(
        self, chip_index: int, healthy: bool, reason: str = ""
    ) -> None:
        """Flip the chip's inventory object between Available and Failed on
        health transitions, so an external scheduler consuming the CRD
        stops placing onto a dead chip (reference phases: vendored
        types.go:49-57; the boot-time publish alone would advertise a dead
        chip as Available forever)."""
        name = self.inventory_name(chip_index)
        if healthy:
            phase, message = PhaseAvailable, "chip recovered"
        else:
            phase, message = PhaseFailed, reason or "chip unhealthy"

        self._submit(
            lambda: self._client.update_status(name, phase, message),
            key=("chip", chip_index),
        )

    def record_migration(self, obj: ElasticTPU) -> None:
        """Publish (or refresh) a MigrationRecord object built by the
        migration coordinator (phase Migrated, ``migration`` payload).
        Keyed per object name so a re-publish supersedes a queued
        duplicate; the coordinator confirms by read-back and re-submits
        until the record is really at the apiserver — the journal, not
        this queue, is the durable copy."""
        self._submit(
            lambda: self._client.create(obj, update_existing=True),
            key=("obj", obj.name),
        )

    def record_released(self, alloc_hash: str) -> None:
        name = self.object_name(alloc_hash)

        def release() -> None:
            try:
                self._client.update_status(
                    name, PhaseReleased, "reclaimed by elastic-tpu-agent"
                )
            except Exception:  # noqa: BLE001 - already gone is fine
                pass
            self._client.delete(name)

        self._submit(release, key=("obj", name))

    def reconcile(
        self,
        live_hashes: Iterable[str],
        chip_indexes: Iterable[int] = (),
    ) -> None:
        """Restore-time sweep: delete objects this node published for
        allocations that no longer exist in the checkpoint store, and
        inventory objects for chips no longer present (keeps the ones that
        are — publish_inventory upserts them)."""
        live = {self.object_name(h) for h in live_hashes}
        live |= {self.inventory_name(i) for i in chip_indexes}

        def sweep() -> None:
            for obj in self._client.list(self._node):
                if obj.name not in live:
                    logger.info("crd reconcile: removing stale %s", obj.name)
                    self._client.delete(obj.name)

        self._submit(sweep, key="reconcile")

    # -- lifecycle ------------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        return self._sink.flush(timeout=timeout)

    def stop(self, timeout: float = 30.0) -> None:
        # Generous default: stop() DRAINS (async_sink) — capping it at a
        # few seconds would re-introduce the abandoned-queue shutdown.
        self._sink.stop(timeout=timeout)

    def run_supervised(self, stop) -> None:
        """Supervisor target (supervisor.py): watchdog over the sink's
        internal worker thread."""
        self._sink.run_supervised(stop)

    @property
    def disabled(self) -> bool:
        return self._sink.disabled

    def _submit(self, op, key=None) -> None:
        self._sink.submit(op, key=key)


def build_recorder(
    kube_client, node_name: str, operator, metrics=None,
    flush_window_s: float = 0.0,
) -> Optional[CRDRecorder]:
    """Manager-side constructor: a recorder bound to this node's client and
    accelerator type; None when there is no kube client (hermetic runs)."""
    if kube_client is None or not node_name:
        return None
    acc = ""
    topo = getattr(operator, "topology", None)
    if topo is not None:
        acc = getattr(topo, "accelerator_type", "") or ""
    return CRDRecorder(
        ElasticTPUClient(kube_client), node_name, accelerator_type=acc,
        metrics=metrics, flush_window_s=flush_window_s,
    )
