"""Async ElasticTPU lifecycle recorder: bind/release → CRD objects.

The reference *intended* this: its plugins carried a full CRD-writing path
(creating ElasticGPU objects per allocation) that was entirely commented
out (reference pkg/plugins/nvidia.go:28-137, manager.go:59-88), and its
RBAC still grants elasticgpu.io CRUD (deploy/elastic-gpu-agent.yaml). Here
the path is real: every bound allocation is published as an `ElasticTPU`
object (phase Bound, claimRef → pod/container, physical chip indexes),
released allocations are marked Released and removed, and restore()
reconciles cluster objects against the checkpoint store.

Design constraints (why this is a worker thread, not inline calls):

- The bind path is the latency SLO (BASELINE.md: Allocate/PreStart p50);
  an apiserver round-trip there would add ~ms and couple the SLO to
  apiserver health. All writes are enqueued and applied asynchronously.
- CRD publication is *observability*, never load-bearing: failures are
  logged and dropped; after ``_MAX_CONSECUTIVE_FAILURES`` (e.g. the CRD is
  not installed, or RBAC denies us) the recorder disables itself so it
  cannot spam the apiserver forever.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Iterable, List, Optional

from .crd import ElasticTPU, ElasticTPUClient, PhaseBound, PhaseReleased

logger = logging.getLogger(__name__)

_MAX_CONSECUTIVE_FAILURES = 5
_STOP = object()


class CRDRecorder:
    """Publishes allocation lifecycle to the ElasticTPU CRD, off the hot
    path. All public methods are non-blocking and never raise."""

    def __init__(
        self,
        client: ElasticTPUClient,
        node_name: str,
        accelerator_type: str = "",
    ) -> None:
        self._client = client
        self._node = node_name
        self._accelerator_type = accelerator_type
        self._queue: "queue.Queue" = queue.Queue()
        self._failures = 0
        self._disabled = False
        self._pending = 0
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="crd-recorder"
        )
        self._thread.start()

    # -- public API (called from plugin bind / GC / manager restore) ----------

    def object_name(self, alloc_hash: str) -> str:
        # DNS-1123: node names are already DNS labels, hash is lowercase hex.
        return f"{self._node}-{alloc_hash}"

    def record_bound(
        self,
        alloc_hash: str,
        resource: str,
        amount: int,
        namespace: str,
        pod: str,
        container: str,
        chip_indexes: List[int],
    ) -> None:
        obj = ElasticTPU(
            name=self.object_name(alloc_hash),
            node_name=self._node,
            capacity={resource: str(amount)},
            chip_indexes=list(chip_indexes),
            accelerator_type=self._accelerator_type,
            claim_namespace=namespace,
            claim_name=pod,
            claim_container=container,
            phase=PhaseBound,
            message=f"bound by elastic-tpu-agent on {self._node}",
        )
        self._submit(lambda: self._client.create(obj, update_existing=True))

    def record_released(self, alloc_hash: str) -> None:
        name = self.object_name(alloc_hash)

        def release() -> None:
            try:
                self._client.update_status(
                    name, PhaseReleased, "reclaimed by elastic-tpu-agent"
                )
            except Exception:  # noqa: BLE001 - already gone is fine
                pass
            self._client.delete(name)

        self._submit(release)

    def reconcile(self, live_hashes: Iterable[str]) -> None:
        """Restore-time sweep: delete objects this node published for
        allocations that no longer exist in the checkpoint store."""
        live = {self.object_name(h) for h in live_hashes}

        def sweep() -> None:
            for obj in self._client.list(self._node):
                if obj.name not in live:
                    logger.info("crd reconcile: removing stale %s", obj.name)
                    self._client.delete(obj.name)

        self._submit(sweep)

    # -- lifecycle ------------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until queued work has drained (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def stop(self, timeout: float = 5.0) -> None:
        self.flush(timeout=timeout)
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)

    @property
    def disabled(self) -> bool:
        return self._disabled

    # -- worker ---------------------------------------------------------------

    def _submit(self, op) -> None:
        if self._disabled:
            return
        with self._cond:
            self._pending += 1
        self._queue.put(op)

    def _worker(self) -> None:
        while True:
            op = self._queue.get()
            if op is _STOP:
                return
            try:
                if not self._disabled:
                    op()
                    self._failures = 0
            except Exception as e:  # noqa: BLE001 - observability must not wedge
                self._failures += 1
                if self._failures >= _MAX_CONSECUTIVE_FAILURES:
                    self._disabled = True
                    logger.warning(
                        "CRD recorder disabled after %d consecutive failures "
                        "(last: %s) — is the ElasticTPU CRD installed and "
                        "RBAC granted?", self._failures, e,
                    )
                else:
                    logger.warning("CRD write failed (%s); continuing", e)
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending <= 0:
                        self._cond.notify_all()


def build_recorder(
    kube_client, node_name: str, operator
) -> Optional[CRDRecorder]:
    """Manager-side constructor: a recorder bound to this node's client and
    accelerator type; None when there is no kube client (hermetic runs)."""
    if kube_client is None or not node_name:
        return None
    acc = ""
    topo = getattr(operator, "topology", None)
    if topo is not None:
        acc = getattr(topo, "accelerator_type", "") or ""
    return CRDRecorder(
        ElasticTPUClient(kube_client), node_name, accelerator_type=acc
    )
