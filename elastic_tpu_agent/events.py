"""In-process event bus: the agent's poll-to-push seam.

Every control loop in the agent historically *polled* on a jittered
period, so lifecycle latency was bounded by the period, not by event
latency (fleet reconcile convergence median ~0.7s at a 1s period).
This bus lets the state sources push instead:

- the kube sitter publishes **pod deltas** straight off the apiserver
  watch stream (:data:`POD_DELTA`),
- ``PodResourcesSnapshotSource`` publishes **assignment deltas** from
  kubelet List diffs (:data:`ASSIGNMENT_DELTA`),
- ``Storage`` publishes **store-change notifications** — bind commits,
  intent open/close, agent_state writes — from the group-commit
  batcher's flush path (:data:`STORE_BIND`, :data:`STORE_INTENT`,
  :data:`STORE_STATE`),

and the reconciler / drain / repartition / migration / sampler loops
subscribe and run *targeted* passes on relevant events, with their
jittered periodic sweep demoted to a safety net (period stretched by
``event_safety_net_factor`` while the bus is healthy — still the
correctness backstop, never removed).

Design contract (tests/test_event_bus.py pins each clause):

- **Publishers never block and never fail.** ``publish`` is O(number of
  subscribers), takes only short internal locks, and swallows nothing
  silently: a full subscriber queue drops the OLDEST pending event and
  counts the drop; a crashing callback subscriber is counted and
  logged, never propagated to the publisher.
- **Bounded queues.** Every subscription has a hard queue cap. A slow
  consumer degrades to "wake up and resweep" semantics (it still holds
  the newest events and its drop counter says exactly how many it
  missed) — it can never exert backpressure on the bind path or the
  watch stream.
- **ManualClock-testable.** Events are stamped from the injected clock
  and carry a global monotone sequence number, so ordering assertions
  are deterministic under ``common.ManualClock``.
- **Degraded mode is loud.** When a source loses its push feed (watch
  stream dies during an apiserver brownout), it flips
  :meth:`EventBus.set_degraded`; the bus wakes EVERY subscriber with a
  :data:`BUS_WAKE` event so loops immediately fall back to their base
  (unstretched) period — the no-gap fallback contract.

The bus is optional everywhere: every integration point accepts
``bus=None`` and degenerates to the exact pre-event polling behavior,
which is also the poll-only fallback mode the chaos matrix runs.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional

from .common import SYSTEM_CLOCK

logger = logging.getLogger(__name__)

# -- topic vocabulary (docs/operations.md "Event-driven core") ----------------

#: Apiserver watch-stream pod changes (kinds: "added", "modified",
#: "deleted", "relist-gone"); key = "namespace/name".
POD_DELTA = "pod.delta"

#: Kubelet pod-resources List diffs (kinds: "added", "removed",
#: "owner-changed"); key = allocation hash.
ASSIGNMENT_DELTA = "assignment.delta"

#: Durable pod-record changes — the bind commit marker (kinds: "save",
#: "delete"); key = "namespace/name". Published AFTER the covering
#: commit has landed (group-commit flush path), never before.
STORE_BIND = "store.bind"

#: Bind-intent journal rows (kinds: "open", "close"); key = intent id.
STORE_INTENT = "store.intent"

#: agent_state lifecycle journal writes (kinds: "save", "delete");
#: key = state key.
STORE_STATE = "store.state"

#: Bus-health wakeup broadcast to ALL subscribers regardless of topic
#: filter (kinds: "degraded", "recovered"); key = source name. Loops
#: use it to recompute their safety-net stretch immediately.
BUS_WAKE = "bus.wake"

ALL_TOPICS = (
    POD_DELTA, ASSIGNMENT_DELTA, STORE_BIND, STORE_INTENT, STORE_STATE,
    BUS_WAKE,
)

#: Per-subscription queue cap when the subscriber doesn't choose one.
#: Sized so a full fleet-sim churn burst fits; overflow is counted,
#: not fatal (the periodic safety-net sweep repairs whatever a dropped
#: event would have pointed at).
DEFAULT_QUEUE_CAP = 512

# wait_trigger() slices its waits so a stop request is honored promptly
# even while blocked on the ready event.
_WAIT_SLICE_S = 0.1


class Event:
    """One published event. Immutable by convention; ``payload`` is a
    small dict of primitives (subscribers must treat it read-only)."""

    __slots__ = ("topic", "kind", "key", "ts", "seq", "payload")

    def __init__(self, topic: str, kind: str, key: str, ts: float,
                 seq: int, payload: dict) -> None:
        self.topic = topic
        self.kind = kind
        self.key = key
        self.ts = ts
        self.seq = seq
        self.payload = payload

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Event(seq={self.seq}, topic={self.topic!r}, "
                f"kind={self.kind!r}, key={self.key!r})")


class Subscription:
    """One subscriber's bounded mailbox (or callback) on the bus.

    Queue mode (``callback=None``): events buffer in a bounded deque;
    the consumer calls :meth:`drain` (all pending, publish order) and
    typically blocks in :meth:`wait_trigger` between passes. Overflow
    drops the OLDEST event and increments :attr:`drops`.

    Callback mode: ``callback(event)`` runs inline on the publisher's
    thread — keep it O(microseconds); exceptions are counted in
    :attr:`callback_errors` and never reach the publisher.
    """

    def __init__(self, bus: "EventBus", name: str, topics: Iterable[str],
                 cap: int, callback: Optional[Callable[[Event], None]] = None,
                 ) -> None:
        self.bus = bus
        self.name = name
        self.topics = frozenset(topics)
        self.cap = max(1, int(cap))
        self.callback = callback
        self._lock = threading.Lock()
        self._buf: deque = deque()
        self._ready = threading.Event()
        self.delivered = 0
        self.drops = 0
        self.callback_errors = 0
        self._closed = False

    # -- publisher side (called by EventBus only) -----------------------------

    def _offer(self, event: Event) -> None:
        if self._closed:
            return
        if self.callback is not None:
            try:
                self.callback(event)
                with self._lock:
                    self.delivered += 1
            except Exception:  # noqa: BLE001 - isolate from publisher
                with self._lock:
                    self.callback_errors += 1
                logger.exception("event subscriber %r callback failed on %r",
                                 self.name, event)
            return
        with self._lock:
            if len(self._buf) >= self.cap:
                self._buf.popleft()
                self.drops += 1
            self._buf.append(event)
            self.delivered += 1
        self._ready.set()

    # -- consumer side --------------------------------------------------------

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)

    def drain(self) -> List[Event]:
        """All buffered events in publish order; clears the mailbox and
        the ready flag."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
            self._ready.clear()
        return out

    def wait_trigger(self, stop: Optional[threading.Event],
                     timeout_s: float) -> str:
        """Block until an event arrives, ``stop`` is set, or
        ``timeout_s`` elapses — returns ``"event"``, ``"stop"`` or
        ``"poll"`` so loops can thread the trigger into their pass (and
        into detection-lag attribution). Pending undrained events fire
        immediately."""
        deadline = _time.monotonic() + max(0.0, timeout_s)
        while True:
            if stop is not None and stop.is_set():
                return "stop"
            if self._ready.is_set():
                return "event"
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return "poll"
            self._ready.wait(timeout=min(remaining, _WAIT_SLICE_S))

    def close(self) -> None:
        self.bus.unsubscribe(self)

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "topics": sorted(self.topics),
                "cap": self.cap,
                "pending": len(self._buf),
                "delivered": self.delivered,
                "drops": self.drops,
                "callback_errors": self.callback_errors,
                "mode": "callback" if self.callback is not None else "queue",
            }


class EventBus:
    """Topic-filtered fan-out with bounded per-subscriber queues.

    One bus per agent process, constructed by the manager before any
    subsystem and handed to sources (publish) and loops (subscribe).
    Thread-safe throughout; ``publish`` never raises and never blocks
    beyond short internal critical sections.
    """

    def __init__(self, clock=None, default_cap: int = DEFAULT_QUEUE_CAP,
                 ) -> None:
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        self._default_cap = max(1, int(default_cap))
        self._lock = threading.Lock()
        self._subs: List[Subscription] = []
        self._seq = 0
        self._degraded: set = set()
        # chaos seam: {topic: remaining count} of publishes to swallow
        # (counted in suppressed_total) — lets the event smoke prove the
        # safety-net sweep catches a dropped event.
        self._suppress: Dict[str, int] = {}
        self.published_total = 0
        self.published_by_topic: Dict[str, int] = {}
        self.suppressed_total = 0

    # -- subscription ---------------------------------------------------------

    def subscribe(self, name: str, topics: Iterable[str],
                  cap: Optional[int] = None,
                  callback: Optional[Callable[[Event], None]] = None,
                  ) -> Subscription:
        for t in topics:
            if t not in ALL_TOPICS:
                raise ValueError(f"unknown event topic {t!r}")
        sub = Subscription(self, name, topics,
                           cap if cap is not None else self._default_cap,
                           callback=callback)
        with self._lock:
            self._subs.append(sub)
        return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            sub._closed = True
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    # -- publishing -----------------------------------------------------------

    def publish(self, topic: str, kind: str = "", key: str = "",
                payload: Optional[dict] = None) -> int:
        """Fan one event out to every matching subscriber; returns the
        number of subscribers it reached. Never raises, never blocks a
        publisher on a slow consumer."""
        with self._lock:
            left = self._suppress.get(topic, 0)
            if left > 0:
                self._suppress[topic] = left - 1
                self.suppressed_total += 1
                return 0
            self._seq += 1
            event = Event(topic, kind, key, self._clock.time(), self._seq,
                          payload if payload is not None else {})
            self.published_total += 1
            self.published_by_topic[topic] = (
                self.published_by_topic.get(topic, 0) + 1
            )
            if topic == BUS_WAKE:
                targets = list(self._subs)
            else:
                targets = [s for s in self._subs if topic in s.topics]
        for sub in targets:
            sub._offer(event)
        return len(targets)

    # -- degraded mode (no-gap fallback) --------------------------------------

    def set_degraded(self, source: str, degraded: bool) -> None:
        """A push source reporting loss (or recovery) of its feed.
        Transitions broadcast :data:`BUS_WAKE` to ALL subscribers so
        every loop immediately recomputes its safety-net stretch —
        a dying watch stream must shrink sweep periods NOW, not after
        the currently armed (stretched) wait runs out."""
        with self._lock:
            was = bool(self._degraded)
            if degraded:
                changed = source not in self._degraded
                self._degraded.add(source)
            else:
                changed = source in self._degraded
                self._degraded.discard(source)
            now = bool(self._degraded)
        if changed:
            logger.warning("event bus source %r %s (degraded sources: %s)",
                           source, "degraded" if degraded else "recovered",
                           "yes" if now else "none")
        if changed and was != now:
            self.publish(BUS_WAKE,
                         kind="degraded" if now else "recovered",
                         key=source)

    def healthy(self) -> bool:
        """True while every push source is feeding the bus — the
        precondition for loops to stretch their periodic sweep."""
        with self._lock:
            return not self._degraded

    def degraded_sources(self) -> List[str]:
        with self._lock:
            return sorted(self._degraded)

    # -- chaos seam -----------------------------------------------------------

    def suppress(self, topic: str, count: int = 1) -> None:
        """Swallow the next ``count`` publishes on ``topic`` (counted in
        ``suppressed_total``). Chaos/test seam: proves the safety-net
        sweep repairs what a dropped event would have pointed at."""
        with self._lock:
            self._suppress[topic] = self._suppress.get(topic, 0) + int(count)

    # -- introspection --------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            subs = list(self._subs)
            out = {
                "published_total": self.published_total,
                "published_by_topic": dict(self.published_by_topic),
                "suppressed_total": self.suppressed_total,
                "degraded_sources": sorted(self._degraded),
                "subscribers": [],
            }
        out["subscribers"] = [s.stats() for s in subs]
        out["drops_total"] = sum(s["drops"] for s in out["subscribers"])
        return out
