"""Prometheus metrics + the agent's unified observability endpoint.

The reference had none (SURVEY.md §5.5 — klog only, RBAC granted events it
never recorded). BASELINE.md's north-star metric is Allocate() p50 latency
plus chip utilization, so both are first-class here.

One HTTP server (replacing prometheus_client's bare start_http_server)
serves five paths:

- ``/metrics``  — Prometheus scrape, names unchanged;
- ``/debug/traces`` — JSON dump of the allocation-trace ring buffer
  (tracing.py), newest first; ``?pod=<ns/name|name>`` filters,
  ``?limit=N`` caps;
- ``/debug/allocations`` — the live chip->pod binding table with
  per-pod granted vs used core percent, chip health, and last trace
  id, straight from the utilization sampler (sampler.py; 503 until a
  sampler is attached);
- ``/debug/timeline`` — the durable lifecycle event journal
  (timeline.py), filterable per entity
  (``?pod=&slice=&chip=&node=&since=&kind=&limit=``; 503 until a
  timeline is attached);
- ``/healthz`` — liveness: 200 + a small JSON status.

Per-pod labeled gauges go through a cardinality guard
(BoundedLabeledGauge): pods churn, and without an eviction bound every
pod that ever ran on the node would leave a live series in the
registry forever.

The server binds loopback by default (``--metrics-addr`` widens it) and
a port conflict raises MetricsServerError with an actionable message
instead of an unhandled traceback at agent startup.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from prometheus_client import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

logger = logging.getLogger(__name__)

_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

# Reconcile passes span ms (quiet tick) to seconds (a full node repair
# diffing four sources of truth), so they get their own wider buckets.
_RECONCILE_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0,
)

# Detection lag spans poll periods: sub-poll (origin landed mid-pass) to
# minutes (a wedged loop limping on supervisor restarts). The low end
# must resolve the <50ms target ROADMAP item 3 is judged against, the
# high end the ~0.7s..multi-period reality being replaced.
_DETECTION_LAG_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0,
)

# Prometheus label values for the async observability sinks: the fleet
# aggregator sums per-sink apiserver traffic, and "events"/"crd" read
# better on a dashboard than the internal worker-thread names.
SINK_LABELS = {"event-recorder": "events", "crd-recorder": "crd"}

DEFAULT_BIND_ADDR = "127.0.0.1"

# Registered debug routes, served as the /debug index so an operator on
# a node shell can discover the surfaces without reading source; the
# 404 body for unknown /debug/* paths carries the same list. One dict —
# a new endpoint added to the handler but not here fails the pinned
# index test, not a 3am triage session.
DEBUG_ROUTES = {
    "/debug/traces": "allocation-trace ring (?pod=&trace=&limit=)",
    "/debug/allocations": "live chip->pod table + subsystem blocks",
    "/debug/timeline": "durable lifecycle journal "
                       "(?pod=&slice=&chip=&node=&since=&kind=&limit=)",
    "/debug/goodput": "goodput ledger: per-pod state partition + "
                      "downtime by cause (?pod=&since=)",
    "/debug/latency": "critical-path observatory: bind phase breakdown "
                      "+ per-loop detection lag (?top=)",
    "/debug/profile": "continuous sampling profiler: hottest stacks + "
                      "measured overhead (?top=)",
    "/debug/requests": "request-level serving observatory: per-request "
                       "partitions, SLO classes, step breakdown "
                       "(?id=&slo=&limit=)",
}


class MetricsServerError(RuntimeError):
    """The observability HTTP endpoint could not start (e.g. the port is
    already bound). Deliberately NOT an OSError: callers must be able to
    catch exactly this and keep the agent running without the endpoint."""


# Distinct pod label sets kept per pod-labeled gauge. Sized for a busy
# node (kubelet caps ~a few hundred pods); beyond it the OLDEST-touched
# series is evicted, so live pods always win over churned ones.
DEFAULT_MAX_POD_SERIES = 512


class BoundedLabeledGauge:
    """Cardinality guard around a labeled Gauge: at most ``max_series``
    distinct label sets, evicting the least-recently-set. Each set()
    refreshes its series' recency, so only series nothing updates any
    more (churned pods) age out."""

    def __init__(self, gauge, max_series: int, evicted=None) -> None:
        self._gauge = gauge
        self._max = max(1, max_series)
        self._evicted = evicted  # optional Counter
        self._lock = threading.Lock()
        self._series: "OrderedDict[tuple, None]" = OrderedDict()

    def _key(self, labels: dict) -> tuple:
        return tuple(labels[name] for name in self._gauge._labelnames)

    def set(self, value: float, **labels) -> None:
        # Tracking AND the underlying gauge mutations happen under the
        # one lock: with them split (the original shape), a concurrent
        # writer could re-set a key between this thread's eviction pop
        # and its child remove(), silently deleting a series the tracker
        # still counted — the 10k-series fleet-churn test catches exactly
        # that. The prometheus-client child ops take their own internal
        # lock and never call back into ours, so nesting is safe.
        key = self._key(labels)
        with self._lock:
            self._series[key] = None
            self._series.move_to_end(key)
            self._gauge.labels(**labels).set(value)
            while len(self._series) > self._max:
                old, _ = self._series.popitem(last=False)
                try:
                    self._gauge.remove(*old)
                except KeyError:
                    pass
                if self._evicted is not None:
                    self._evicted.inc()

    def remove(self, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series.pop(key, None)
            try:
                self._gauge.remove(*key)
            except KeyError:
                pass

    @property
    def series_count(self) -> int:
        with self._lock:
            return len(self._series)


class AgentMetrics:
    def __init__(
        self, registry=None, max_pod_series: int = DEFAULT_MAX_POD_SERIES
    ) -> None:
        self._registry = registry if registry is not None else REGISTRY
        kw = {"registry": registry} if registry is not None else {}
        self.allocate_latency = Histogram(
            "elastic_tpu_allocate_seconds",
            "Device-plugin Allocate() handler latency",
            buckets=_BUCKETS,
            **kw,
        )
        self.prestart_latency = Histogram(
            "elastic_tpu_prestart_seconds",
            "Device-plugin PreStartContainer() handler latency "
            "(includes pod-resources Locate)",
            buckets=_BUCKETS,
            **kw,
        )
        self.chips = Gauge(
            "elastic_tpu_chips", "Physical TPU chips discovered", **kw
        )
        self.healthy_chips = Gauge(
            "elastic_tpu_chips_healthy",
            "Chips currently advertised Healthy to kubelet",
            **kw,
        )
        self.bound_allocations = Gauge(
            "elastic_tpu_bound_allocations",
            "Live pod->chip bindings recorded in storage",
            **kw,
        )
        self.bind_inflight = Gauge(
            "elastic_tpu_bind_inflight",
            "PreStartContainer binds currently in flight across both "
            "resource servers",
            **kw,
        )
        self.bind_lock_wait = Histogram(
            "elastic_tpu_bind_lock_wait_seconds",
            "Time a bind spent waiting for its per-owner bind-lock stripe "
            "(contention = sibling core/memory pair, or stripe collision)",
            buckets=_BUCKETS,
            **kw,
        )
        self.gc_reclaimed = Counter(
            "elastic_tpu_gc_reclaimed_total",
            "Allocations reclaimed by GC",
            **kw,
        )
        self.restored_links = Counter(
            "elastic_tpu_restored_links_total",
            "Virtual device nodes re-created by restore()",
            **kw,
        )
        # -- build identity & lifecycle timeline (timeline.py) -------------
        self.build_info = Gauge(
            "elastic_tpu_build_info",
            "Always 1; the labels carry the agent build identity "
            "(prometheus build-info convention) — join with "
            "elastic_tpu_agent_start_time_seconds to see which version "
            "restarted when",
            ["version"],
            **kw,
        )
        self.agent_start_time = Gauge(
            "elastic_tpu_agent_start_time_seconds",
            "Unix time this agent process started serving; a reset "
            "marks a restart even when counters alone are ambiguous",
            **kw,
        )
        self.timeline_events = Counter(
            "elastic_tpu_timeline_events_total",
            "Lifecycle events journaled into the durable timeline this "
            "boot (the journal itself persists across restarts)",
            **kw,
        )
        self.timeline_evicted = Gauge(
            "elastic_tpu_timeline_evicted_rows",
            "Durable count of timeline events the ring cap has dropped "
            "(reads the journal's own eviction counter)",
            **kw,
        )
        # -- continuous reconciler (reconciler.py) -------------------------
        self.reconcile_repairs = Counter(
            "elastic_tpu_reconcile_repairs_total",
            "Divergences repaired by the reconciler, per divergence class",
            ["kind"],
            **kw,
        )
        self.reconcile_runs = Counter(
            "elastic_tpu_reconcile_runs_total",
            "Reconciler passes completed (boot restore included)",
            **kw,
        )
        self.reconcile_duration = Histogram(
            "elastic_tpu_reconcile_duration_seconds",
            "Wall time of one full reconcile pass (store <-> kubelet <-> "
            "disk <-> live-pod diff plus repairs)",
            buckets=_RECONCILE_BUCKETS,
            **kw,
        )
        self.reconcile_last_converged = Gauge(
            "elastic_tpu_reconcile_last_converged_timestamp",
            "Unix time of the last reconcile pass that ended with the "
            "node fully converged: no failed sweeps/replays, no snapshot "
            "error, no corrupt records, nothing observed diverged or "
            "pending confirmation. A node whose value stops advancing "
            "while the fleet's does is the one to triage.",
            **kw,
        )
        self.orphan_sweep_failures = Counter(
            "elastic_tpu_orphan_sweep_failures_total",
            "Orphan link/spec deletions that failed; each is retried on "
            "the next reconcile pass instead of being dropped",
            **kw,
        )
        self.open_bind_intents = Gauge(
            "elastic_tpu_bind_intents_open",
            "Uncommitted bind intents in the write-ahead journal "
            "(sustained non-zero = a bind crashed and was not yet "
            "recovered, or a bind is wedged mid-flight)",
            **kw,
        )
        self.series_evicted = Counter(
            "elastic_tpu_metric_series_evicted_total",
            "Labeled metric series evicted by the cardinality guard",
            **kw,
        )
        # -- slice orchestration (slices/) ---------------------------------
        self.packing_span = Histogram(
            "elastic_tpu_packing_ici_span",
            "Total pairwise ICI hop count of a bind's chip set (the "
            "packing score: 0 = single chip, 1 = one adjacent pair; a "
            "rising distribution means grants are landing scattered "
            "across the mesh instead of on adjacent sub-grids)",
            buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16),
            **kw,
        )
        # Bounded like every per-pod series: slice ids are job-unique,
        # and under --reconcile-dry-run nothing prunes them, so a plain
        # labeled gauge would grow the scrape without bound under churn.
        # (slice_reforms stays a plain Counter: its series only appear
        # when a reform EXECUTES, which dry-run never does, and prune
        # removes them with the slice.)
        self.slice_members = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_slice_members",
                "Current world size (member hosts) of a multi-host "
                "slice this node hosts a member of",
                ["slice"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.slice_reforms = Counter(
            "elastic_tpu_slice_reforms_total",
            "Elastic slice reforms executed on this node (member loss "
            "or rejoin -> topology env re-emitted at the new world "
            "size, epoch bumped)",
            ["slice"],
            **kw,
        )
        # -- graceful drain lifecycle (drain.py) ---------------------------
        self.maintenance_imminent = Gauge(
            "elastic_tpu_maintenance_imminent",
            "1 while GCE announces an upcoming host maintenance event "
            "for this node (MIGRATE/TERMINATE_ON_HOST_MAINTENANCE), "
            "else 0 — set the moment detection first trips, before any "
            "drain work starts",
            **kw,
        )
        self.drain_state = Gauge(
            "elastic_tpu_drain_state",
            "Drain lifecycle state of this node: 0=active 1=cordoned "
            "2=draining 3=drained 4=reclaimed",
            **kw,
        )
        self.drains_total = Counter(
            "elastic_tpu_drains_total",
            "Drain lifecycles COMPLETED on this node, by trigger source "
            "and outcome: drained_acked = every resident acknowledged a "
            "durable checkpoint before its bindings went (the drain "
            "saved the work), drained_exited = residents merely exited "
            "(a pre-checkpoint crash looks identical from outside — "
            "nothing proves work was saved), reclaimed = the deadline "
            "fired, cancelled = the trigger cleared mid-drain",
            ["trigger", "outcome"],
            **kw,
        )
        self.drain_reclaimed_pods = Counter(
            "elastic_tpu_drain_reclaimed_pods_total",
            "Resident pods whose bindings were reclaimed because the "
            "drain deadline expired before they exited",
            **kw,
        )
        self.drain_phase_seconds = Histogram(
            "elastic_tpu_drain_phase_seconds",
            "Wall time of one drain-lifecycle phase: "
            "cordon_to_signaled (cordon until every resident carried "
            "the checkpoint signal), signaled_to_drained (residents "
            "all exited gracefully), signaled_to_reclaimed (the "
            "deadline fired instead) — a fleet whose mass sits in "
            "reclaimed instead of drained has a checkpoint problem, "
            "not a drain problem",
            ["phase"],
            buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0,
                     300.0, 600.0, 1800.0),
            **kw,
        )
        # -- migration handshake (migration.py) ----------------------------
        self.workload_checkpoint_age = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_workload_checkpoint_age_seconds",
                "Seconds since each resident pod last acknowledged a "
                "durable checkpoint (ack/<hash>.json on the alloc "
                "surface) — 'are we actually checkpointing?' from one "
                "scrape. Series exist only for pods that have EVER "
                "acked; a bound pod with no series has never "
                "checkpointed under the handshake",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        # -- goodput ledger (goodput.py) -----------------------------------
        # Ratio per pod is bounded like every per-pod series; downtime by
        # cause is a small closed vocabulary (goodput.CAUSES), exported
        # as a gauge over the ledger's replayed totals — the journal is
        # the durable source of truth, the scrape only mirrors it.
        self.goodput_ratio = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_goodput_ratio",
                "Fraction of a live pod's known lifetime the goodput "
                "ledger attributes to productive time (1.0 = nothing "
                "the agent did got in the way; see /debug/goodput for "
                "the per-interval attribution)",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.downtime_seconds = Gauge(
            "elastic_tpu_downtime_seconds_total",
            "Non-productive pod-seconds attributed to each cause by the "
            "goodput ledger's journal replay (maintenance_drain, "
            "preemption, operator_drain, qos_throttle, qos_evict, "
            "migration, migration_precopy, migration_cutover, "
            "slice_reform, agent_restart, bind_queue, unattributed) — "
            "the fleet aggregator sums this per cause",
            ["cause"],
            **kw,
        )
        self.workload_tokens_per_s = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_workload_tokens_per_second",
                "Latest tokens/s a pod's flight recorder published to "
                "its alloc-surface sidecar (flight/<hash>.json) — what "
                "the workload ACHIEVED on its grant, next to the "
                "granted/used percents. Series exist only for pods "
                "that publish, and go away with the pod's bindings.",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.workload_ttft = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_workload_ttft_seconds",
                "Median time-to-first-token a pod's flight recorder "
                "published to its alloc-surface sidecar — the serving "
                "latency the pod ACHIEVED, next to its tokens/s. Same "
                "freshness rule as tokens/s: stale summaries drop the "
                "series rather than freeze it.",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.drain_early_reclaims = Counter(
            "elastic_tpu_drain_early_reclaims_total",
            "Draining residents reclaimed BEFORE the deadline because "
            "their checkpoint ack was durable — the chips the "
            "handshake freed early",
            **kw,
        )
        self.migration_records = Counter(
            "elastic_tpu_migration_records_total",
            "MigrationRecords published (and confirmed) at the "
            "apiserver for residents whose checkpoints were verified "
            "durable before reclaim",
            **kw,
        )
        self.migrations_completed = Counter(
            "elastic_tpu_migrations_completed_total",
            "Inbound migrations VERIFIED on this node: the replacement "
            "pod restored and acked a resume at step >= the record's "
            "acked step and the current slice world size",
            **kw,
        )
        # -- dynamic re-partitioning & QoS enforcement (repartition.py) ----
        self.repartitions = Counter(
            "elastic_tpu_repartitions_total",
            "Live quota moves executed by the repartition controller: "
            "grow = a busy pod absorbed a co-located idle pod's slack, "
            "shrink = slack returned to a donor under pressure (or a "
            "peer leaving unwound the donation)",
            ["direction"],
            **kw,
        )
        self.throttles = Counter(
            "elastic_tpu_throttles_total",
            "Sustained-overcommit escalations from alarm to throttle: "
            "the pod's quota was clamped back to its base grant and the "
            "evict deadline armed",
            **kw,
        )
        self.qos_evictions = Counter(
            "elastic_tpu_qos_evictions_total",
            "Throttled pods still overcommitting at the evict deadline "
            "whose bindings were reclaimed through the reconciler's "
            "reclaimed_pod repair class",
            **kw,
        )
        # -- serving data plane (workloads/serving.py) ---------------------
        # All read through attach_serving's set_function hooks: the
        # engine's hot path never touches prometheus, and the values
        # are the engine's own monotone counters (gauges rather than
        # Counters because the source of truth lives in the engine).
        self.serving_pool_blocks = Gauge(
            "elastic_tpu_serving_pool_blocks",
            "Total KV block-pool capacity of the attached serving "
            "engine (junk block included)",
            **kw,
        )
        self.serving_pool_used = Gauge(
            "elastic_tpu_serving_pool_used_blocks",
            "KV pool blocks currently held (live request tables + "
            "registered prefixes + prefix-cache holdings)",
            **kw,
        )
        self.serving_prefix_cache_hits = Gauge(
            "elastic_tpu_serving_prefix_cache_hits",
            "Admissions that reused at least one cached prefix block "
            "(engine-lifetime count)",
            **kw,
        )
        self.serving_prefix_cache_misses = Gauge(
            "elastic_tpu_serving_prefix_cache_misses",
            "Admissions that reused nothing from the prefix cache "
            "(engine-lifetime count)",
            **kw,
        )
        self.serving_prefix_cache_evictions = Gauge(
            "elastic_tpu_serving_prefix_cache_evictions",
            "Cached blocks dropped under pool pressure or the cache "
            "cap (engine-lifetime count)",
            **kw,
        )
        self.serving_prefix_cache_hit_rate = Gauge(
            "elastic_tpu_serving_prefix_cache_hit_rate",
            "hits / (hits + misses) of the automatic prefix cache; "
            "a falling rate under steady traffic means the shared "
            "prefixes stopped fitting the pool",
            **kw,
        )
        self.serving_prefilled_tokens = Gauge(
            "elastic_tpu_serving_prefilled_tokens",
            "Prompt tokens actually run through a prefill forward "
            "(engine-lifetime; compare with "
            "elastic_tpu_serving_admitted_tokens for the cache's "
            "savings)",
            **kw,
        )
        self.serving_admitted_tokens = Gauge(
            "elastic_tpu_serving_admitted_tokens",
            "Prompt tokens admitted including cache-reused ones "
            "(engine-lifetime)",
            **kw,
        )
        # Disaggregated prefill/decode serving (SharedKVPool roles):
        # per-role backlog plus the cross-role block-adoption counter —
        # the phase-imbalance signal the repartition controller exploits.
        self.serving_role_queue_depth = Gauge(
            "elastic_tpu_serving_role_queue_depth",
            "Backlog of a serving role sharing the paged KV pool: "
            "pending chunked prefills for the prefill role, live decode "
            "requests (plus pending tails) for the decode role",
            ["role"],
            **kw,
        )
        self.serving_pool_adoptions = Gauge(
            "elastic_tpu_serving_pool_adoptions",
            "Admissions that adopted shared-pool KV blocks another role "
            "prefilled (refcounted via the prefix cache; "
            "engine-lifetime count)",
            **kw,
        )
        self.serving_pool_adopted_tokens = Gauge(
            "elastic_tpu_serving_pool_adopted_tokens",
            "Prompt tokens adopted from shared-pool blocks another role "
            "prefilled (engine-lifetime count)",
            **kw,
        )
        # Speculative decoding + MoE routing (workloads/speculative.py,
        # workloads/moe.py): the bench-only workloads joining the
        # observability plane. Absent blocks read as 0 — a plain engine
        # needs no shape change.
        self.serving_spec_drafted = Gauge(
            "elastic_tpu_serving_spec_drafted_tokens",
            "Draft-model tokens proposed by the speculative decode "
            "loop (engine-lifetime count; 0 when speculation is off)",
            **kw,
        )
        self.serving_spec_accepted = Gauge(
            "elastic_tpu_serving_spec_accepted_tokens",
            "Drafted tokens that survived target-model verification "
            "(engine-lifetime count)",
            **kw,
        )
        self.serving_spec_acceptance_rate = Gauge(
            "elastic_tpu_serving_spec_acceptance_rate",
            "accepted/drafted for the speculative decode loop — a "
            "falling rate means the draft model stopped predicting the "
            "target and the speedup is gone",
            **kw,
        )
        self.serving_moe_imbalance = Gauge(
            "elastic_tpu_serving_moe_expert_imbalance",
            "max/mean expert load of the attached MoE router's observed "
            "routing (1.0 = perfectly balanced; capacity overflow drops "
            "rise with it)",
            **kw,
        )
        self.serving_moe_dropped = Gauge(
            "elastic_tpu_serving_moe_dropped_tokens",
            "Tokens dropped by MoE expert-capacity overflow (observed-"
            "lifetime count)",
            **kw,
        )
        # -- request-level serving observatory (workloads/request_obs.py) --
        # Gauges read at scrape via attach_requests; the TTFT/TPOT/phase
        # histograms live with the other histograms below and are
        # observed at source on request finish.
        self.requests_live = Gauge(
            "elastic_tpu_requests_live",
            "Requests currently holding a slot on an attached serving "
            "engine (open partitions, pending handoffs excluded)",
            **kw,
        )
        self.requests_pending_handoff = Gauge(
            "elastic_tpu_requests_pending_handoff",
            "Disaggregated requests published by a prefill role and not "
            "yet adopted by a decode role — a growing value means the "
            "decode side stopped draining the handoff registry",
            **kw,
        )
        self.request_slo_attainment = Gauge(
            "elastic_tpu_request_slo_attainment_ratio",
            "Fraction of finished requests in an SLO class that met "
            "their target (ttft<=target, tpot<=target, batch=finished); "
            "-1 until the class has finished requests",
            ["slo"],
            **kw,
        )
        # -- self-memory accounting (ROADMAP item 1: bounded memory at
        # 10k+ pod-series must be observable OUTSIDE the scale harness)
        self.agent_rss = Gauge(
            "elastic_tpu_agent_rss_bytes",
            "Resident set size of the agent process (/proc/self/statm; "
            "0 where /proc is unavailable). Divide by the live series/"
            "pod count for the per-series memory the scale leg asserts "
            "a ceiling on.",
            **kw,
        )
        self.trace_ring_bytes = Gauge(
            "elastic_tpu_trace_ring_bytes",
            "Approximate bytes held by the in-process allocation-trace "
            "ring (sampled-extrapolated estimate; tracing.py). The ring "
            "is capacity-bounded — this gauge is how that bound stays "
            "falsifiable under churn.",
            **kw,
        )
        from .common import read_rss_bytes

        self.agent_rss.set_function(read_rss_bytes)

        def _ring_bytes() -> float:
            try:
                from .tracing import get_tracer

                return float(get_tracer().ring_bytes())
            except Exception:  # noqa: BLE001 - scrape must never break
                return 0.0

        self.trace_ring_bytes.set_function(_ring_bytes)
        # -- storage write amplification (storage/batcher.py) --------------
        # Gauges over the store's own monotone counters (set_function via
        # attach_storage): commits/writes per bind is the fleet
        # aggregator's storage-amplification numerator.
        self.storage_writes = Gauge(
            "elastic_tpu_storage_writes_total",
            "Logical write transactions requested of the checkpoint "
            "store (each was one sqlite COMMIT before group-commit "
            "batching)",
            **kw,
        )
        self.storage_commits = Gauge(
            "elastic_tpu_storage_commits_total",
            "sqlite COMMITs the checkpoint store actually paid; with "
            "--storage-batch-window > 0 one commit covers many writes "
            "(compare with elastic_tpu_storage_writes_total)",
            **kw,
        )
        self.observability_dropped = Counter(
            "elastic_tpu_observability_dropped_total",
            "CRD/event writes dropped by the bounded async queue",
            **kw,
        )
        self.nri_injections = Counter(
            "elastic_tpu_nri_injections_total",
            "Containers adjusted (devices injected) via the NRI plugin",
            **kw,
        )
        # AsyncSink introspection (async_sink.py): the observability
        # paths self-disable after consecutive failures — without these
        # the self-disabling is itself invisible until someone wonders
        # where the Events went.
        self.sink_writes = Counter(
            "elastic_tpu_sink_writes_total",
            "Apiserver write ops drained by an async observability sink "
            "(request-amplification accounting: the fleet aggregator "
            "divides this by binds to get sink traffic per bind)",
            ["sink"],
            **kw,
        )
        self.kubelet_lists = Counter(
            "elastic_tpu_kubelet_list_total",
            "Full pod-resources List RPCs issued to kubelet (locator "
            "refresh/prefetch + reconciler snapshots) — the kubelet side "
            "of per-bind request amplification",
            **kw,
        )
        self.apiserver_pod_lists = Counter(
            "elastic_tpu_apiserver_pod_list_total",
            "Full-cluster pod LISTs issued to the apiserver (slice "
            "membership refresh, TTL-cached) — the apiserver side of "
            "request amplification; every list is counted at the "
            "source, never inferred",
            **kw,
        )
        self.sink_queue_depth = Gauge(
            "elastic_tpu_sink_queue_depth",
            "Ops queued in an async observability sink",
            ["sink"],
            **kw,
        )
        self.sink_merged = Gauge(
            "elastic_tpu_sink_merged_ops",
            "Queued sink ops superseded by a newer same-key write before "
            "draining — apiserver writes the coalescing window saved",
            ["sink"],
            **kw,
        )
        self.sink_consecutive_failures = Gauge(
            "elastic_tpu_sink_consecutive_failures",
            "Consecutive write failures of an async observability sink "
            "(resets to 0 on success; the sink disables at its limit)",
            ["sink"],
            **kw,
        )
        self.sink_disabled = Gauge(
            "elastic_tpu_sink_disabled",
            "1 when an async observability sink has self-disabled after "
            "repeated failures, else 0",
            ["sink"],
            **kw,
        )
        # -- utilization & health accounting (sampler.py) -----------------
        self.chip_duty_cycle = Gauge(
            "elastic_tpu_chip_duty_cycle_percent",
            "Last sampled per-chip duty cycle (0-100)",
            ["chip"],
            **kw,
        )
        self.chip_hbm_used = Gauge(
            "elastic_tpu_chip_hbm_used_bytes",
            "Last sampled per-chip HBM usage",
            ["chip"],
            **kw,
        )
        self.pod_core_granted = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_pod_core_granted_percent",
                "Fractional tpu-core percent granted to a pod",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.pod_core_used = BoundedLabeledGauge(
            Gauge(
                "elastic_tpu_pod_core_used_percent",
                "Sampler-attributed tpu-core percent a pod is using",
                ["pod"],
                **kw,
            ),
            max_series=max_pod_series,
            evicted=self.series_evicted,
        )
        self.overcommit_detected = Counter(
            "elastic_tpu_overcommit_detected_total",
            "Sustained-overcommit episodes: a pod's attributed core usage "
            "stayed above its fractional grant",
            **kw,
        )
        # -- subsystem supervision (supervisor.py) -------------------------
        self.subsystem_up = Gauge(
            "elastic_tpu_subsystem_up",
            "1 while a supervised subsystem is running, 0 when crashed, "
            "circuit-broken or stopped",
            ["subsystem"],
            **kw,
        )
        self.subsystem_restarts = Counter(
            "elastic_tpu_subsystem_restarts_total",
            "Crash-restarts performed by the supervisor, per subsystem",
            ["subsystem"],
            **kw,
        )
        self.subsystem_crash_loops = Counter(
            "elastic_tpu_subsystem_crash_loops_total",
            "Circuit-breaker openings (subsystem crashed too often inside "
            "the sliding window and was marked failed)",
            ["subsystem"],
            **kw,
        )
        self.thread_crashes = Counter(
            "elastic_tpu_thread_crashes_total",
            "Threads that died on an uncaught exception (process-wide "
            "threading.excepthook; supervised subsystems never reach it)",
            **kw,
        )
        self.sitter_sync_age = Gauge(
            "elastic_tpu_sitter_sync_age_seconds",
            "Seconds since the pod cache last synced with the apiserver "
            "(list success or watch event); -1 before the first sync",
            **kw,
        )
        # -- critical-path latency observatory (latency.py) ----------------
        self.bind_phase_seconds = Histogram(
            "elastic_tpu_bind_phase_seconds",
            "Bind critical-path time attributed per phase (lock wait, "
            "kubelet list, storage sync, spec write, sink enqueue, "
            "sidecar; 'unattributed' is the residual vs the measured "
            "total). Bucket exemplars (trace ids) are served at "
            "/debug/latency since the text exposition cannot carry them.",
            ["phase"],
            buckets=_BUCKETS,
            **kw,
        )
        self.request_ttft = Histogram(
            "elastic_tpu_request_ttft_seconds",
            "Measured time-to-first-token per finished serving request, "
            "labeled by SLO class (fixed vocabulary ttft|tpot|batch — "
            "junk annotations coerce to batch, never mint labels). For "
            "stitched disaggregated requests this spans the handoff.",
            ["slo"],
            buckets=_BUCKETS,
            **kw,
        )
        self.request_tpot = Histogram(
            "elastic_tpu_request_tpot_seconds",
            "Mean per-token decode interval per finished serving "
            "request (>=2 tokens), labeled by SLO class",
            ["slo"],
            buckets=_BUCKETS,
            **kw,
        )
        self.request_phase_seconds = Histogram(
            "elastic_tpu_request_phase_seconds",
            "Per-request time attributed per partition phase "
            "(queued|prefill|decode|stalled|handoff); the per-request "
            "conservation residual is served at /debug/requests",
            ["phase"],
            buckets=_BUCKETS,
            **kw,
        )
        self.detection_lag = Histogram(
            "elastic_tpu_detection_lag_seconds",
            "Divergence origin -> detection/repair latency per control "
            "loop (reconciler, drain, sampler, repartition, migration, "
            "goodput) — the event-to-repair number ROADMAP item 3 moves "
            "from ~0.7s to <50ms. trigger=event|poll records what woke "
            "the observing pass (targeted event-bus pass vs the "
            "periodic safety-net sweep), so event-vs-poll lag is "
            "directly comparable per loop",
            ["loop", "stage", "trigger"],
            buckets=_DETECTION_LAG_BUCKETS,
            **kw,
        )
        self.detection_lag_clamped = Counter(
            "elastic_tpu_detection_lag_clamped_total",
            "Detection-lag observations whose origin timestamp was in "
            "the future (clock skew) and were clamped to 0 instead of "
            "exported negative",
            **kw,
        )
        # -- metrics-server self-observability -----------------------------
        self.scrape_duration = Histogram(
            "elastic_tpu_scrape_duration_seconds",
            "Wall time the observability HTTP handler spent answering a "
            "request (all paths) — the scraper's own cost, measured",
            buckets=_BUCKETS,
            **kw,
        )
        self.scrape_requests = Counter(
            "elastic_tpu_scrape_requests_total",
            "Requests answered by the observability HTTP handler; path "
            "label is the normalized route ('other' for unknown paths, "
            "so cardinality stays bounded under scanner noise)",
            ["path"],
            **kw,
        )
        # -- continuous self-profiler (profiler.py) ------------------------
        self.profiler_overhead = Gauge(
            "elastic_tpu_profiler_overhead_ratio",
            "Fraction of wall time the sampling profiler spends walking "
            "stacks (its measured self-cost; the latency smoke pins it "
            "<= 1%); 0 while disabled",
            **kw,
        )
        self.profiler_samples = Gauge(
            "elastic_tpu_profiler_samples_total",
            "Stack-walk samples taken by the continuous profiler since "
            "agent start",
            **kw,
        )
        self._sampler = None
        self._supervisor = None
        self._sitter = None
        self._timeline = None
        self._goodput = None
        self._latency = None
        self._lag = None
        self._profiler = None
        self._requests = None
        self._httpd: Optional[ThreadingHTTPServer] = None

    def attach_sampler(self, sampler) -> None:
        """Point /debug/allocations at a live UtilizationSampler. Late
        attachment is deliberate: the endpoint starts before the manager
        (cli.py) and answers 503 until the sampler exists."""
        self._sampler = sampler

    def attach_timeline(self, timeline) -> None:
        """Point /debug/timeline at the agent's lifecycle journal
        (timeline.py); the endpoint answers 503 until attached, like
        /debug/allocations. Also exports the journal's durable eviction
        counter and stamps the boot id into /healthz."""
        self._timeline = timeline

        def _evicted() -> float:
            try:
                return float(timeline.status().get("evicted_total") or 0)
            except Exception:  # noqa: BLE001 - scrape must never break
                return 0.0

        self.timeline_evicted.set_function(_evicted)

    def attach_goodput(self, ledger) -> None:
        """Point /debug/goodput at the agent's GoodputLedger
        (goodput.py); the endpoint answers 503 until attached, like
        /debug/allocations and /debug/timeline."""
        self._goodput = ledger

    def attach_serving(self, status_fn) -> None:
        """Export a live serving engine's stats()
        (workloads/serving.py) as the elastic_tpu_serving_* gauges.
        ``status_fn`` is read at scrape time via set_function — a
        broken engine reads as 0s, never a failed scrape."""

        def read(*path):
            def _read() -> float:
                try:
                    node = status_fn() or {}
                    for key in path[:-1]:
                        node = node.get(key) or {}
                    value = node.get(path[-1])
                    return float(value) if value is not None else 0.0
                except Exception:  # noqa: BLE001 - scrape never breaks
                    return 0.0
            return _read

        self.serving_pool_blocks.set_function(read("pool_blocks"))
        self.serving_pool_used.set_function(read("used_blocks"))
        self.serving_prefilled_tokens.set_function(
            read("prefilled_tokens_total")
        )
        self.serving_admitted_tokens.set_function(
            read("admitted_tokens_total")
        )
        self.serving_prefix_cache_hits.set_function(
            read("prefix_cache", "hits")
        )
        self.serving_prefix_cache_misses.set_function(
            read("prefix_cache", "misses")
        )
        self.serving_prefix_cache_evictions.set_function(
            read("prefix_cache", "evictions")
        )
        self.serving_prefix_cache_hit_rate.set_function(
            read("prefix_cache", "hit_rate")
        )
        # Disaggregated roles (serving.disaggregated_status): absent
        # blocks read as 0, so a unified engine's status needs no shape
        # change and the role series stay flat until roles exist.
        for role in ("prefill", "decode"):
            self.serving_role_queue_depth.labels(role=role).set_function(
                read("roles", role, "queue_depth")
            )
        self.serving_pool_adoptions.set_function(
            read("shared_pool", "adoptions")
        )
        self.serving_pool_adopted_tokens.set_function(
            read("shared_pool", "adopted_tokens")
        )
        # Speculative + MoE blocks appear in stats() only when the
        # engine runs those workloads; read() yields 0 otherwise.
        self.serving_spec_drafted.set_function(
            read("speculative", "drafted_tokens")
        )
        self.serving_spec_accepted.set_function(
            read("speculative", "accepted_tokens")
        )
        self.serving_spec_acceptance_rate.set_function(
            read("speculative", "acceptance_rate")
        )
        self.serving_moe_imbalance.set_function(
            read("moe", "imbalance")
        )
        self.serving_moe_dropped.set_function(
            read("moe", "dropped_tokens")
        )

    def attach_requests(self, observatory) -> None:
        """Wire a RequestObservatory (workloads/request_obs.py) both
        ways: the observatory observes its TTFT/TPOT/phase histograms
        at source through us, and /debug/requests plus the request
        gauges read its ledgers at scrape. 503 until attached, like
        the other late-bound debug surfaces."""
        self._requests = observatory
        observatory.bind_metrics(self)

        self.requests_live.set_function(
            lambda: float(observatory.live_count)
        )
        self.requests_pending_handoff.set_function(
            lambda: float(observatory.pending_handoff_count)
        )

        def attain(slo):
            def _read() -> float:
                try:
                    v = observatory.attainment(slo)
                    return -1.0 if v is None else float(v)
                except Exception:  # noqa: BLE001 - scrape never breaks
                    return -1.0
            return _read

        from .workloads.request_obs import SLO_CLASSES

        for slo in SLO_CLASSES:
            self.request_slo_attainment.labels(slo=slo).set_function(
                attain(slo)
            )

    def attach_storage(self, storage) -> None:
        """Export the checkpoint store's write/commit counters (group-
        commit amplification accounting) via set_function reads — the
        store's hot path never touches prometheus."""

        def read(key):
            def _read() -> float:
                try:
                    return float(storage.write_stats().get(key) or 0)
                except Exception:  # noqa: BLE001 - scrape never breaks
                    return 0.0
            return _read

        self.storage_writes.set_function(read("writes_total"))
        self.storage_commits.set_function(read("commits_total"))

    def attach_supervisor(self, supervisor) -> None:
        """Fold supervisor state into /healthz: any circuit-broken
        CRITICAL subsystem flips the endpoint to 503 so the DaemonSet
        liveness probe restarts the pod; degraded subsystems ride along
        in the JSON without failing the probe."""
        self._supervisor = supervisor

    def attach_sitter(self, sitter) -> None:
        """Expose pod-cache staleness: a long apiserver outage shows up
        as a growing sync age instead of silent cache rot."""
        self._sitter = sitter
        self.sitter_sync_age.set_function(
            lambda: (
                -1.0 if sitter.sync_age_s() is None else sitter.sync_age_s()
            )
        )

    def attach_latency(self, observatory, lag_tracker=None) -> None:
        """Point /debug/latency at the bind-phase observatory and (when
        given) the detection-lag tracker; 503 until attached, like the
        other late-bound debug surfaces."""
        self._latency = observatory
        if lag_tracker is not None:
            self._lag = lag_tracker

    def attach_profiler(self, profiler) -> None:
        """Point /debug/profile at the continuous sampling profiler and
        export its self-measured cost — the profiler's <=1% overhead
        contract is only honest if the overhead itself is scraped."""
        self._profiler = profiler

        def _overhead() -> float:
            try:
                return float(profiler.overhead_ratio())
            except Exception:  # noqa: BLE001 - scrape never breaks
                return 0.0

        self.profiler_overhead.set_function(_overhead)
        self.profiler_samples.set_function(
            lambda: float(profiler.samples_total)
        )

    def register_sink(self, sink) -> None:
        """Export a live AsyncSink's internals as gauges. Uses
        set_function so the scrape always reads current state — no
        update calls sprinkled through the sink's hot path."""
        name = sink.name
        self.sink_queue_depth.labels(sink=name).set_function(
            lambda: sink.queue_depth
        )
        if hasattr(sink, "merged"):
            self.sink_merged.labels(sink=name).set_function(
                lambda: float(sink.merged)
            )
        self.sink_consecutive_failures.labels(sink=name).set_function(
            lambda: sink.consecutive_failures
        )
        self.sink_disabled.labels(sink=name).set_function(
            lambda: float(sink.disabled)
        )
        # Real write traffic, counted at the source (async_sink invokes
        # on_write once per successfully drained op): the fleet
        # aggregator sums these instead of inferring apiserver load.
        if hasattr(sink, "on_write"):
            sink.on_write = self.sink_writes.labels(
                sink=SINK_LABELS.get(name, name)
            ).inc

    def observe_allocate(self, seconds: float) -> None:
        self.allocate_latency.observe(seconds)

    def observe_prestart(self, seconds: float) -> None:
        self.prestart_latency.observe(seconds)

    # -- the unified HTTP endpoint --------------------------------------------

    def serve(
        self,
        port: int,
        addr: str = DEFAULT_BIND_ADDR,
        tracer=None,
    ) -> ThreadingHTTPServer:
        """Start the observability endpoint on ``addr:port`` (port 0 =
        ephemeral, for tests; the bound server is returned and kept on
        self). ``tracer`` defaults to the process-wide tracing ring."""
        if tracer is None:
            from .tracing import get_tracer

            tracer = get_tracer()
        registry = self._registry
        agent_metrics = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 - silence stderr
                logger.debug("metrics http: " + fmt, *args)

            def _reply(self, code, content_type, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, payload, code=200) -> None:
                self._reply(
                    code, "application/json",
                    json.dumps(payload).encode(),
                )

            def _require_loopback(self) -> bool:
                # Debug dumps stay node-local even when the bind is
                # widened for Prometheus (--metrics-addr 0.0.0.0 on
                # hostNetwork): they name every pod/chip/device on the
                # node — not for cross-tenant eyes. Reach them via the
                # node shell or kubectl port-forward.
                parsed = urlparse(self.path)
                if self.client_address[0] in (
                    "127.0.0.1", "::1", "::ffff:127.0.0.1",
                ):
                    return True
                self._reply_json(
                    {"error": f"{parsed.path} is served to "
                              "loopback clients only"},
                    code=403,
                )
                return False

            def do_GET(self):  # noqa: N802
                # Self-observability: every request — scrape, debug
                # dump, probe, scanner noise — is timed and counted.
                # The path label is normalized to the known routes
                # ('other' for everything else) so a port scanner
                # cannot mint unbounded label values.
                t0 = time.monotonic()
                parsed = urlparse(self.path)
                try:
                    self._route(parsed)
                finally:
                    try:
                        norm = parsed.path.rstrip("/") or "/"
                        if norm not in (
                            "/metrics", "/healthz", "/debug",
                        ) and norm not in DEBUG_ROUTES:
                            norm = "other"
                        agent_metrics.scrape_requests.labels(
                            path=norm
                        ).inc()
                        agent_metrics.scrape_duration.observe(
                            time.monotonic() - t0
                        )
                    except Exception:  # noqa: BLE001 - never kill a reply
                        pass

            def _route(self, parsed) -> None:
                try:
                    if parsed.path == "/metrics":
                        self._reply(
                            200, CONTENT_TYPE_LATEST,
                            generate_latest(registry),
                        )
                    elif parsed.path == "/debug/traces":
                        if not self._require_loopback():
                            return
                        q = parse_qs(parsed.query)
                        pod = q.get("pod", [None])[0]
                        trace_id = q.get("trace", [None])[0]
                        limit = None
                        if q.get("limit"):
                            try:
                                limit = max(0, int(q["limit"][0]))
                            except ValueError:
                                self._reply_json(
                                    {"error": "limit must be an integer"},
                                    code=400,
                                )
                                return
                        self._reply_json({
                            "traces": tracer.dump(
                                pod=pod, limit=limit, trace_id=trace_id
                            ),
                            "completed_total": tracer.completed,
                            "capacity": tracer.capacity,
                        })
                    elif parsed.path == "/debug/timeline":
                        if not self._require_loopback():
                            return
                        timeline = agent_metrics._timeline
                        if timeline is None:
                            self._reply_json(
                                {"error": "lifecycle timeline not "
                                          "attached (agent starting)"},
                                code=503,
                            )
                            return
                        q = parse_qs(parsed.query)
                        params = {}
                        for name, key in (
                            ("pod", "pod"), ("slice", "slice_id"),
                            ("node", "node"), ("trace", "trace"),
                        ):
                            if q.get(name):
                                params[key] = q[name][0]
                        for name, key, cast in (
                            ("chip", "chip", int),
                            ("since", "since", float),
                            ("limit", "limit", int),
                        ):
                            if q.get(name):
                                try:
                                    params[key] = cast(q[name][0])
                                except ValueError:
                                    self._reply_json(
                                        {"error": f"{name} must be "
                                                  "numeric"},
                                        code=400,
                                    )
                                    return
                        if q.get("kind"):
                            params["kinds"] = q["kind"]
                        payload = timeline.status()
                        payload["events"] = timeline.events(**params)
                        self._reply_json(payload)
                    elif parsed.path == "/debug/goodput":
                        if not self._require_loopback():
                            return
                        ledger = agent_metrics._goodput
                        if ledger is None:
                            self._reply_json(
                                {"error": "goodput ledger not attached "
                                          "(agent starting)"},
                                code=503,
                            )
                            return
                        q = parse_qs(parsed.query)
                        pod = q.get("pod", [None])[0]
                        since = None
                        if q.get("since"):
                            try:
                                since = float(q["since"][0])
                            except ValueError:
                                self._reply_json(
                                    {"error": "since must be numeric"},
                                    code=400,
                                )
                                return
                        self._reply_json(
                            ledger.status(pod=pod, since=since)
                        )
                    elif parsed.path == "/debug/latency":
                        if not self._require_loopback():
                            return
                        latency = agent_metrics._latency
                        if latency is None:
                            self._reply_json(
                                {"error": "latency observatory not "
                                          "attached (agent starting)"},
                                code=503,
                            )
                            return
                        q = parse_qs(parsed.query)
                        top = None
                        if q.get("top"):
                            try:
                                top = max(1, int(q["top"][0]))
                            except ValueError:
                                self._reply_json(
                                    {"error": "top must be an integer"},
                                    code=400,
                                )
                                return
                        lag = agent_metrics._lag
                        self._reply_json({
                            "bind": latency.status(top=top),
                            "detection_lag": (
                                lag.status() if lag is not None else None
                            ),
                            "slow_span_ms": round(
                                tracer.slow_span_s * 1000, 3
                            ),
                        })
                    elif parsed.path == "/debug/profile":
                        if not self._require_loopback():
                            return
                        profiler = agent_metrics._profiler
                        if profiler is None:
                            self._reply_json(
                                {"error": "profiler not attached "
                                          "(agent starting)"},
                                code=503,
                            )
                            return
                        q = parse_qs(parsed.query)
                        top = 30
                        if q.get("top"):
                            try:
                                top = max(1, int(q["top"][0]))
                            except ValueError:
                                self._reply_json(
                                    {"error": "top must be an integer"},
                                    code=400,
                                )
                                return
                        self._reply_json(profiler.status(top=top))
                    elif parsed.path == "/debug/requests":
                        if not self._require_loopback():
                            return
                        observatory = agent_metrics._requests
                        if observatory is None:
                            self._reply_json(
                                {"error": "request observatory not "
                                          "attached (agent starting)"},
                                code=503,
                            )
                            return
                        q = parse_qs(parsed.query)
                        rid = None
                        limit = None
                        for name in ("id", "limit"):
                            if q.get(name):
                                try:
                                    val = max(0, int(q[name][0]))
                                except ValueError:
                                    self._reply_json(
                                        {"error": f"{name} must be "
                                                  "an integer"},
                                        code=400,
                                    )
                                    return
                                if name == "id":
                                    rid = val
                                else:
                                    limit = val
                        slo = q.get("slo", [None])[0]
                        if slo is not None:
                            from .workloads.request_obs import (
                                SLO_CLASSES,
                            )
                            if slo not in SLO_CLASSES:
                                self._reply_json(
                                    {"error": "slo must be one of "
                                              + "|".join(SLO_CLASSES)},
                                    code=400,
                                )
                                return
                        self._reply_json(observatory.status(
                            request_id=rid, slo=slo, limit=limit,
                        ))
                    elif parsed.path in ("/debug", "/debug/"):
                        if not self._require_loopback():
                            return
                        self._reply_json({"routes": DEBUG_ROUTES})
                    elif parsed.path == "/debug/allocations":
                        if not self._require_loopback():
                            return
                        sampler = agent_metrics._sampler
                        if sampler is None:
                            self._reply_json(
                                {"error": "utilization sampler not "
                                          "attached (agent starting, or "
                                          "sampling disabled)"},
                                code=503,
                            )
                            return
                        self._reply_json(sampler.allocations_snapshot())
                    elif parsed.path == "/healthz":
                        status = {
                            "status": "ok",
                            "traces_completed": tracer.completed,
                        }
                        code = 200
                        if agent_metrics._sampler is not None:
                            status["sampler_samples"] = (
                                agent_metrics._sampler.samples_total
                            )
                        sitter = agent_metrics._sitter
                        if sitter is not None:
                            status["sitter_sync_age_s"] = sitter.sync_age_s()
                        if agent_metrics._timeline is not None:
                            # Boot identity: restarts must be visible
                            # from the probe side too, not only inside
                            # journal histories.
                            status["boot_id"] = (
                                agent_metrics._timeline.boot_id
                            )
                        sup = agent_metrics._supervisor
                        if sup is not None:
                            snap = sup.healthz()
                            status["subsystems"] = snap["subsystems"]
                            status["degraded"] = snap["degraded"]
                            status["critical_failed"] = snap["critical_failed"]
                            if snap["critical_failed"]:
                                # the liveness-probe contract: a 503 here
                                # makes kubelet restart the whole pod —
                                # the only recovery once a critical loop
                                # is circuit-broken
                                status["status"] = "failing"
                                code = 503
                            elif snap["degraded"]:
                                status["status"] = "degraded"
                        self._reply_json(status, code=code)
                    elif parsed.path.startswith("/debug/"):
                        # Unknown debug paths answer an explicit JSON
                        # 404 naming the real routes instead of the
                        # generic catch-all — a typo'd surface should
                        # self-correct from its own error body.
                        self._reply_json(
                            {"error": f"no such debug path {parsed.path}",
                             "debug_routes": sorted(DEBUG_ROUTES)},
                            code=404,
                        )
                    else:
                        self._reply_json(
                            {"error": f"no such path {parsed.path}",
                             "paths": ["/metrics", "/debug",
                                       *sorted(DEBUG_ROUTES),
                                       "/healthz"]},
                            code=404,
                        )
                except BrokenPipeError:  # client went away mid-reply
                    pass
                except Exception:  # noqa: BLE001 - never kill the server
                    logger.exception("metrics http handler failed")
                    try:
                        self._reply_json(
                            {"error": "internal error"}, code=500
                        )
                    except Exception:  # noqa: BLE001
                        pass

        try:
            httpd = ThreadingHTTPServer((addr, port), Handler)
        except OSError as e:
            raise MetricsServerError(
                f"observability endpoint cannot bind {addr}:{port}: {e} "
                "(is another agent or exporter already listening? pass a "
                "different --metrics-port, or 0 to disable)"
            ) from e
        httpd.daemon_threads = True
        threading.Thread(
            target=httpd.serve_forever, daemon=True, name="metrics-http"
        ).start()
        self._httpd = httpd
        logger.info(
            "observability endpoint on %s:%d (/metrics /debug/traces "
            "/debug/allocations /debug/timeline /healthz)",
            addr, httpd.server_address[1],
        )
        return httpd

    def serve_with_retry(
        self,
        port: int,
        addr: str = DEFAULT_BIND_ADDR,
        retry_s: float = 15.0,
    ) -> Optional[ThreadingHTTPServer]:
        """serve(), but a bind failure starts a background retry loop
        instead of giving up. With the DaemonSet liveness probe hitting
        /healthz, permanently running without the endpoint would turn a
        transient port conflict (typically the previous agent pod still
        draining on hostNetwork) into an unfixable probe-restart loop;
        retrying binds as soon as the old holder releases the port.
        Returns the server, or None while the port is still contended."""
        try:
            return self.serve(port, addr=addr)
        except MetricsServerError as e:
            logger.error(
                "%s — agent continues, retrying the bind every %.0fs "
                "(liveness probes fail until it succeeds)", e, retry_s,
            )

        def _retry() -> None:
            while self._httpd is None:
                time.sleep(retry_s)
                try:
                    self.serve(port, addr=addr)
                    logger.info(
                        "observability endpoint recovered on %s:%d",
                        addr, port,
                    )
                    return
                except MetricsServerError:
                    continue

        threading.Thread(
            target=_retry, daemon=True, name="metrics-retry"
        ).start()
        return None

    @property
    def http_port(self) -> Optional[int]:
        """The bound port of the observability endpoint (None until
        serve(); useful with port 0)."""
        if self._httpd is None:
            return None
        return self._httpd.server_address[1]

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# -- exposition-format lint (promtool-style, in-repo, no new dependency) ------
#
# `promtool check metrics` is the tool operators actually run against a
# scrape; CI cannot assume it exists in the image, so this is the same
# rule set as plain functions: every family with samples has HELP and
# TYPE (TYPE before the first sample), no duplicate series, sample
# lines grammatical, label values escaped per the exposition format
# (only \\ , \" and \n escapes are legal inside a quoted value).

_EXPO_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_EXPO_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_EXPO_VALUE_RE = re.compile(
    r"^(?:[+-]?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?)"
    r"|NaN|[+-]?Inf)$"
)
_EXPO_TYPES = frozenset(
    {"counter", "gauge", "histogram", "summary", "untyped", "info"}
)
# A sample's family: its name minus the well-known generated suffixes
# (prometheus_client emits `x_total`/`x_created` under family `x`, and
# histogram `x_bucket`/`x_sum`/`x_count` under `x`).
_EXPO_SUFFIXES = ("_bucket", "_sum", "_count", "_total", "_created",
                  "_gsum", "_gcount", "_info")


def _expo_parse_labels(raw: str):
    """Parse the `{...}` body of a sample line; returns (labels dict,
    error string or None). Hand-rolled so ESCAPING mistakes surface as
    lint problems instead of silently mis-parsing."""
    labels = {}
    i, n = 0, len(raw)
    while i < n:
        j = i
        while j < n and raw[j] not in "=":
            j += 1
        name = raw[i:j].strip()
        if not _EXPO_LABEL_NAME_RE.match(name):
            return labels, f"bad label name {name!r}"
        if j >= n or raw[j] != "=":
            return labels, f"label {name!r} missing '='"
        j += 1
        if j >= n or raw[j] != '"':
            return labels, f"label {name!r} value not quoted"
        j += 1
        value = []
        while j < n:
            ch = raw[j]
            if ch == "\\":
                if j + 1 >= n or raw[j + 1] not in ('\\', '"', 'n'):
                    return labels, (
                        f"label {name!r}: illegal escape "
                        f"\\{raw[j + 1] if j + 1 < n else ''!s}"
                    )
                value.append(raw[j:j + 2])
                j += 2
                continue
            if ch == '"':
                break
            value.append(ch)
            j += 1
        else:
            return labels, f"label {name!r} value unterminated"
        if name in labels:
            return labels, f"label {name!r} repeated"
        labels[name] = "".join(value)
        j += 1  # past closing quote
        if j < n:
            if raw[j] != ",":
                return labels, f"junk after label {name!r}: {raw[j:]!r}"
            j += 1
        i = j
    return labels, None


def _expo_family_of(sample_name: str, families) -> "Optional[str]":
    if sample_name in families:
        return sample_name
    for suffix in _EXPO_SUFFIXES:
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            if base in families:
                return base
    return None


def lint_exposition(text: str) -> list:
    """Lint a /metrics payload; returns problems (empty = conformant).
    Consumed by the exposition-conformance test and usable against any
    scrape (`lint_exposition(urlopen(...).read().decode())`)."""
    problems = []
    helped, typed = set(), set()
    families_with_samples = {}
    seen_series = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # plain comment: legal
            name = parts[2]
            if not _EXPO_NAME_RE.match(name):
                problems.append(
                    f"line {lineno}: bad metric name {name!r} in "
                    f"{parts[1]}"
                )
                continue
            if parts[1] == "HELP":
                if name in helped:
                    problems.append(
                        f"line {lineno}: duplicate HELP for {name}"
                    )
                helped.add(name)
            else:
                if name in typed:
                    problems.append(
                        f"line {lineno}: duplicate TYPE for {name}"
                    )
                if len(parts) < 4 or parts[3] not in _EXPO_TYPES:
                    problems.append(
                        f"line {lineno}: TYPE {name} "
                        f"{parts[3] if len(parts) > 3 else ''!r} is not "
                        "a known type"
                    )
                if name in families_with_samples:
                    problems.append(
                        f"line {lineno}: TYPE for {name} after its "
                        "samples"
                    )
                typed.add(name)
            continue
        # sample line: name[{labels}] value [timestamp]
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                problems.append(f"line {lineno}: unbalanced braces")
                continue
            name = line[:brace]
            labels, err = _expo_parse_labels(line[brace + 1:close])
            if err:
                problems.append(f"line {lineno}: {err}")
            rest = line[close + 1:].strip()
        else:
            fields = line.split(None, 1)
            name = fields[0]
            labels = {}
            rest = fields[1].strip() if len(fields) > 1 else ""
        if not _EXPO_NAME_RE.match(name):
            problems.append(f"line {lineno}: bad sample name {name!r}")
            continue
        value_fields = rest.split()
        if not value_fields or not _EXPO_VALUE_RE.match(value_fields[0]):
            problems.append(
                f"line {lineno}: {name} sample value "
                f"{value_fields[0] if value_fields else ''!r} is not a "
                "number"
            )
        if len(value_fields) > 2:
            problems.append(
                f"line {lineno}: {name} trailing junk after value"
            )
        series = (name, tuple(sorted(labels.items())))
        if series in seen_series:
            problems.append(
                f"line {lineno}: duplicate series {name}"
                f"{dict(labels) if labels else ''}"
            )
        seen_series.add(series)
        family = _expo_family_of(name, typed | helped)
        if family is None:
            # a sample with neither HELP nor TYPE anywhere: flag once
            families_with_samples.setdefault(name, lineno)
            problems.append(
                f"line {lineno}: sample {name} has no HELP/TYPE family"
            )
        else:
            families_with_samples.setdefault(family, lineno)
    for family, lineno in sorted(families_with_samples.items()):
        if family in typed and family not in helped:
            problems.append(f"family {family} (line {lineno}) has no HELP")
        if family in helped and family not in typed:
            problems.append(f"family {family} (line {lineno}) has no TYPE")
    return problems
