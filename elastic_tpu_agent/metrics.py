"""Prometheus metrics endpoint.

The reference had none (SURVEY.md §5.5 — klog only, RBAC granted events it
never recorded). BASELINE.md's north-star metric is Allocate() p50 latency
plus chip utilization, so both are first-class here.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import (
    Counter,
    Gauge,
    Histogram,
    start_http_server,
)

_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)


class AgentMetrics:
    def __init__(self, registry=None) -> None:
        kw = {"registry": registry} if registry is not None else {}
        self.allocate_latency = Histogram(
            "elastic_tpu_allocate_seconds",
            "Device-plugin Allocate() handler latency",
            buckets=_BUCKETS,
            **kw,
        )
        self.prestart_latency = Histogram(
            "elastic_tpu_prestart_seconds",
            "Device-plugin PreStartContainer() handler latency "
            "(includes pod-resources Locate)",
            buckets=_BUCKETS,
            **kw,
        )
        self.chips = Gauge(
            "elastic_tpu_chips", "Physical TPU chips discovered", **kw
        )
        self.healthy_chips = Gauge(
            "elastic_tpu_chips_healthy",
            "Chips currently advertised Healthy to kubelet",
            **kw,
        )
        self.bound_allocations = Gauge(
            "elastic_tpu_bound_allocations",
            "Live pod->chip bindings recorded in storage",
            **kw,
        )
        self.gc_reclaimed = Counter(
            "elastic_tpu_gc_reclaimed_total",
            "Allocations reclaimed by GC",
            **kw,
        )
        self.restored_links = Counter(
            "elastic_tpu_restored_links_total",
            "Virtual device nodes re-created by restore()",
            **kw,
        )
        self.observability_dropped = Counter(
            "elastic_tpu_observability_dropped_total",
            "CRD/event writes dropped by the bounded async queue",
            **kw,
        )
        self.nri_injections = Counter(
            "elastic_tpu_nri_injections_total",
            "Containers adjusted (devices injected) via the NRI plugin",
            **kw,
        )

    def observe_allocate(self, seconds: float) -> None:
        self.allocate_latency.observe(seconds)

    def observe_prestart(self, seconds: float) -> None:
        self.prestart_latency.observe(seconds)

    def serve(self, port: int) -> None:
        start_http_server(port)
