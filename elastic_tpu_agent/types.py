"""Core value types: Device identity and pod binding records.

Capability parity with the reference's ``pkg/types/device.go`` and
``pkg/types/pod.go`` (see SURVEY.md §1 L7): a Device is a *sorted* set of
fake-device IDs plus the first 8 hex chars of sha256 over ``":".join(ids)``.
That hash is the join key of the whole system — it names the virtual device
nodes under /dev, the env var handed to the container, and what the OCI hook
resolves back to physical chip indexes.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple


def device_hash(ids: Iterable[str]) -> str:
    """First 8 hex chars of sha256 over the sorted, ':'-joined ID set.

    Stable across processes and restarts; collision-safe enough for the
    per-node population of live allocations (reference: device.go:49-54).
    """
    joined = ":".join(sorted(ids))
    return hashlib.sha256(joined.encode("utf-8")).hexdigest()[:8]


@dataclass(frozen=True, eq=False)
class Device:
    """An allocation identity: a sorted fake-device-ID set + resource name.

    ``ids`` are the kubelet-visible fake device IDs (e.g. 100 per chip for
    tpu-core, one per MiB for tpu-memory). Two Devices are equal iff their
    sorted ID sets are equal; the resource name is carried metadata and is
    excluded from __eq__/__hash__.
    """

    ids: Tuple[str, ...]
    resource: str = ""

    def __init__(self, ids: Iterable[str], resource: str = "") -> None:
        object.__setattr__(self, "ids", tuple(sorted(ids)))
        object.__setattr__(self, "resource", resource)

    @property
    def hash(self) -> str:
        return device_hash(self.ids)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Device) and self.ids == other.ids

    def __hash__(self) -> int:
        return hash(self.ids)

    def equals(self, other: "Device") -> bool:
        return self.ids == other.ids

    def __len__(self) -> int:
        return len(self.ids)

    def to_dict(self) -> dict:
        return {"ids": list(self.ids), "resource": self.resource}

    @classmethod
    def from_dict(cls, d: dict) -> "Device":
        return cls(d.get("ids", []), d.get("resource", ""))


@dataclass(frozen=True)
class PodContainer:
    """Addresses one container of one pod (reference: pod.go:10-16)."""

    namespace: str
    name: str
    container: str

    @property
    def pod_key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class AllocationRecord:
    """Extra per-container binding state beyond the Device identity.

    The reference persisted only the Device; its GC then had to *guess* how
    many /dev links PreStartContainer created, which leaks links for
    cross-chip core splits (SURVEY.md §7 "known defects"). We persist the
    exact created node IDs and the physical chip indexes so GC and Restore
    are exact.
    """

    device: Device
    chip_indexes: List[int] = field(default_factory=list)
    created_node_ids: List[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "device": self.device.to_dict(),
            "chip_indexes": list(self.chip_indexes),
            "created_node_ids": list(self.created_node_ids),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "AllocationRecord":
        return cls(
            device=Device.from_dict(d["device"]),
            chip_indexes=list(d.get("chip_indexes", [])),
            created_node_ids=list(d.get("created_node_ids", [])),
        )


@dataclass
class PodInfo:
    """Pod binding record: namespace/name + container -> resource -> record.

    JSON-(de)serializable; this is the value stored in the checkpoint store
    (reference: pod.go:24-62 persisted as JSON in BoltDB). Unlike the
    reference's flat container->Device map, allocations are keyed by
    container THEN resource: a container normally holds both a tpu-core and
    a tpu-memory binding, and the reference's flat map let one overwrite
    the other, leaking the loser's /dev links at GC (SURVEY.md §7 defects).
    """

    namespace: str
    name: str
    # container name -> resource name -> record
    allocations: Dict[str, Dict[str, AllocationRecord]] = field(
        default_factory=dict
    )

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def set_allocation(self, container: str, rec: AllocationRecord) -> None:
        self.allocations.setdefault(container, {})[rec.device.resource] = rec

    def device_of(self, container: str, resource: str) -> Optional[Device]:
        rec = self.allocations.get(container, {}).get(resource)
        return rec.device if rec else None

    def records(self) -> Iterator["AllocationRecord"]:
        for by_resource in self.allocations.values():
            yield from by_resource.values()

    def containers(self) -> Iterator[str]:
        return iter(self.allocations)

    def to_json(self) -> str:
        return json.dumps(
            {
                "namespace": self.namespace,
                "name": self.name,
                "allocations": {
                    c: {r: rec.to_dict() for r, rec in by_res.items()}
                    for c, by_res in self.allocations.items()
                },
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, raw: str) -> "PodInfo":
        d = json.loads(raw)
        return cls(
            namespace=d["namespace"],
            name=d["name"],
            allocations={
                c: {
                    r: AllocationRecord.from_dict(rd)
                    for r, rd in by_res.items()
                }
                for c, by_res in d.get("allocations", {}).items()
            },
        )


def parse_pod_key(key: str) -> Tuple[str, str]:
    """Split "namespace/name" into its parts."""
    namespace, _, name = key.partition("/")
    return namespace, name
