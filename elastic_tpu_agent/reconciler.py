"""Continuous reconciler: the restore path, promoted to a subsystem.

``TPUManager._restore()`` used to converge node-local bind state exactly
once, at boot. Anything that diverged *after* startup — a kubelet
restart handing a container different device ids, a pod force-deleted
while the agent was down longer than the sitter remembers, an operator
delete that failed and was warn-logged into oblivion, an agent crash in
the middle of a bind — stayed diverged until the next agent restart
happened to fix it. Funky and Arax (PAPERS.md) both argue the same
point from the FPGA/accelerator-virtualization side: host-local mapping
state must be treated as a transactionally recoverable log, not as
best-effort side effects.

This module is that log's recovery executor, run continuously:

- every bind is now a journaled transaction (``Storage.journal_intent``
  written before the first side effect, committed inside the bind
  stripe after the checkpoint — plugins/tpushare.py). An intent that
  survives is, by construction, a bind a crash cut short: the
  reconciler **rolls it back** (delete the planned links, unlink the
  spec, restore sibling specs) and, when kubelet's pod-resources view
  proves the assignment still stands, **replays** the whole bind.
- each pass diffs four sources of truth — the checkpoint store, the
  kubelet pod-resources snapshot (with device ids), the on-disk
  symlinks + alloc-spec files, and the live pod set — and repairs each
  divergence class, counted per class in
  ``elastic_tpu_reconcile_repairs_total{kind=...}``.
- repairs that act on *observed absence* (an unbound kubelet
  assignment, a mid-flight-looking intent, a drifted device-id set)
  are confirmed across two consecutive passes before acting, so a
  reconciler tick can never mistake an in-flight bind for debris; the
  boot pass runs before the device-plugin servers exist and therefore
  acts immediately. Orphan link/spec sweeps don't need confirmation:
  artifacts are snapshotted first, then the journal, then the store —
  and because an intent row is removed only after its record is
  checkpointed, every pre-snapshot artifact of a healthy bind is named
  by the journal read or the (later) records read, never by neither.
- ``dry_run`` turns periodic passes into observers: divergences are
  detected, counted and surfaced on ``/debug/allocations`` and the
  doctor bundle, but nothing is repaired (the boot pass still repairs
  — an agent must converge before it serves binds; the cautious
  operator's workflow is documented in docs/operations.md).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional

from . import events as bus_events
from . import faults
from .storage.store import StorageError
from .tracing import get_tracer
from .types import Device, PodContainer, parse_pod_key

logger = logging.getLogger(__name__)

# Event-triggered pass pacing: a burst of bus events (one churny bind
# emits several store notifications) coalesces behind one short
# debounce, and event-triggered passes never start closer together than
# the min interval — a fleet-wide churn storm degrades to ~20 targeted
# passes/s/node, not one pass per event.
EVENT_DEBOUNCE_S = 0.01
EVENT_MIN_INTERVAL_S = 0.05

DEFAULT_PERIOD_S = 30.0

# Divergence classes (the `kind` label of
# elastic_tpu_reconcile_repairs_total; docs/operations.md documents
# each symptom -> repair pairing).
KIND_RESTORED_LINK = "restored_link"        # recorded link missing on disk
KIND_RESTORED_SPEC = "restored_spec"        # recorded spec file missing
KIND_RECLAIMED_POD = "reclaimed_pod"        # record for a pod that is gone
KIND_ORPHAN_LINK = "orphan_link"            # link with no record/intent
KIND_ORPHAN_SPEC = "orphan_spec"            # spec with no record/intent
KIND_INTENT_COMMITTED = "intent_committed"  # journal row outlived its commit
KIND_INTENT_ROLLED_BACK = "intent_rolled_back"  # crashed mid-bind: undo
KIND_REPLAYED_BIND = "replayed_bind"        # kubelet assignment, no record
KIND_REBOUND_DRIFT = "rebound_drift"        # kubelet reassigned device ids
KIND_SLICE_REFORMED = "slice_reformed"      # slice membership changed: re-form

# The single source of truth for divergence classes: metric label ->
# report counter key. _count(), _new_report() and run()'s repaired sum
# all derive from it, so adding a class is one edit.
KIND_REPORT_KEY = {
    KIND_RESTORED_LINK: "restored_links",
    KIND_RESTORED_SPEC: "restored_specs",
    KIND_RECLAIMED_POD: "reclaimed_pods",
    KIND_ORPHAN_LINK: "orphan_links",
    KIND_ORPHAN_SPEC: "orphan_specs",
    KIND_INTENT_COMMITTED: "intents_committed",
    KIND_INTENT_ROLLED_BACK: "intents_rolled_back",
    KIND_REPLAYED_BIND: "replayed_binds",
    KIND_REBOUND_DRIFT: "rebound_drift",
    KIND_SLICE_REFORMED: "slice_reforms",
}
ALL_KINDS = tuple(KIND_REPORT_KEY)


def _new_report(boot: bool, dry_run: bool) -> dict:
    # restored_links/reclaimed_pods/kept_pods/corrupt_records/
    # orphan_links/orphan_specs are the historical restore() report
    # contract (tests and the Restored node event read them).
    report = {key: 0 for key in KIND_REPORT_KEY.values()}
    report.update({
        "kept_pods": 0,
        "corrupt_records": 0,
        "sweep_failures": 0,
        "replay_failures": 0,
        "slice_check_errors": 0,  # membership unknowable this pass
        "slice_reform_failures": 0,
        "divergences_observed": 0,  # dry-run: repairs that WOULD run
        "snapshot_error": None,
        "boot": boot,
        "dry_run": dry_run,
    })
    return report


class Reconciler:
    """Supervised convergence loop over store <-> kubelet <-> disk <-> pods.

    Registered with the supervisor as DEGRADED: a broken reconciler
    must not take binding down with it — the node keeps serving
    Allocate/PreStart while /healthz and the doctor bundle surface the
    degradation.
    """

    def __init__(
        self,
        storage,
        operator,
        plugin,
        sitter,
        snapshot_source=None,
        alloc_spec_dir: str = "",
        metrics=None,
        events=None,
        crd_recorder=None,
        period_s: float = DEFAULT_PERIOD_S,
        dry_run: bool = False,
        rng=None,
        slice_reformer=None,
        timeline=None,
        lag_tracker=None,
        bus=None,
        event_safety_net_factor: float = 1.0,
    ) -> None:
        self._storage = storage
        self._operator = operator
        self._plugin = plugin
        self._sitter = sitter
        self._source = snapshot_source
        self._alloc_dir = alloc_spec_dir
        self._metrics = metrics
        self._events = events
        self._crd = crd_recorder
        self.period_s = period_s
        self.dry_run = dry_run
        # SliceReformer (slices/recovery.py): slice membership is a
        # divergence class — member loss re-forms the survivors.
        self._slices = slice_reformer
        # Lifecycle timeline (timeline.py): every repair is journaled
        # with its divergence class + the entity it acted on, so "what
        # sequence of events converged this pod" is answerable later.
        self._timeline = timeline
        # DrainOrchestrator (drain.py), assigned by the manager after
        # both exist: while a drain has reclaimed this node's bindings,
        # kubelet's still-listed assignments must NOT be replayed back.
        self.drain = None
        # RepartitionController (repartition.py), same late assignment:
        # a pod whose bindings QoS enforcement reclaimed must not have
        # its still-listed assignment replayed back either.
        self.repartition = None
        # MigrationCoordinator (migration.py), same late assignment: an
        # acked resident reclaimed EARLY (checkpoint durable, drain
        # deadline not yet reached) keeps its kubelet assignment until
        # eviction — replaying it would re-bind the chips the handshake
        # just freed.
        self.migration = None
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()
        self._repairs: Dict[str, int] = {k: 0 for k in ALL_KINDS}
        self._sweep_failures_total = 0
        self._replay_failures_total = 0
        self._slice_reform_failures_total = 0
        self._runs_total = 0
        self._last_run_ts: Optional[float] = None
        self._last_duration_s: Optional[float] = None
        self._last_converged_ts: Optional[float] = None
        self._last_report: dict = {}
        # Two-pass confirmation state: candidates seen on the previous
        # completed pass; acted on when seen again.
        self._prev_candidates: set = set()
        self._tick_candidates: set = set()
        # Replay failure backoff: key -> (consecutive failures,
        # runs_total before which no retry happens). A never-bindable
        # assignment (e.g. a pod using our resources without the
        # elastic scheduler — its bind fails by design) must not be
        # re-attempted and warn-logged every pass forever.
        self._replay_backoff: Dict[tuple, tuple] = {}
        self._last_error: Optional[str] = None
        # DetectionLagTracker (latency.py): each repair reports
        # origin->repair latency when the divergence origin was marked
        # (fault injectors / fleet sim stamp marks; unmarked divergences
        # simply record nothing).
        self._lag = lag_tracker
        # Event-driven core (events.py): pod deltas, kubelet assignment
        # deltas and store-change notifications trigger a pass NOW
        # instead of waiting out the jittered period; while the bus is
        # healthy the periodic sweep is demoted to a safety net
        # (period x event_safety_net_factor) but NEVER removed — it
        # remains the correctness backstop for dropped events.
        self._bus = bus
        self.event_safety_net_factor = max(1.0, float(
            event_safety_net_factor
        ))
        self._event_sub = None
        if bus is not None:
            self._event_sub = bus.subscribe(
                "reconciler",
                (bus_events.POD_DELTA, bus_events.ASSIGNMENT_DELTA,
                 bus_events.STORE_BIND, bus_events.STORE_INTENT),
            )
        self._event_passes_total = 0
        # What woke the pass currently running ("event" | "poll"):
        # _count threads it into detection-lag attribution.
        self._pass_trigger = "poll"
        # Pod keys whose store records were seen DELETED by the batch
        # of events that triggered the current pass — commit-ordered
        # proof of a persistent divergence, exempt from two-pass
        # confirmation for this one pass.
        self._event_evidence: set = set()

    # -- plumbing -------------------------------------------------------------

    def _count(
        self, report: dict, kind: str, keys: Optional[dict] = None,
        emit: bool = True, **attrs,
    ) -> None:
        report[KIND_REPORT_KEY[kind]] += 1
        with self._lock:
            self._repairs[kind] = self._repairs.get(kind, 0) + 1
        m = self._metrics
        if m is not None and hasattr(m, "reconcile_repairs"):
            try:
                m.reconcile_repairs.labels(kind=kind).inc()
            except Exception:  # noqa: BLE001 - metrics never break repair
                pass
        if self._lag is not None:
            # The reconciler both detects and repairs in one pass, so
            # one call observes both stages; key resolution mirrors the
            # timeline keys (pod first, then the device hash).
            self._lag.handled(
                "reconciler", kind,
                key=(keys or {}).get("pod") or (keys or {}).get("hash")
                or "",
                trigger=self._pass_trigger,
            )
        if emit and self._timeline is not None:
            from .timeline import KIND_RECONCILE_REPAIR

            # One journal event per repair, divergence class as an
            # attribute: per-entity histories show WHAT the reconciler
            # did to them, not just that repairs happened somewhere.
            self._timeline.emit(
                KIND_RECONCILE_REPAIR, keys=keys,
                **{"class": kind, **attrs},
            )

    def _sweep_failure(self, report: dict) -> None:
        report["sweep_failures"] += 1
        with self._lock:
            self._sweep_failures_total += 1
        m = self._metrics
        if m is not None and hasattr(m, "orphan_sweep_failures"):
            try:
                m.orphan_sweep_failures.inc()
            except Exception:  # noqa: BLE001
                pass

    def _candidate(self, key: tuple) -> None:
        self._tick_candidates.add(key)

    def _confirmed(self, key: tuple) -> bool:
        """True when this divergence was already observed on the
        previous pass (so it is persistent, not an in-flight bind)."""
        self._candidate(key)  # keep confirming for the next pass too
        return key in self._prev_candidates

    def _spec_plugin(self):
        """Any per-resource plugin (they share the alloc-spec dir);
        None for plugin kinds without the tpushare spec surface."""
        return getattr(self._plugin, "core", None)

    def _plugin_for(self, resource: str):
        fn = getattr(self._plugin, "plugin_for_resource", None)
        return fn(resource) if fn is not None else None

    def _pod_alive(self, namespace: str, name: str):
        """(pod_or_None, known) — ``known`` False when the apiserver
        could not be asked (never treat 'cannot tell' as 'gone')."""
        pod = self._sitter.get_pod(namespace, name)
        if pod is not None:
            return pod, True
        try:
            return self._sitter.get_pod_from_api(namespace, name), True
        except Exception as e:  # noqa: BLE001 - apiserver down: keep state
            logger.warning(
                "reconcile: apiserver check failed for %s/%s: %s",
                namespace, name, e,
            )
            return None, False

    # -- one pass -------------------------------------------------------------

    def reconcile_once(
        self, boot: bool = False, now: Optional[float] = None,
        trigger: str = "poll",
    ) -> dict:
        """One full convergence pass; returns the per-class report.

        ``boot=True`` is the agent-startup restore: it runs before the
        device-plugin servers register (no binds can be in flight), so
        every repair acts immediately and the CRD inventory is reconciled
        too. Periodic passes confirm absence-based repairs across two
        passes and honor ``dry_run``. ``trigger`` records what woke the
        pass ("event" = targeted event-bus wakeup, "poll" = periodic
        sweep) for detection-lag attribution.
        """
        faults.fire("reconciler.tick")
        self._pass_trigger = str(trigger)
        t_pass = time.monotonic()
        active = boot or not self.dry_run
        report = _new_report(boot, self.dry_run and not boot)
        self._tick_candidates = set()

        # Artifact snapshot FIRST: any link/spec a healthy in-flight
        # bind has made by now is named by its journal intent (written
        # before creation) or its committed record — both read AFTER
        # this point — so the orphan sweep can never eat a live bind.
        links: List[str] = []
        if hasattr(self._operator, "list_links"):
            links = list(self._operator.list_links())
        try:
            # .json.tmp: _write_json_atomic's temp, leaked by a crash
            # between write and rename — named by hash, so the journal
            # invariant covers it exactly like the final file.
            spec_files = [
                f for f in os.listdir(self._alloc_dir)
                if f.endswith(".json") or f.endswith(".json.tmp")
            ]
        except OSError:
            spec_files = []

        # ONE journal read per pass, taken after the artifact snapshot
        # and BEFORE any pods-table read: intent rows are removed only
        # AFTER their record is checkpointed, so journal-before-store
        # guarantees every pre-snapshot artifact of a healthy bind is in
        # this list or in the (later-read) records — never in neither.
        # Over-inclusion (an intent resolved later this pass) only makes
        # the sweep's known set larger, which is safe.
        # Journal/store read failures RAISE (run() escalates persistent
        # ones to the supervisor): silently returning an empty report
        # would look exactly like a healthy quiet pass while the node
        # has lost all self-repair.
        intents = self._storage.open_intents()
        corrupt = self._storage.corrupt_keys()
        report["corrupt_records"] = len(corrupt)

        assignments = None
        if self._source is not None:
            try:
                with get_tracer().span("reconcile_snapshot"):
                    assignments = self._source.assignments()
            except Exception as e:  # noqa: BLE001 - kubelet down: partial pass
                report["snapshot_error"] = str(e)
                logger.warning(
                    "reconcile: pod-resources snapshot unavailable "
                    "(%s); skipping kubelet-diff repairs", e,
                )

        if boot and self._slices is not None:
            # BEFORE any repair that can rebind (intent replay, drift
            # rebind): a cold registry's pod_env would restamp the stale
            # annotation world at epoch 0 over a reformed spec. Feeding
            # the stamped views in first re-arms the reform override and
            # the epoch floor for every repair this pass runs.
            with get_tracer().span("reconcile_slice_prelearn"):
                self._prelearn_slices()
        with get_tracer().span("reconcile_intents"):
            self._resolve_intents(intents, report, boot, active)
        with get_tracer().span("reconcile_records"):
            self._walk_records(report, assignments, boot, active)
        with get_tracer().span("reconcile_orphans"):
            self._sweep_orphans(
                links, spec_files, intents, corrupt, report, boot, active
            )
        with get_tracer().span("reconcile_unbound"):
            self._replay_unbound(assignments, report, boot, active)
        if self._slices is not None:
            with get_tracer().span("reconcile_slices"):
                self._reconcile_slices(report, boot, active)
        if boot and self._crd is not None:
            self._reconcile_crd()

        report["pending_confirmation"] = len(self._tick_candidates)
        report["repaired_total"] = sum(
            report[key] for key in KIND_REPORT_KEY.values()
        )
        if not boot and report["repaired_total"] and self._events is not None:
            # One batched node event per repairing periodic pass (the
            # boot pass's event is emitted by manager.restore()) —
            # `kubectl describe node` must show that bindings changed
            # underneath the pods.
            from .kube.events import ReasonReconciled

            try:
                self._events.node_event(
                    ReasonReconciled,
                    "reconciler repaired "
                    + ", ".join(
                        f"{report[key]} {kind}"
                        for kind, key in KIND_REPORT_KEY.items()
                        if report[key]
                    ),
                )
            except Exception:  # noqa: BLE001 - observability only
                logger.exception("reconcile event emit failed")
        duration_s = time.monotonic() - t_pass
        report["duration_s"] = duration_s
        # Converged = the pass ended with NOTHING outstanding: no failed
        # sweep/replay, kubelet answerable, no corrupt rows, nothing
        # observed diverged (dry-run) or awaiting confirmation. Repairs
        # that SUCCEEDED don't block convergence — the node is converged
        # at the end of the pass that fixed it. Fleet-level "reconcile
        # convergence time" is measured off this timestamp.
        converged = (
            report["sweep_failures"] == 0
            and report["replay_failures"] == 0
            and report["snapshot_error"] is None
            and report["corrupt_records"] == 0
            and report["divergences_observed"] == 0
            and report["pending_confirmation"] == 0
            # slice membership unknowable (apiserver unanswerable) is the
            # apiserver's analogue of snapshot_error: a lost member may
            # be going undetected, so the node is NOT converged.
            and report["slice_check_errors"] == 0
            and report["slice_reform_failures"] == 0
        )
        wall_now = time.time() if now is None else now
        with self._lock:
            self._prev_candidates = self._tick_candidates
            self._tick_candidates = set()
            self._runs_total += 1
            self._last_run_ts = wall_now
            self._last_duration_s = duration_s
            if converged:
                self._last_converged_ts = wall_now
            self._last_report = dict(report)
        m = self._metrics
        if m is not None:
            try:
                if hasattr(m, "reconcile_runs"):
                    m.reconcile_runs.inc()
                if hasattr(m, "reconcile_duration"):
                    m.reconcile_duration.observe(duration_s)
                if converged and hasattr(m, "reconcile_last_converged"):
                    m.reconcile_last_converged.set(wall_now)
                if hasattr(m, "open_bind_intents"):
                    m.open_bind_intents.set(
                        len(self._storage.open_intents())
                    )
            except Exception:  # noqa: BLE001
                pass
        return report

    # -- intents --------------------------------------------------------------

    def _resolve_intents(
        self, intents: List[dict], report: dict, boot: bool, active: bool
    ) -> None:
        for intent in intents:
            if self._storage.intent_inflight(intent["id"]):
                # A live bind thread in this process owns the row — no
                # matter how slowly it is going (sqlite busy retries, a
                # stalled hostPath, stripe queueing in a rebind burst),
                # it is not debris. The marker is exact: the bind's
                # finally drops it on every exit, so a thread that died
                # stops shielding its row immediately.
                continue
            key = ("intent", intent["id"])
            if not active:
                self._candidate(key)
                report["divergences_observed"] += 1
                continue
            if not boot and not self._confirmed(key):
                # First sighting: belt and braces on top of the
                # in-flight marker. Confirm on the next pass.
                continue
            self._resolve_intent(intent, report)

    def _resolve_intent(self, intent: dict, report: dict) -> None:
        from .plugins import tpushare

        namespace, name = parse_pod_key(intent["pod_key"])
        owner = PodContainer(namespace, name, intent["container"])
        resource = intent["resource"]
        alloc_hash = intent["hash"]
        payload = intent.get("payload", {})
        plugin = self._plugin_for(resource)
        with tpushare.bind_lock(owner.pod_key):
            # Re-check under the owner's bind stripe: commits happen
            # inside this stripe, so an intent still open here cannot
            # belong to a bind that is past its checkpoint.
            if not self._storage.intent_open(intent["id"]):
                return
            try:
                info = self._storage.load(namespace, name)
            except StorageError:
                # Corrupt checkpoint row: we cannot prove this bind
                # un-happened — leave the intent for the operator
                # (corrupt_records is alarmed separately).
                logger.warning(
                    "reconcile: intent %d for %s left open — checkpoint "
                    "record is corrupt", intent["id"], owner.pod_key,
                )
                return
            rec = None
            if info is not None:
                rec = info.allocations.get(
                    intent["container"], {}
                ).get(resource)
            if rec is not None and rec.device.hash == alloc_hash:
                # The bind reached its commit point (record checkpointed)
                # and died before dropping the journal row. Roll FORWARD:
                # make sure the recorded artifacts exist, then commit.
                for pos, link_id in enumerate(rec.created_node_ids):
                    if not self._operator.check(link_id):
                        try:
                            self._operator.create(
                                rec.chip_indexes[pos], link_id
                            )
                        except Exception:  # noqa: BLE001
                            logger.exception(
                                "reconcile: re-create %s failed", link_id
                            )
                self._storage.journal_remove(intent["id"])
                self._count(
                    report, KIND_INTENT_COMMITTED,
                    keys={"pod": owner.pod_key,
                          "container": owner.container,
                          "hash": alloc_hash},
                    intent_id=intent["id"], resource=resource,
                )
                logger.info(
                    "reconcile: intent %d (%s %s) was committed; journal "
                    "row dropped", intent["id"], owner.pod_key, alloc_hash,
                )
                return
            # A concurrent RETRY bind for the same device set journals
            # its own intent before creating links — and those links
            # carry the same hash-derived names this intent planned.
            # If such a sibling intent exists, the artifacts may be the
            # retry's, not this corpse's: drop only the stale row and
            # let the live bind (or its own recovery) own the rest.
            try:
                retry_exists = any(
                    i["id"] != intent["id"] and i["hash"] == alloc_hash
                    for i in self._storage.open_intents()
                )
            except StorageError:
                retry_exists = True  # can't tell: stay non-destructive
            if retry_exists:
                self._storage.journal_remove(intent["id"])
                self._count(
                    report, KIND_INTENT_ROLLED_BACK,
                    keys={"pod": owner.pod_key,
                          "container": owner.container,
                          "hash": alloc_hash},
                    intent_id=intent["id"], resource=resource,
                    reason="superseded_by_retry",
                )
                logger.info(
                    "reconcile: dropped stale intent %d for %s — a "
                    "newer intent owns hash %s", intent["id"],
                    owner.pod_key, alloc_hash,
                )
                return
            # The bind never committed: undo every side effect it may
            # have gotten to (all idempotent — ENOENT deletes succeed).
            for link_id in payload.get("planned_link_ids", []):
                try:
                    self._operator.delete(link_id)
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "reconcile: rollback delete %s failed", link_id
                    )
                    self._sweep_failure(report)
            if plugin is not None:
                plugin.remove_alloc_spec_locked(alloc_hash, owner)
            else:
                try:
                    os.unlink(
                        os.path.join(self._alloc_dir, f"{alloc_hash}.json")
                    )
                except OSError:
                    pass
            self._storage.journal_remove(intent["id"])
            self._count(
                report, KIND_INTENT_ROLLED_BACK,
                keys={"pod": owner.pod_key,
                      "container": owner.container,
                      "hash": alloc_hash,
                      "chips": list(payload.get("chip_indexes", []))},
                intent_id=intent["id"], resource=resource,
                reason="crashed_mid_bind",
            )
            logger.warning(
                "reconcile: rolled back crashed bind intent %d "
                "(%s %s %s)", intent["id"], owner.pod_key, resource,
                alloc_hash,
            )

    # -- store walk -----------------------------------------------------------

    def _walk_records(
        self, report: dict, assignments, boot: bool, active: bool
    ) -> None:
        # Reverse index of kubelet's view: who is assigned what, by owner.
        owner_assign: Dict[tuple, tuple] = {}
        if assignments is not None:
            for resource, by_hash in assignments.items():
                for h, (owner, ids) in by_hash.items():
                    owner_assign[
                        (owner.pod_key, owner.container, resource)
                    ] = (h, ids)
        for key, info in list(self._storage.items()):
            pod, known = self._pod_alive(info.namespace, info.name)
            if pod is None and not known:
                report["kept_pods"] += 1
                continue
            if pod is None:
                if active:
                    self._reclaim_pod(info, report)
                else:
                    report["divergences_observed"] += 1
                continue
            report["kept_pods"] += 1
            for container, by_resource in list(info.allocations.items()):
                for resource, record in list(by_resource.items()):
                    if self.drain is not None and (
                        self.drain.suppress_replays()
                    ):
                        # Drain reclaim is tearing bindings down while
                        # this pass walks a pre-reclaim record snapshot:
                        # re-creating "missing" links or rebinding
                        # "missing" specs here would resurrect exactly
                        # what the drain just removed. Checked per
                        # record (not per pass) so a reclaim starting
                        # mid-pass stops the rebuilds immediately.
                        continue
                    owner = PodContainer(
                        info.namespace, info.name, container
                    )
                    cur = owner_assign.get((key, container, resource))
                    if cur is not None and cur[0] != record.device.hash:
                        # kubelet reassigned this container's device ids
                        # (kubelet restart wipes its device manager state)
                        # — kubelet's view is what the container's cgroup
                        # rules were built from, so it wins.
                        dkey = ("drift", key, container, resource)
                        if not active:
                            self._candidate(dkey)
                            report["divergences_observed"] += 1
                        elif boot or self._confirmed(dkey):
                            self._repair_drift(owner, record, cur, report)
                        continue
                    self._repair_artifacts(
                        owner, record, resource, report, active
                    )

    def _repair_artifacts(
        self, owner, record, resource: str, report: dict, active: bool
    ) -> None:
        """Recorded allocation, live pod: its links and spec must exist."""
        for pos, link_id in enumerate(record.created_node_ids):
            if self._operator.check(link_id):
                continue
            if not active:
                report["divergences_observed"] += 1
                continue
            try:
                self._operator.create(record.chip_indexes[pos], link_id)
                self._count(
                    report, KIND_RESTORED_LINK,
                    keys={"pod": owner.pod_key,
                          "container": owner.container,
                          "hash": record.device.hash,
                          "chips": [record.chip_indexes[pos]]},
                    link=link_id,
                )
            except Exception:  # noqa: BLE001
                logger.exception("reconcile: re-create %s failed", link_id)
        plugin = self._plugin_for(resource)
        if plugin is None or plugin.alloc_spec_exists(record.device.hash):
            return
        if not active:
            report["divergences_observed"] += 1
            return
        # The spec file feeds the OCI hook / NRI adjustment at the
        # container's NEXT start; rebuild it by replaying the bind
        # (idempotent — same device, same record, re-merged siblings).
        try:
            plugin.rebind(owner, record.device)
            self._count(
                report, KIND_RESTORED_SPEC,
                keys={"pod": owner.pod_key, "container": owner.container,
                      "hash": record.device.hash,
                      "chips": list(record.chip_indexes)},
                resource=resource,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "reconcile: spec rebuild for %s %s failed: %s",
                owner.pod_key, record.device.hash, e,
            )
            report["replay_failures"] += 1
            with self._lock:
                self._replay_failures_total += 1

    def _repair_drift(self, owner, record, cur: tuple, report: dict) -> None:
        from .plugins import tpushare

        new_hash, new_ids = cur
        resource = record.device.resource
        plugin = self._plugin_for(resource)
        if plugin is None:
            return
        with tpushare.bind_lock(owner.pod_key):
            # Somebody (a live bind, a previous repair) may have already
            # converged this record — re-check under the stripe.
            try:
                info = self._storage.load(owner.namespace, owner.name)
            except StorageError:
                return
            rec = None
            if info is not None:
                rec = info.allocations.get(owner.container, {}).get(resource)
            if rec is None or rec.device.hash != record.device.hash:
                return
            for link_id in rec.created_node_ids:
                try:
                    self._operator.delete(link_id)
                except Exception:  # noqa: BLE001
                    logger.warning(
                        "reconcile: drift cleanup delete %s failed", link_id
                    )
                    self._sweep_failure(report)
            plugin.remove_alloc_spec_locked(rec.device.hash, owner)
            # Drop the stale record NOW: if the rebind below fails, the
            # store must not keep claiming links we just deleted (the
            # assignment stays visibly unbound and is replayed later).
            self._storage.mutate(
                owner.namespace, owner.name,
                lambda i: i.allocations.get(
                    owner.container, {}
                ).pop(resource, None),
            )
            if self._crd is not None:
                try:
                    self._crd.record_released(rec.device.hash)
                except Exception:  # noqa: BLE001 - observability only
                    pass
        try:
            plugin.rebind(owner, Device(list(new_ids), resource))
            self._count(
                report, KIND_REBOUND_DRIFT,
                keys={"pod": owner.pod_key, "container": owner.container,
                      "hash": new_hash},
                resource=resource, old_hash=record.device.hash,
            )
            logger.warning(
                "reconcile: %s %s re-bound after kubelet device-id drift "
                "(%s -> %s)", owner.pod_key, resource,
                record.device.hash, new_hash,
            )
        except Exception as e:  # noqa: BLE001
            logger.warning(
                "reconcile: drift rebind for %s %s failed: %s",
                owner.pod_key, resource, e,
            )
            report["replay_failures"] += 1
            with self._lock:
                self._replay_failures_total += 1

    def _reclaim_pod(self, info, report: dict, locked: bool = False) -> None:
        """``locked=True`` = the caller already holds the owner's bind
        stripe (drain_reclaim tears down LIVE pods from the drain
        thread and must serialize against binds and this reconciler's
        own repairs); the stripes are not reentrant, so the spec
        removal switches to its ``_locked`` variant. The historical
        dead-pod path stays unlocked — it only ever ran on the single
        reconciler thread for pods that no longer exist."""
        spec_plugin = self._spec_plugin()
        for container, by_resource in info.allocations.items():
            owner = PodContainer(info.namespace, info.name, container)
            for record in by_resource.values():
                for link_id in record.created_node_ids:
                    try:
                        self._operator.delete(link_id)
                    except Exception:  # noqa: BLE001
                        logger.warning(
                            "reconcile: reclaim delete %s failed "
                            "(retried next pass)", link_id,
                        )
                        self._sweep_failure(report)
                if spec_plugin is not None:
                    if locked:
                        spec_plugin.remove_alloc_spec_locked(
                            record.device.hash, owner
                        )
                    else:
                        spec_plugin.remove_alloc_spec(
                            record.device.hash, owner
                        )
                if self._crd is not None:
                    try:
                        self._crd.record_released(record.device.hash)
                    except Exception:  # noqa: BLE001
                        pass
        self._storage.delete(info.namespace, info.name)
        self._count(
            report, KIND_RECLAIMED_POD,
            keys={"pod": info.key},
            hashes=[
                record.device.hash for record in info.records()
            ],
        )
        logger.info("reconcile: reclaimed dead pod %s", info.key)

    def reclaim_pods(self, pod_keys) -> dict:
        """Policy-driven reclaim: tear down the named pods' bindings —
        links, specs, CRD releases, store records — through the SAME
        repair executor the reconciler uses for dead pods, so the work
        is counted under the ``reclaimed_pod`` divergence class and
        leaves zero orphan artifacts. Two callers: the drain
        orchestrator's deadline reclaim (drain.py) and the repartition
        controller's QoS eviction (repartition.py). The pods may still
        be live at the apiserver; each caller suppresses replays until
        its pods are actually gone. Each pod's teardown runs under the
        owner's bind stripe — these run from OTHER threads against LIVE
        pods, so they must serialize against in-flight binds and the
        reconcile pass's own repairs exactly like the drift repair
        does."""
        from .plugins import tpushare

        report = _new_report(boot=False, dry_run=False)
        for pod_key in pod_keys:
            namespace, name = parse_pod_key(pod_key)
            try:
                info = self._storage.load(namespace, name)
            except StorageError:
                logger.warning(
                    "drain reclaim: %s has a corrupt record; left for "
                    "the corrupt-row runbook", pod_key,
                )
                continue
            if info is None:
                continue
            try:
                with tpushare.bind_lock(pod_key):
                    self._reclaim_pod(info, report, locked=True)
            except Exception:  # noqa: BLE001 - keep reclaiming the rest
                logger.exception("policy reclaim: %s failed", pod_key)
                self._sweep_failure(report)
        return report

    # Historical name (PR 8): the drain orchestrator and its tests call
    # the reclaim by this alias.
    drain_reclaim = reclaim_pods

    # -- orphan sweep ---------------------------------------------------------

    def _sweep_orphans(
        self,
        links: List[str],
        spec_files: List[str],
        intents: List[dict],
        corrupt: List[str],
        report: dict,
        boot: bool,
        active: bool,
    ) -> None:
        if corrupt:
            # A corrupt checkpoint row may describe a LIVE allocation
            # whose links/specs we can no longer enumerate; sweeping now
            # could destroy state under a running container. Stay
            # non-destructive until the row is gone.
            logger.warning(
                "reconcile: skipping orphan sweep — %d corrupt checkpoint "
                "record(s) present", len(corrupt),
            )
            return
        # Known set: the pass-start journal read (taken BEFORE the store
        # read — intent rows only disappear after their record lands, so
        # nothing healthy can fall between the two) plus a records read
        # taken after it. `intents` being pre-resolution only ever makes
        # this set larger, which is safe.
        known_links: set = set()
        known_hashes: set = set()
        for intent in intents:
            known_links.update(intent["payload"].get("planned_link_ids", []))
            known_hashes.add(intent["hash"])
        for _, info in self._storage.items():
            for record in info.records():
                known_links.update(record.created_node_ids)
                known_hashes.add(record.device.hash)
        for link_id in links:
            if link_id in known_links:
                continue
            if not self._operator.check(link_id):
                # Already gone (an intent rollback this pass, a bind's
                # own rollback): a vanished entry from the snapshot is
                # not a divergence — don't count phantom repairs, and
                # don't alarm a dry-run operator with them.
                continue
            if link_id.endswith(".tmp") and not boot:
                # A pending atomic-create temp is never named by any
                # intent (temp names embed pid+thread), so the journal
                # invariant doesn't cover it — a live create could be
                # microseconds from its rename. Crash debris is still
                # there next pass; a pending temp is not.
                if not self._confirmed(("orphan_tmp", link_id)):
                    continue
            if not active:
                report["divergences_observed"] += 1
                continue
            try:
                self._operator.delete(link_id)
                self._count(report, KIND_ORPHAN_LINK, link=link_id)
            except Exception:  # noqa: BLE001
                # NOT dropped forever any more: counted, and retried on
                # the next pass (the link stays unrecorded).
                logger.warning(
                    "reconcile: orphan delete %s failed (retried next "
                    "pass)", link_id,
                )
                self._sweep_failure(report)
        for fname in spec_files:
            stem = (
                fname[: -len(".json.tmp")] if fname.endswith(".json.tmp")
                else fname[: -len(".json")]
            )
            if stem in known_hashes:
                continue
            if not os.path.exists(os.path.join(self._alloc_dir, fname)):
                continue  # vanished since the snapshot: not a divergence
            if not active:
                report["divergences_observed"] += 1
                continue
            try:
                os.unlink(os.path.join(self._alloc_dir, fname))
                self._count(
                    report, KIND_ORPHAN_SPEC, keys={"hash": stem}
                )
                # the allocation's sidecar files — usage self-report
                # AND checkpoint ack — die with its spec (the same
                # common.AllocSidecarSubdirs list remove_alloc_spec
                # uses: a sweep that bypassed it must not leak either)
                from .common import AllocSidecarSubdirs

                for subdir in AllocSidecarSubdirs:
                    for suffix in (".json", ".json.tmp"):
                        try:
                            os.unlink(os.path.join(
                                self._alloc_dir, subdir,
                                stem + suffix,
                            ))
                        except OSError:
                            pass
            except FileNotFoundError:
                pass
            except OSError:
                logger.warning(
                    "reconcile: orphan spec unlink %s failed (retried "
                    "next pass)", fname,
                )
                self._sweep_failure(report)

    # -- unbound kubelet assignments ------------------------------------------

    def _replay_unbound(
        self, assignments, report: dict, boot: bool, active: bool
    ) -> None:
        """kubelet says a live container holds our devices, the store has
        no record: a bind that crashed before its checkpoint (or whose
        intent was rolled back above). Replay it end to end."""
        if assignments is None:
            return
        if self.drain is not None and self.drain.suppress_replays():
            # Drain reclaimed this node's bindings past the deadline;
            # the pods (and their kubelet assignments) may outlive the
            # reclaim until eviction. Replaying them would faithfully
            # re-bind everything the drain just tore down.
            logger.info(
                "reconcile: unbound-assignment replay suppressed "
                "(node drain reclaimed bindings)"
            )
            return
        for resource in sorted(assignments):
            plugin = self._plugin_for(resource)
            if plugin is None:
                continue  # not our extended resource
            for alloc_hash in sorted(assignments[resource]):
                owner, ids = assignments[resource][alloc_hash]
                if self.repartition is not None and (
                    self.repartition.replay_suppressed(owner.pod_key)
                ):
                    # QoS enforcement reclaimed this pod's bindings; its
                    # kubelet assignment outlives the reclaim until the
                    # pod is deleted. Replaying would re-bind exactly
                    # what the throttle->evict escalation tore down.
                    continue
                if self.migration is not None and (
                    self.migration.replay_suppressed(owner.pod_key)
                ):
                    # The migration coordinator reclaimed this acked
                    # resident ahead of the drain deadline; until the
                    # pod is evicted, its assignment must stay reclaimed.
                    continue
                try:
                    info = self._storage.load(owner.namespace, owner.name)
                except StorageError:
                    continue  # corrupt: never double-bind over it
                rec = None
                if info is not None:
                    rec = info.allocations.get(
                        owner.container, {}
                    ).get(resource)
                if rec is not None:
                    continue  # bound (drift is the record walk's job)
                # Keyed by OWNER too: under churn a reclaimed pod's
                # device set can return under a NEW pod (same chip/unit
                # pattern, fresh assignment) within one pass window —
                # without the owner in the key, the dead generation's
                # candidate would insta-confirm the new one and replay
                # a bind that is seconds from binding itself.
                ukey = ("unbound", resource, alloc_hash, owner.pod_key)
                if not active:
                    self._candidate(ukey)
                    report["divergences_observed"] += 1
                    continue
                if not boot and not self._confirmed(ukey) and (
                    owner.pod_key not in self._event_evidence
                ):
                    # kubelet assigns devices BEFORE PreStartContainer
                    # runs; a fresh assignment is normally seconds from
                    # binding itself. Only replay ones that stay unbound
                    # across two passes — UNLESS the triggering events
                    # included this pod's store-delete notification: the
                    # store itself confirmed the record is gone (an
                    # in-flight bind never emits a delete), so waiting a
                    # second pass adds nothing but lag.
                    continue
                failures, next_run = self._replay_backoff.get(ukey, (0, 0))
                if not boot and self._runs_total < next_run:
                    continue  # backing off a repeatedly-failing replay
                pod, known = self._pod_alive(owner.namespace, owner.name)
                if pod is None:
                    continue  # stale kubelet state or unknowable: skip
                try:
                    plugin.rebind(owner, Device(list(ids), resource))
                    self._count(
                        report, KIND_REPLAYED_BIND,
                        keys={"pod": owner.pod_key,
                              "container": owner.container,
                              "hash": alloc_hash},
                        resource=resource,
                    )
                    self._replay_backoff.pop(ukey, None)
                    logger.warning(
                        "reconcile: replayed unbound assignment %s %s -> "
                        "%s", resource, alloc_hash, owner.pod_key,
                    )
                except Exception as e:  # noqa: BLE001
                    # Exponential pass-count backoff (2,4,...,32): an
                    # assignment that CANNOT bind — e.g. a pod that
                    # bypassed the elastic scheduler, so the bind fails
                    # its annotation check by design — must not be
                    # retried and warn-logged every pass for the pod's
                    # whole lifetime.
                    failures += 1
                    self._replay_backoff[ukey] = (
                        failures,
                        self._runs_total + min(2 ** failures, 32),
                    )
                    logger.warning(
                        "reconcile: replay of %s %s for %s failed "
                        "(attempt %d, next retry in ~%d passes): %s",
                        resource, alloc_hash, owner.pod_key, failures,
                        min(2 ** failures, 32), e,
                    )
                    report["replay_failures"] += 1
                    with self._lock:
                        self._replay_failures_total += 1
        # Assignments that disappeared take their backoff state with
        # them (pod deleted, or finally bound via a real PreStart).
        live_keys = {
            ("unbound", res, h, by_hash[h][0].pod_key)
            for res, by_hash in assignments.items()
            for h in by_hash
        }
        for key in [k for k in self._replay_backoff if k not in live_keys]:
            del self._replay_backoff[key]

    # -- slice membership (slices/recovery.py) --------------------------------

    def _prelearn_slices(self) -> None:
        """Boot-only: re-learn every stamped slice world/epoch from the
        on-disk specs before any repair runs. The registry is process
        memory; a reboot must not let the first drift rebind of the
        pass stamp annotation-world/epoch-0 over a reformed spec."""
        for _key, info in list(self._storage.items()):
            for by_resource in list(info.allocations.values()):
                try:
                    stamped = self._slices.stamped_view(by_resource)
                except Exception:  # noqa: BLE001 - best-effort pre-learn
                    continue
                if stamped is not None:
                    self._slices.observe(stamped)

    def _reconcile_slices(
        self, report: dict, boot: bool, active: bool
    ) -> None:
        """Slice membership as a divergence class: for every bound pod
        carrying a slice identity, diff the hosts stamped into its
        alloc-spec env against the shared apiserver's live membership.
        A persistent mismatch (confirmed across two passes, like every
        absence-based repair) re-forms the survivors: topology env
        re-emitted at the new world size under the bind stripe, epoch
        bumped, ``TPUSliceReformed`` emitted."""
        from .common import AnnotationSliceID
        from .slices.recovery import SliceMembershipError

        seen_slices: set = set()
        local_members: Dict[str, set] = {}  # slice -> pod keys seen bound
        live_cache: Dict[str, set] = {}  # one apiserver view per pass
        for key, info in list(self._storage.items()):
            pod = self._sitter.get_pod(info.namespace, info.name)
            ann = (
                (pod or {}).get("metadata", {}).get("annotations", {}) or {}
            )
            slice_id = ann.get(AnnotationSliceID, "")
            if slice_id:
                seen_slices.add(slice_id)
            for container, by_resource in list(info.allocations.items()):
                if pod is not None and not slice_id:
                    # The live pod visibly carries no slice annotation:
                    # authoritative non-member, skip the spec reads (a
                    # slice-free node must not pay per-pod JSON parses
                    # every pass just to conclude "not a slice").
                    continue
                # The stamped spec is the durable membership record:
                # collect + re-learn it even when the sitter momentarily
                # cannot return the pod, so a watch blip never prunes a
                # live slice's registry state (epoch included).
                stamped = self._slices.stamped_view(by_resource)
                if stamped is None:
                    continue  # unstamped: nothing to diff or reform yet
                seen_slices.add(stamped[0])
                local_members.setdefault(stamped[0], set()).add(
                    f"{info.namespace}/{info.name}"
                )
                self._slices.observe(stamped)
                if pod is None:
                    continue  # dead/unknown pods are the record walk's job
                stamped_slice = stamped[0]
                owner = PodContainer(info.namespace, info.name, container)
                try:
                    div = self._slices.divergence(
                        owner, by_resource, live_hosts_cache=live_cache,
                        stamped=stamped,
                    )
                except SliceMembershipError as e:
                    # Membership UNKNOWABLE (apiserver down): never treat
                    # it as loss. Reported, retried next pass.
                    report["slice_check_errors"] += 1
                    logger.warning(
                        "reconcile: slice membership for %s unknowable: "
                        "%s", stamped_slice, e,
                    )
                    continue
                if div is None:
                    continue
                skey = (
                    "slice", stamped_slice, owner.pod_key, container,
                    tuple(div["new_hosts"]),
                )
                if not active:
                    self._candidate(skey)
                    report["divergences_observed"] += 1
                    continue
                if not boot and not self._confirmed(skey):
                    # First sighting: a member mid-registration (or a
                    # watch blip) must not trigger a spurious reform.
                    continue
                if not boot:
                    # The confirming sighting must come from an
                    # INDEPENDENT apiserver LIST: with a reconcile
                    # period shorter than the membership TTL, both
                    # passes would otherwise read the same cached
                    # snapshot and "two sightings" would be one stale
                    # observation wearing two hats.
                    try:
                        fresh = {
                            stamped_slice:
                                self._slices.registry.live_hosts(
                                    stamped_slice, refresh=True
                                ),
                        }
                    except SliceMembershipError as e:
                        report["slice_check_errors"] += 1
                        logger.warning(
                            "reconcile: slice %s reform confirmation "
                            "blocked, membership unknowable: %s",
                            stamped_slice, e,
                        )
                        continue
                    live_cache.update(fresh)
                    div = self._slices.divergence(
                        owner, by_resource, live_hosts_cache=fresh,
                        stamped=stamped,
                    )
                    if div is None:
                        continue  # healthy on the fresh view after all
                    fresh_skey = (
                        "slice", stamped_slice, owner.pod_key, container,
                        tuple(div["new_hosts"]),
                    )
                    if fresh_skey != skey:
                        # The world moved between sightings: restart
                        # confirmation for the NEW shape.
                        self._candidate(fresh_skey)
                        continue
                try:
                    self._slices.reform(owner, by_resource, div)
                    # emit=False: SliceReformer.reform journals the
                    # richer slice_reformed event itself (epoch, lost/
                    # joined hosts) — two events for one reform would
                    # read as two reforms.
                    self._count(report, KIND_SLICE_REFORMED, emit=False)
                except Exception as e:  # noqa: BLE001 - retried next pass
                    logger.warning(
                        "reconcile: slice reform for %s (%s) failed: %s",
                        owner.pod_key, stamped_slice, e,
                    )
                    # Counted under its OWN key: a failing reform must
                    # point triage at the slice runbook, not at
                    # replayed_bind's.
                    report["slice_reform_failures"] += 1
                    with self._lock:
                        self._slice_reform_failures_total += 1
        if active:
            # Dry-run passes are observe-only: pruning mutates registry
            # state (epoch, reform counts, member gauges).
            registry = self._slices.registry
            registry.prune(seen_slices)
            # Per-POD housekeeping for slices that survive the prune: a
            # reclaimed member pod must not stay listed as a live local
            # member. Only dropped once its store record is gone —
            # re-checked per pod so a bind landing mid-pass is kept.
            for sid, st in registry.status().items():
                for pod_key in list(st.get("local_pods", {})):
                    if pod_key in local_members.get(sid, ()):
                        continue
                    ns, _, name = pod_key.partition("/")
                    if self._storage.load(ns, name) is None:
                        registry.drop_local_pod(sid, pod_key)

    # -- CRD inventory (boot only, as restore() always did) -------------------

    def _reconcile_crd(self) -> None:
        live = [
            record.device.hash
            for _, info in self._storage.items()
            for record in info.records()
        ]
        try:
            chips = [c.index for c in self._operator.devices()]
        except Exception:  # noqa: BLE001 - discovery failure
            chips = []
        with get_tracer().span("crd_reconcile", live=len(live)):
            try:
                self._crd.reconcile(live, chip_indexes=chips)
            except Exception:  # noqa: BLE001 - observability, never fatal
                logger.exception("reconcile: CRD sweep failed")

    # -- the supervised loop --------------------------------------------------

    def run(self, stop: threading.Event) -> None:
        """Supervised loop: jittered pacing around ``period_s`` (0.75x -
        1.25x, so a fleet of agents never thunders onto the kubelet in
        lockstep after a node-pool-wide restart).

        With an event bus the wait doubles as an event trigger: bus
        events start a targeted pass immediately (debounced), and the
        periodic sweep stretches to ``period_s x
        event_safety_net_factor`` while the bus is healthy — unless the
        LAST pass left work outstanding (pending confirmations,
        failures, observed divergences), in which case the next sweep
        comes at the base period regardless (two-pass confirmation must
        never wait out a stretched safety net)."""
        consecutive_failures = 0
        last_event_pass = 0.0
        outstanding = False
        while True:
            # Evidence lives for exactly one pass: cleared before the
            # wait, set only when this iteration drains store-delete
            # notifications.
            self._event_evidence = set()
            factor = 1.0
            sub = self._event_sub
            if (
                sub is not None and not outstanding
                and self._bus.healthy()
            ):
                factor = self.event_safety_net_factor
            delay = self.period_s * factor * (
                0.75 + 0.5 * self._rng.random()
            )
            if sub is None:
                if stop.wait(delay):
                    return
                trigger = "poll"
            else:
                trigger = sub.wait_trigger(stop, delay)
                if trigger == "stop":
                    return
                if trigger == "event":
                    # Debounce the burst, and pace event-triggered
                    # passes at least EVENT_MIN_INTERVAL_S apart.
                    since = time.monotonic() - last_event_pass
                    pace = max(EVENT_DEBOUNCE_S,
                               EVENT_MIN_INTERVAL_S - since)
                    if stop.wait(pace):
                        return
                    drained = sub.drain()
                    last_event_pass = time.monotonic()
                    if drained and all(
                        e.topic == bus_events.BUS_WAKE for e in drained
                    ):
                        # Pure bus-health wake (watch died/recovered):
                        # run the sweep NOW at poll attribution — the
                        # no-gap fallback — and recompute the stretch
                        # on the next iteration.
                        trigger = "poll"
                    else:
                        with self._lock:
                            self._event_passes_total += 1
                        # A store-delete notification is commit-ordered
                        # proof the pod's record is GONE — not an
                        # in-flight bind racing the kubelet List — so
                        # the pass it triggers may replay that pod
                        # without the two-pass confirmation wait.
                        self._event_evidence = {
                            e.key for e in drained
                            if e.topic == bus_events.STORE_BIND
                            and e.kind == "delete"
                        }
            with get_tracer().trace("reconcile") as tr:
                try:
                    report = self.reconcile_once(trigger=trigger)
                    consecutive_failures = 0
                    outstanding = bool(
                        report["pending_confirmation"]
                        or report["sweep_failures"]
                        or report["replay_failures"]
                        or report["divergences_observed"]
                    )
                except Exception as e:  # noqa: BLE001
                    # One-off failures (apiserver blip, transient sqlite
                    # lock) are absorbed without burning a supervisor
                    # restart; a PERSISTENTLY failing pass must escape to
                    # the supervisor — otherwise the node silently loses
                    # all self-repair while /healthz reads healthy.
                    consecutive_failures += 1
                    outstanding = True  # failed pass: retry at base period
                    with self._lock:
                        self._last_error = f"{type(e).__name__}: {e}"
                    if consecutive_failures >= 3:
                        raise
                    logger.exception(
                        "reconcile pass failed (%d consecutive; "
                        "escalating to the supervisor at 3)",
                        consecutive_failures,
                    )
                    continue
                repaired = report["repaired_total"]
                tr.set(
                    repaired=repaired,
                    observed=report["divergences_observed"],
                    sweep_failures=report["sweep_failures"],
                    replay_failures=report["replay_failures"],
                )
                if (
                    repaired == 0
                    and report["sweep_failures"] == 0
                    and report["replay_failures"] == 0
                    and report["divergences_observed"] == 0
                ):
                    # TRULY quiet passes run forever; don't churn real
                    # allocation traces out of the bounded ring. Dry-run
                    # observations and failing replays ARE the signal —
                    # they must stay visible in /debug/traces.
                    tr.discard()

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        """The ``reconcile`` block of /debug/allocations and the doctor
        bundle: last run, per-class repair totals, and every open
        (uncommitted) bind intent with its age — a stuck intent must be
        diagnosable from a bundle alone."""
        try:
            intents = self._storage.open_intents_brief()
        except Exception:  # noqa: BLE001 - storage may already be closed
            intents = []
        sub = self._event_sub
        events_block = None
        if sub is not None:
            events_block = {
                "safety_net_factor": self.event_safety_net_factor,
                "bus_healthy": self._bus.healthy(),
                "subscription": sub.stats(),
            }
        with self._lock:
            if events_block is not None:
                events_block["event_passes_total"] = (
                    self._event_passes_total
                )
            return {
                "period_s": self.period_s,
                "events": events_block,
                "dry_run": self.dry_run,
                "runs_total": self._runs_total,
                "last_run_ts": self._last_run_ts,
                "last_duration_s": self._last_duration_s,
                "last_converged_ts": self._last_converged_ts,
                "repairs_total": {
                    k: v for k, v in self._repairs.items() if v
                },
                "sweep_failures_total": self._sweep_failures_total,
                "replay_failures_total": self._replay_failures_total,
                "slice_reform_failures_total": (
                    self._slice_reform_failures_total
                ),
                "last_error": self._last_error,
                "pending_confirmation": len(self._prev_candidates),
                "open_intents": intents,
                "last_report": dict(self._last_report),
            }
