"""Perf-regression ledger: the committed bench trajectory, read back.

Every growth round commits a ``BENCH_r<NN>.json`` snapshot (bench.py),
but until now nothing ever READ the history — a latency regression
only surfaced if a human eyeballed two JSON files. This module parses
the committed trajectory (plus any smoke-produced structural-latency
records handed to it) into a schema-validated per-leg time series and
gates on it: ``make perf-gate`` fails when the newest round's tracked
latency regresses beyond tolerance against the recent trajectory.

Gate rule (deliberately robust to noisy CI boxes): for each tracked
lower-is-better series, the baseline is the **median of the last
``window`` rounds before the newest**; the newest value regresses when
it exceeds ``baseline * (1 + tolerance) + floor_ms``. The committed
trajectory legitimately drifts as scenarios get harder (rounds add
pods/host load), so the tolerance is wide — this gate catches
"something doubled", not "something grew 5%".

Like metrics.lint_exposition, everything returns a problems list
(empty = clean) and carries a self-test that PROVES the gate trips on
a seeded regression — a gate that cannot fail is not a gate.
"""

from __future__ import annotations

import copy
import glob
import json
import os
import re
import statistics
from typing import Dict, List, Optional, Tuple

# Tracked per-leg series: (name, path into a round's JSON). All
# lower-is-better milliseconds, from the bench's own-pipeline block.
TRACKED: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("allocate_p50_ms", ("parsed", "extra", "ours", "allocate_p50_ms")),
    ("prestart_p50_ms", ("parsed", "extra", "ours", "prestart_p50_ms")),
    ("bind_p50_ms", ("parsed", "extra", "ours", "bind_p50_ms")),
    ("bind_p99_ms", ("parsed", "extra", "ours", "bind_p99_ms")),
)

# Serving-plane series: HIGHER-is-better ratios (prefix-cache prefill
# reduction, live-repartition speedup). These legs entered the bench
# later than the bind legs, so rounds without the leaf contribute no
# point — the gate never retro-fails old history — but once a leg
# publishes, a collapse in its ratio trips the gate exactly like a
# bind-latency regression does.
TRACKED_RATIOS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("serving_prefill_reduction",
     ("parsed", "extra", "request_obs", "prefill_reduction")),
    ("qos_live_speedup",
     ("parsed", "extra", "qos_repartition", "live_speedup")),
)

# Event-core series: lower-is-better milliseconds from the fleet
# bench's event leg (bench.py run_event_leg). Like the ratio series
# these entered the bench after the committed history began, so they
# are tolerant-of-missing — rounds that predate the event core simply
# contribute no point and are NOT schema errors — but once published,
# an event-to-repair or churn-bind-p99 blowup trips the gate exactly
# like a bind-latency regression does.
TRACKED_EVENT: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("event_to_repair_ms",
     ("parsed", "extra", "event_core", "event_to_repair_ms")),
    ("bind_churn_p99_ms",
     ("parsed", "extra", "event_core", "bind_churn_p99_ms")),
)

# Migration series: lower-is-better, from the bench's migration_core
# block (lifted out of the fleet leg's pre-copy scenario, ISSUE 20).
# ``migration_downtime_ms`` is the cutover pause — the headline the
# sub-second-migration work exists to keep small — and
# ``migration_delta_bytes_ratio`` is final-delta/full-state, whose
# blowup means delta streaming degraded back toward shipping full
# checkpoints. Both tolerant-of-missing like the other late-entry
# series; the ratio uses DEFAULT_FLOOR_RATIO for slack (a 0.25ms floor
# would swamp a unitless ~0.04 ratio).
TRACKED_MIGRATION: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("migration_downtime_ms",
     ("parsed", "extra", "migration_core", "migration_downtime_ms")),
    ("migration_delta_bytes_ratio",
     ("parsed", "extra", "migration_core", "migration_delta_bytes_ratio")),
)

DEFAULT_TOLERANCE = 0.5   # +50% over the rolling-median baseline
DEFAULT_FLOOR_MS = 0.25   # plus absolute slack: sub-ms jitter never trips
DEFAULT_FLOOR_RATIO = 0.05  # ratio-series absolute slack (unitless)
DEFAULT_WINDOW = 3        # baseline = median of this many prior rounds
MIN_ROUNDS = 2            # one round has no trajectory to regress against

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _dig(data: dict, path: Tuple[str, ...]):
    node = data
    for key in path:
        if not isinstance(node, dict):
            return None
        node = node.get(key)
    return node


def load_history(
    root: str = ".", include: Optional[List[str]] = None
) -> Tuple[List[dict], List[str]]:
    """Load the committed BENCH_r*.json trajectory (plus any ``include``
    files, e.g. a smoke's structural-latency record) into round dicts
    ``{"n", "path", "data"}`` sorted by round number. Unreadable files
    are problems, not crashes."""
    problems: List[str] = []
    rounds: List[dict] = []
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    for path in [*paths, *(include or [])]:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{path}: unreadable ({e})")
            continue
        match = _ROUND_RE.search(os.path.basename(path))
        n = data.get("n")
        if not isinstance(n, int):
            n = int(match.group(1)) if match else len(rounds) + 1
        rounds.append({"n": n, "path": path, "data": data})
    rounds.sort(key=lambda r: (r["n"], r["path"]))
    return rounds, problems


def validate_round(data: dict, path: str = "") -> List[str]:
    """Schema-check one round snapshot; returns problems (empty =
    valid). The schema is the shape bench.py has always written —
    validated now so a malformed snapshot fails the gate loudly
    instead of silently dropping out of the series."""
    where = path or "<round>"
    problems: List[str] = []
    if not isinstance(data, dict):
        return [f"{where}: round is not an object"]
    if not isinstance(data.get("n"), int) or data["n"] < 1:
        problems.append(f"{where}: 'n' must be a positive integer")
    if not isinstance(data.get("cmd"), str) or not data.get("cmd"):
        problems.append(f"{where}: 'cmd' must be a non-empty string")
    if not isinstance(data.get("rc"), int):
        problems.append(f"{where}: 'rc' must be an integer")
    parsed = data.get("parsed")
    if not isinstance(parsed, dict):
        problems.append(f"{where}: 'parsed' block missing")
        return problems
    if not isinstance(parsed.get("metric"), str) or not parsed.get("metric"):
        problems.append(f"{where}: parsed.metric must be a non-empty string")
    if not isinstance(parsed.get("value"), (int, float)) or isinstance(
        parsed.get("value"), bool
    ):
        problems.append(f"{where}: parsed.value must be a number")
    extra = parsed.get("extra")
    if extra is not None and not isinstance(extra, dict):
        problems.append(f"{where}: parsed.extra must be an object")
        extra = None
    ours = (extra or {}).get("ours")
    if ours is not None:
        if not isinstance(ours, dict):
            problems.append(f"{where}: parsed.extra.ours must be an object")
        else:
            for name, _path in TRACKED:
                value = ours.get(_path[-1])
                if value is None:
                    problems.append(
                        f"{where}: parsed.extra.ours.{_path[-1]} missing"
                    )
                elif not isinstance(value, (int, float)) or isinstance(
                    value, bool
                ) or value < 0:
                    problems.append(
                        f"{where}: parsed.extra.ours.{_path[-1]} must be a "
                        "non-negative number"
                    )
    return problems


def validate_history(rounds: List[dict]) -> List[str]:
    problems: List[str] = []
    seen_n: Dict[int, str] = {}
    for r in rounds:
        problems.extend(validate_round(r["data"], r["path"]))
        prev = seen_n.get(r["n"])
        if prev is not None:
            problems.append(
                f"{r['path']}: duplicate round n={r['n']} (also {prev})"
            )
        seen_n[r["n"]] = r["path"]
    return problems


def series(
    rounds: List[dict],
    tracked: Tuple[Tuple[str, Tuple[str, ...]], ...] = TRACKED,
) -> Dict[str, List[Tuple[int, float]]]:
    """Per-leg time series: tracked metric name -> [(round n, value)].
    Rounds missing a metric simply contribute no point (the gate
    judges the series that exist)."""
    out: Dict[str, List[Tuple[int, float]]] = {}
    for r in rounds:
        for name, path in tracked:
            value = _dig(r["data"], path)
            if isinstance(value, (int, float)) and not isinstance(
                value, bool
            ):
                out.setdefault(name, []).append((r["n"], float(value)))
    return out


def perf_gate(
    rounds: List[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    floor_ms: float = DEFAULT_FLOOR_MS,
    window: int = DEFAULT_WINDOW,
) -> List[str]:
    """The regression gate; returns problems (empty = trajectory
    clean). Each tracked series' newest point is judged against the
    median of the ``window`` points before it."""
    problems: List[str] = []
    if len(rounds) < MIN_ROUNDS:
        return problems  # one point is a datum, not a trajectory
    for name, points in sorted(series(rounds, TRACKED + TRACKED_EVENT).items()):
        if len(points) < MIN_ROUNDS:
            continue
        n, latest = points[-1]
        prior = [v for _, v in points[:-1]][-max(1, window):]
        baseline = statistics.median(prior)
        limit = baseline * (1.0 + tolerance) + floor_ms
        if latest > limit:
            problems.append(
                f"REGRESSION {name}: round {n} measured {latest:.3f}ms "
                f"> {limit:.3f}ms allowed "
                f"(baseline median {baseline:.3f}ms over last "
                f"{len(prior)} round(s), tolerance +{tolerance:.0%} "
                f"+ {floor_ms}ms)"
            )
    # migration series: lower-is-better like the latency series, but
    # the bytes ratio is unitless so its absolute slack is
    # DEFAULT_FLOOR_RATIO, not the millisecond floor
    for name, points in sorted(series(rounds, TRACKED_MIGRATION).items()):
        if len(points) < MIN_ROUNDS:
            continue
        n, latest = points[-1]
        prior = [v for _, v in points[:-1]][-max(1, window):]
        baseline = statistics.median(prior)
        is_ms = name.endswith("_ms")
        floor = floor_ms if is_ms else DEFAULT_FLOOR_RATIO
        unit = "ms" if is_ms else "x"
        limit = baseline * (1.0 + tolerance) + floor
        if latest > limit:
            problems.append(
                f"REGRESSION {name}: round {n} measured "
                f"{latest:.3f}{unit} > {limit:.3f}{unit} allowed "
                f"(baseline median {baseline:.3f}{unit} over last "
                f"{len(prior)} round(s), tolerance +{tolerance:.0%} "
                f"+ {floor}{unit})"
            )
    # serving ratio series: inverted trip (a COLLAPSED ratio is the
    # regression), same rolling-median baseline
    for name, points in sorted(series(rounds, TRACKED_RATIOS).items()):
        if len(points) < MIN_ROUNDS:
            continue
        n, latest = points[-1]
        prior = [v for _, v in points[:-1]][-max(1, window):]
        baseline = statistics.median(prior)
        limit = baseline * (1.0 - tolerance) - DEFAULT_FLOOR_RATIO
        if latest < limit:
            problems.append(
                f"REGRESSION {name}: round {n} measured {latest:.3f}x "
                f"< {limit:.3f}x allowed "
                f"(baseline median {baseline:.3f}x over last "
                f"{len(prior)} round(s), tolerance -{tolerance:.0%} "
                f"- {DEFAULT_FLOOR_RATIO})"
            )
    return problems


def self_test(
    rounds: List[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    floor_ms: float = DEFAULT_FLOOR_MS,
    window: int = DEFAULT_WINDOW,
) -> List[str]:
    """Prove the gate can fail: seed a synthetic regression (the newest
    round's tracked latencies multiplied well past tolerance) and
    assert the gate trips on every tracked series. Returns problems
    with the GATE (empty = the gate demonstrably works)."""
    if len(rounds) < MIN_ROUNDS:
        return ["self-test needs at least two committed rounds"]
    seeded = copy.deepcopy(rounds[-1])
    seeded["n"] = rounds[-1]["n"] + 1
    seeded["path"] = "<seeded-regression>"
    factor = (1.0 + tolerance) * 4
    ours = _dig(seeded["data"], ("parsed", "extra", "ours"))
    if not isinstance(ours, dict):
        return ["self-test: newest round has no parsed.extra.ours block"]
    for name, path in TRACKED:
        value = ours.get(path[-1])
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            ours[path[-1]] = value * factor + 10 * floor_ms
    tripped = perf_gate(
        [*rounds, seeded], tolerance=tolerance,
        floor_ms=floor_ms, window=window,
    )
    problems: List[str] = []
    caught = {p.split()[1].rstrip(":") for p in tripped}
    for name, path in TRACKED:
        if path[-1] in ours and name not in caught:
            problems.append(
                f"self-test: seeded {factor:.1f}x regression on {name} "
                "did NOT trip the gate"
            )
    problems.extend(ratio_self_test(
        rounds, tolerance=tolerance, window=window,
    ))
    problems.extend(event_self_test(
        rounds, tolerance=tolerance, floor_ms=floor_ms, window=window,
    ))
    problems.extend(migration_self_test(
        rounds, tolerance=tolerance, floor_ms=floor_ms, window=window,
    ))
    return problems


def ratio_self_test(
    rounds: List[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    window: int = DEFAULT_WINDOW,
) -> List[str]:
    """Prove the inverted (higher-is-better) gate can fail too: seed a
    collapsed serving ratio and assert it trips. Uses the committed
    trajectory when it carries serving points; otherwise a synthetic
    three-round trajectory — the committed history predates the
    serving legs, and a gate whose failure mode is only provable on
    future data is not yet a gate."""
    name, path = TRACKED_RATIOS[0]  # serving_prefill_reduction
    base = [r for r in rounds if isinstance(_dig(r["data"], path),
                                            (int, float))]
    if len(base) >= MIN_ROUNDS:
        trajectory = base
        seeded = copy.deepcopy(base[-1])
        seeded["n"] = base[-1]["n"] + 1
    else:
        trajectory = []
        for i, value in enumerate((4.0, 4.2, 4.1)):
            data: dict = {}
            node = data
            for key in path[:-1]:
                node = node.setdefault(key, {})
            node[path[-1]] = value
            trajectory.append({
                "n": i + 1, "path": f"<synthetic-{i + 1}>",
                "data": data,
            })
        seeded = copy.deepcopy(trajectory[-1])
        seeded["n"] = trajectory[-1]["n"] + 1
    seeded["path"] = "<seeded-ratio-regression>"
    node = seeded["data"]
    for key in path[:-1]:
        node = node.setdefault(key, {})
    collapsed = float(node[path[-1]]) * (1.0 - tolerance) / 4.0
    node[path[-1]] = collapsed
    tripped = perf_gate(
        [*trajectory, seeded], tolerance=tolerance, window=window,
    )
    if not any(f"REGRESSION {name}" in p for p in tripped):
        return [
            f"self-test: seeded collapse of {name} to {collapsed:.3f}x "
            "did NOT trip the gate"
        ]
    return []


def migration_self_test(
    rounds: List[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    floor_ms: float = DEFAULT_FLOOR_MS,
    window: int = DEFAULT_WINDOW,
) -> List[str]:
    """Prove the migration gate can fail: seed a cutover-downtime
    blowup (pre-copy silently degrading to a full-checkpoint pause)
    and a delta-bytes-ratio blowup (delta streaming shipping most of
    the state again) and assert each trips. Uses the committed
    trajectory once it carries migration_core points; until then a
    synthetic three-round trajectory — same rationale as the other
    late-entry series' self-tests."""
    problems: List[str] = []
    synthetic = {
        "migration_downtime_ms": (180.0, 220.0, 200.0),
        "migration_delta_bytes_ratio": (0.12, 0.15, 0.13),
    }
    for name, path in TRACKED_MIGRATION:
        base = [r for r in rounds if isinstance(_dig(r["data"], path),
                                                (int, float))]
        if len(base) >= MIN_ROUNDS:
            trajectory = base
            seeded = copy.deepcopy(base[-1])
            seeded["n"] = base[-1]["n"] + 1
        else:
            trajectory = []
            for i, value in enumerate(synthetic[name]):
                data: dict = {}
                node = data
                for key in path[:-1]:
                    node = node.setdefault(key, {})
                node[path[-1]] = value
                trajectory.append({
                    "n": i + 1, "path": f"<synthetic-{i + 1}>",
                    "data": data,
                })
            seeded = copy.deepcopy(trajectory[-1])
            seeded["n"] = trajectory[-1]["n"] + 1
        seeded["path"] = "<seeded-migration-regression>"
        node = seeded["data"]
        for key in path[:-1]:
            node = node.setdefault(key, {})
        floor = floor_ms if name.endswith("_ms") else DEFAULT_FLOOR_RATIO
        blown = float(node[path[-1]]) * (1.0 + tolerance) * 4 + 10 * floor
        node[path[-1]] = blown
        tripped = perf_gate(
            [*trajectory, seeded], tolerance=tolerance,
            floor_ms=floor_ms, window=window,
        )
        if not any(f"REGRESSION {name}" in p for p in tripped):
            problems.append(
                f"self-test: seeded blowup of {name} to {blown:.3f} "
                "did NOT trip the gate"
            )
    return problems


def event_self_test(
    rounds: List[dict],
    tolerance: float = DEFAULT_TOLERANCE,
    floor_ms: float = DEFAULT_FLOOR_MS,
    window: int = DEFAULT_WINDOW,
) -> List[str]:
    """Prove the event-core gate can fail: for each event series, seed
    a blown-up latency and assert it trips. Uses the committed
    trajectory once it carries event-core points; until then a
    synthetic three-round trajectory — same rationale as
    ratio_self_test: a gate whose failure mode is only provable on
    future data is not yet a gate."""
    problems: List[str] = []
    for name, path in TRACKED_EVENT:
        base = [r for r in rounds if isinstance(_dig(r["data"], path),
                                                (int, float))]
        if len(base) >= MIN_ROUNDS:
            trajectory = base
            seeded = copy.deepcopy(base[-1])
            seeded["n"] = base[-1]["n"] + 1
        else:
            trajectory = []
            for i, value in enumerate((20.0, 22.0, 21.0)):
                data: dict = {}
                node = data
                for key in path[:-1]:
                    node = node.setdefault(key, {})
                node[path[-1]] = value
                trajectory.append({
                    "n": i + 1, "path": f"<synthetic-{i + 1}>",
                    "data": data,
                })
            seeded = copy.deepcopy(trajectory[-1])
            seeded["n"] = trajectory[-1]["n"] + 1
        seeded["path"] = "<seeded-event-regression>"
        node = seeded["data"]
        for key in path[:-1]:
            node = node.setdefault(key, {})
        blown = float(node[path[-1]]) * (1.0 + tolerance) * 4 + 10 * floor_ms
        node[path[-1]] = blown
        tripped = perf_gate(
            [*trajectory, seeded], tolerance=tolerance,
            floor_ms=floor_ms, window=window,
        )
        if not any(f"REGRESSION {name}" in p for p in tripped):
            problems.append(
                f"self-test: seeded blowup of {name} to {blown:.3f}ms "
                "did NOT trip the gate"
            )
    return problems
