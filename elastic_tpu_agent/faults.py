"""Deterministic fault injection: named failpoints, armed only in tests.

The agent is a node-critical DaemonSet with ~8 background loops; proving
that each one recovers from a crash needs a way to *cause* the crash
deterministically — monkeypatching from tests cannot reach a loop that
is already running inside the real manager. This registry is that seam:
hot paths call ``faults.fire("<point>")`` which is a near-free no-op
until a test (or a developer via ``ELASTIC_TPU_FAULTS`` /
``--faults``) arms the point with a behavior spec.

Specs (``<kind>[:<arg>]``):

- ``raise`` / ``raise:N`` / ``raise-once`` — raise FaultError at the
  point, every time / the next N times / once. Exercises the *handled*
  error paths (loops that catch-and-retry, rollback on bind failure).
- ``delay:SECONDS`` — sleep at the point (slow apiserver / slow disk).
- ``die-thread`` / ``die-thread:N`` — raise DieThread, a BaseException
  that sails past every ``except Exception`` trap, killing the calling
  thread the way an uncaught bug would. This is what proves the
  supervisor actually restarts a loop: ``raise`` alone is absorbed by
  the loops' own catch-and-continue guards.
- ``notice`` / ``notice:N`` — a consumable signal rather than a fault:
  ``fire()`` ignores it; a poll site asks ``faults.check(point)``
  which returns True (and consumes one charge) while armed. This is
  how chaos tests inject external notifications the code merely polls
  for — e.g. ``drain.preempt-notice=notice:1`` makes the drain
  orchestrator see exactly one spot-preemption notice.

Arming is test-only: production deployments never set the env knob, and
an unarmed ``fire()`` is a dict-emptiness check. Points are plain
dotted names (``sitter.relist``, ``storage.save``, ``gc.sweep``, ...);
firing an unknown point is always safe.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class FaultError(RuntimeError):
    """The exception a ``raise``-kind failpoint throws (an ordinary
    Exception: the code under test is expected to handle it)."""


class DieThread(BaseException):
    """Thrown by ``die-thread`` failpoints. Deliberately a BaseException:
    it must escape the broad ``except Exception`` traps that the agent's
    loops use for *handled* failures, so the thread actually dies and
    the supervision layer is what has to save it."""


class _Fault:
    __slots__ = ("kind", "arg", "remaining", "fired")

    def __init__(self, kind: str, arg: Optional[float], remaining: Optional[int]):
        self.kind = kind
        self.arg = arg
        self.remaining = remaining  # None = unlimited
        self.fired = 0


def _parse_spec(spec: str) -> _Fault:
    spec = spec.strip()
    if spec == "raise-once":
        return _Fault("raise", None, 1)
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    if kind == "raise":
        n = int(arg) if arg else None
        return _Fault("raise", None, n)
    if kind == "delay":
        if not arg:
            raise ValueError("delay fault needs seconds: delay:0.5")
        return _Fault("delay", float(arg), None)
    if kind == "die-thread":
        n = int(arg) if arg else None
        return _Fault("die-thread", None, n)
    if kind == "notice":
        n = int(arg) if arg else None
        return _Fault("notice", None, n)
    raise ValueError(
        f"unknown fault spec {spec!r} "
        "(want raise[-once|:N] | delay:S | die-thread[:N] | notice[:N])"
    )


class FaultRegistry:
    """Thread-safe map of failpoint name -> armed behavior."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._armed: Dict[str, _Fault] = {}
        self.total_fired = 0

    def arm(self, point: str, spec: str) -> None:
        fault = _parse_spec(spec)
        with self._lock:
            self._armed[point] = fault
        logger.warning("FAULT ARMED (test-only): %s=%s", point, spec)

    def arm_spec(self, multi: str) -> None:
        """Arm from a comma-separated ``point=spec,point=spec`` string
        (the ELASTIC_TPU_FAULTS / --faults format)."""
        for part in multi.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, spec = part.partition("=")
            if not spec:
                raise ValueError(f"bad fault entry {part!r} (want point=spec)")
            self.arm(point.strip(), spec)

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._armed.clear()
            else:
                self._armed.pop(point, None)

    def armed(self) -> Dict[str, str]:
        with self._lock:
            return {
                p: f"{f.kind}"
                + (f":{f.remaining}" if f.remaining is not None else "")
                for p, f in self._armed.items()
            }

    def fired(self, point: str) -> int:
        """How many times ``point`` fired while armed (assertion helper;
        resets when the point is re-armed)."""
        with self._lock:
            fault = self._armed.get(point)
            return fault.fired if fault is not None else 0

    def check(self, point: str) -> bool:
        """Consume one charge of a ``notice``-armed point: True while
        armed, False otherwise (and always False for non-notice kinds —
        ``fire()`` owns those). Poll sites use this to receive injected
        external signals deterministically."""
        with self._lock:
            fault = self._armed.get(point)
            if fault is None or fault.kind != "notice":
                return False
            fault.fired += 1
            self.total_fired += 1
            if fault.remaining is not None:
                fault.remaining -= 1
                if fault.remaining <= 0:
                    del self._armed[point]
        logger.warning("failpoint %s: notice consumed", point)
        return True

    def fire(self, point: str) -> None:
        with self._lock:
            fault = self._armed.get(point)
            if fault is None or fault.kind == "notice":
                return
            fault.fired += 1
            self.total_fired += 1
            if fault.remaining is not None:
                fault.remaining -= 1
                if fault.remaining <= 0:
                    del self._armed[point]
            kind, arg = fault.kind, fault.arg
        # act outside the lock: delay must not serialize other points
        if kind == "delay":
            time.sleep(arg)
            return
        if kind == "die-thread":
            logger.warning("failpoint %s: killing thread %s", point,
                           threading.current_thread().name)
            raise DieThread(f"failpoint {point}")
        logger.warning("failpoint %s: raising FaultError", point)
        raise FaultError(f"injected failure at {point}")


_registry = FaultRegistry()


def get_registry() -> FaultRegistry:
    return _registry


def fire(point: str) -> None:
    """Module-level fast path: no-op unless the point is armed."""
    if not _registry._armed:  # unlocked emptiness check: hot-path cheap
        return
    _registry.fire(point)


def check(point: str) -> bool:
    """Module-level fast path for notice points (see
    :meth:`FaultRegistry.check`): False unless armed with ``notice``."""
    if not _registry._armed:
        return False
    return _registry.check(point)


class armed:
    """Context manager for tests: arm on enter, disarm on exit.

    >>> with faults.armed("gc.sweep", "die-thread:1"):
    ...     trigger_gc()
    """

    def __init__(self, point: str, spec: str) -> None:
        self._point = point
        _registry.arm(point, spec)

    def __enter__(self) -> "armed":
        return self

    def __exit__(self, *exc) -> None:
        _registry.disarm(self._point)
