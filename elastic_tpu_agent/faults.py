"""Deterministic fault injection: named failpoints, armed only in tests.

The agent is a node-critical DaemonSet with ~8 background loops; proving
that each one recovers from a crash needs a way to *cause* the crash
deterministically — monkeypatching from tests cannot reach a loop that
is already running inside the real manager. This registry is that seam:
hot paths call ``faults.fire("<point>")`` which is a near-free no-op
until a test (or a developer via ``ELASTIC_TPU_FAULTS`` /
``--faults``) arms the point with a behavior spec.

Specs (``<kind>[:<arg>]``):

- ``raise`` / ``raise:N`` / ``raise-once`` — raise FaultError at the
  point, every time / the next N times / once. Exercises the *handled*
  error paths (loops that catch-and-retry, rollback on bind failure).
- ``delay:SECONDS`` — sleep at the point (slow apiserver / slow disk).
- ``die-thread`` / ``die-thread:N`` — raise DieThread, a BaseException
  that sails past every ``except Exception`` trap, killing the calling
  thread the way an uncaught bug would. This is what proves the
  supervisor actually restarts a loop: ``raise`` alone is absorbed by
  the loops' own catch-and-continue guards.
- ``notice`` / ``notice:N`` — a consumable signal rather than a fault:
  ``fire()`` ignores it; a poll site asks ``faults.check(point)``
  which returns True (and consumes one charge) while armed. This is
  how chaos tests inject external notifications the code merely polls
  for — e.g. ``drain.preempt-notice=notice:1`` makes the drain
  orchestrator see exactly one spot-preemption notice.

Brownout kinds (chaos-matrix material, sim/chaos.py): deterministic
one-shots cannot express a *flaky* dependency — a disk that fails one
write in three, an RPC that is slow by a different amount every call, a
dependency that is only broken for a while. These kinds are seeded, so
a chaos program replayed from the same seed trips the same calls:

- ``prob:P:SEED`` — raise FaultError with probability ``P`` per fire,
  decided by a private ``random.Random(SEED)`` stream (``prob:0.3:7``).
  Fires that do not trip consume nothing; ``fired`` counts trips only.
- ``delay-range:LO:HI:SEED`` — sleep a uniform duration in ``[LO, HI]``
  seconds per fire, drawn from the seeded stream
  (``delay-range:0.001:0.05:7``) — jittery-slow, not fixed-slow.
- ``window:START:DUR`` — raise FaultError only while the registry
  clock's monotonic time is within ``[armed_at+START, armed_at+START+
  DUR)`` — a brownout that begins and ends on schedule. Outside the
  window the point is a no-op (and never expires); chaos programs
  disarm it explicitly. The registry's ``clock`` attribute is the
  injectable time source (tests hand in a ManualClock).

Arming is test-only: production deployments never set the env knob, and
an unarmed ``fire()`` is a dict-emptiness check. Points are plain
dotted names (``sitter.relist``, ``storage.save``, ``gc.sweep``, ...);
firing an unknown point is always safe.

Full spec grammar::

    spec      := "raise" | "raise-once" | "raise:" N
               | "delay:" SECONDS
               | "die-thread" [":" N]
               | "notice" [":" N]
               | "prob:" P [":" SEED]
               | "delay-range:" LO ":" HI [":" SEED]
               | "window:" START ":" DUR
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, Optional

from .common import SYSTEM_CLOCK

logger = logging.getLogger(__name__)


class FaultError(RuntimeError):
    """The exception a ``raise``-kind failpoint throws (an ordinary
    Exception: the code under test is expected to handle it)."""


class DieThread(BaseException):
    """Thrown by ``die-thread`` failpoints. Deliberately a BaseException:
    it must escape the broad ``except Exception`` traps that the agent's
    loops use for *handled* failures, so the thread actually dies and
    the supervision layer is what has to save it."""


class _Fault:
    __slots__ = (
        "kind", "arg", "remaining", "fired",
        "rng", "lo", "hi", "win_start", "win_dur", "armed_at",
    )

    def __init__(self, kind: str, arg: Optional[float], remaining: Optional[int]):
        self.kind = kind
        self.arg = arg
        self.remaining = remaining  # None = unlimited
        self.fired = 0
        # seeded-kind state (prob / delay-range / window)
        self.rng: Optional[random.Random] = None
        self.lo = 0.0
        self.hi = 0.0
        self.win_start = 0.0
        self.win_dur = 0.0
        self.armed_at = 0.0  # registry clock at arm(); window anchor


def _parse_spec(spec: str) -> _Fault:
    spec = spec.strip()
    if spec == "raise-once":
        return _Fault("raise", None, 1)
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    if kind == "raise":
        n = int(arg) if arg else None
        return _Fault("raise", None, n)
    if kind == "delay":
        if not arg:
            raise ValueError("delay fault needs seconds: delay:0.5")
        return _Fault("delay", float(arg), None)
    if kind == "die-thread":
        n = int(arg) if arg else None
        return _Fault("die-thread", None, n)
    if kind == "notice":
        n = int(arg) if arg else None
        return _Fault("notice", None, n)
    if kind == "prob":
        parts = arg.split(":") if arg else []
        if not parts or not parts[0]:
            raise ValueError("prob fault needs a probability: prob:0.3:7")
        p = float(parts[0])
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"prob fault probability out of [0,1]: {p}")
        fault = _Fault("prob", p, None)
        fault.rng = random.Random(int(parts[1]) if len(parts) > 1 else 0)
        return fault
    if kind == "delay-range":
        parts = arg.split(":") if arg else []
        if len(parts) < 2:
            raise ValueError(
                "delay-range fault needs bounds: delay-range:0.001:0.05:7"
            )
        fault = _Fault("delay-range", None, None)
        fault.lo, fault.hi = float(parts[0]), float(parts[1])
        if fault.hi < fault.lo:
            raise ValueError(
                f"delay-range bounds inverted: {fault.lo} > {fault.hi}"
            )
        fault.rng = random.Random(int(parts[2]) if len(parts) > 2 else 0)
        return fault
    if kind == "window":
        parts = arg.split(":") if arg else []
        if len(parts) != 2:
            raise ValueError("window fault needs start:dur: window:1.0:2.5")
        fault = _Fault("window", None, None)
        fault.win_start, fault.win_dur = float(parts[0]), float(parts[1])
        if fault.win_dur < 0:
            raise ValueError(f"window duration negative: {fault.win_dur}")
        return fault
    raise ValueError(
        f"unknown fault spec {spec!r} "
        "(want raise[-once|:N] | delay:S | die-thread[:N] | notice[:N] | "
        "prob:P[:SEED] | delay-range:LO:HI[:SEED] | window:START:DUR)"
    )


class FaultRegistry:
    """Thread-safe map of failpoint name -> armed behavior.

    ``clock`` is the injectable time source ``window`` kinds anchor to
    (monotonic); chaos tests hand in a ManualClock and advance it."""

    def __init__(self, clock=SYSTEM_CLOCK) -> None:
        self._lock = threading.Lock()
        self._armed: Dict[str, _Fault] = {}
        self.total_fired = 0
        self.clock = clock

    def arm(self, point: str, spec: str) -> None:
        fault = _parse_spec(spec)
        fault.armed_at = self.clock.monotonic()
        with self._lock:
            self._armed[point] = fault
        logger.warning("FAULT ARMED (test-only): %s=%s", point, spec)

    def arm_spec(self, multi: str) -> None:
        """Arm from a comma-separated ``point=spec,point=spec`` string
        (the ELASTIC_TPU_FAULTS / --faults format)."""
        for part in multi.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, spec = part.partition("=")
            if not spec:
                raise ValueError(f"bad fault entry {part!r} (want point=spec)")
            self.arm(point.strip(), spec)

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._armed.clear()
            else:
                self._armed.pop(point, None)

    def armed(self) -> Dict[str, str]:
        with self._lock:
            return {
                p: f"{f.kind}"
                + (f":{f.remaining}" if f.remaining is not None else "")
                for p, f in self._armed.items()
            }

    def fired(self, point: str) -> int:
        """How many times ``point`` fired while armed (assertion helper;
        resets when the point is re-armed)."""
        with self._lock:
            fault = self._armed.get(point)
            return fault.fired if fault is not None else 0

    def check(self, point: str) -> bool:
        """Consume one charge of a ``notice``-armed point: True while
        armed, False otherwise (and always False for non-notice kinds —
        ``fire()`` owns those). Poll sites use this to receive injected
        external signals deterministically."""
        with self._lock:
            fault = self._armed.get(point)
            if fault is None or fault.kind != "notice":
                return False
            fault.fired += 1
            self.total_fired += 1
            if fault.remaining is not None:
                fault.remaining -= 1
                if fault.remaining <= 0:
                    del self._armed[point]
        logger.warning("failpoint %s: notice consumed", point)
        return True

    def fire(self, point: str) -> None:
        with self._lock:
            fault = self._armed.get(point)
            if fault is None or fault.kind == "notice":
                return
            # Seeded/windowed kinds decide whether this call trips at
            # all BEFORE any charge is consumed: a prob fire that does
            # not trip (or a window fire outside the window) must leave
            # ``fired`` counting trips only — that is what chaos
            # verdicts assert against.
            if fault.kind == "prob":
                if fault.rng.random() >= fault.arg:
                    return
            elif fault.kind == "window":
                dt = self.clock.monotonic() - fault.armed_at
                if not (
                    fault.win_start <= dt < fault.win_start + fault.win_dur
                ):
                    return
            fault.fired += 1
            self.total_fired += 1
            if fault.remaining is not None:
                fault.remaining -= 1
                if fault.remaining <= 0:
                    del self._armed[point]
            kind, arg = fault.kind, fault.arg
            if kind == "delay-range":
                arg = fault.lo + fault.rng.random() * (fault.hi - fault.lo)
        # act outside the lock: delay must not serialize other points
        if kind in ("delay", "delay-range"):
            time.sleep(arg)
            return
        if kind == "die-thread":
            logger.warning("failpoint %s: killing thread %s", point,
                           threading.current_thread().name)
            raise DieThread(f"failpoint {point}")
        logger.warning("failpoint %s: raising FaultError", point)
        raise FaultError(f"injected failure at {point}")


_registry = FaultRegistry()


def get_registry() -> FaultRegistry:
    return _registry


def fire(point: str) -> None:
    """Module-level fast path: no-op unless the point is armed."""
    if not _registry._armed:  # unlocked emptiness check: hot-path cheap
        return
    _registry.fire(point)


def check(point: str) -> bool:
    """Module-level fast path for notice points (see
    :meth:`FaultRegistry.check`): False unless armed with ``notice``."""
    if not _registry._armed:
        return False
    return _registry.check(point)


class armed:
    """Context manager for tests: arm on enter, disarm on exit.

    >>> with faults.armed("gc.sweep", "die-thread:1"):
    ...     trigger_gc()
    """

    def __init__(self, point: str, spec: str) -> None:
        self._point = point
        _registry.arm(point, spec)

    def __enter__(self) -> "armed":
        return self

    def __exit__(self, *exc) -> None:
        _registry.disarm(self._point)
