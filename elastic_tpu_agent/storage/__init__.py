from .store import Storage, StorageError

__all__ = ["Storage", "StorageError"]
