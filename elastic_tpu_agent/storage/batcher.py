"""Group-commit write batcher: one durable COMMIT serves many writers.

Unbatched, every storage write pays its own ``COMMIT`` — at fleet churn
that is 5+ sqlite commits per bind (intent journal, checkpoint, intent
commit, two timeline events), and the scale harness measures the
write amplification directly. This batcher coalesces them: writers
execute their statements on the shared connection as before (so
same-connection reads stay read-your-writes), then register with the
batcher instead of committing; a flusher thread commits the open
transaction once per flush window, covering every write that joined it.

Crash-consistency is a property of WHO WAITS, not of the batching:

- **sync writers** (bind checkpoints, intent journals, agent_state
  transitions) block until the group commit that covers their write has
  durably landed — exactly the durability they had with a private
  commit, minus the per-write fsync. The bind's commit marker is still
  on disk before PreStartContainer returns.
- **async writers** (timeline events, intent-commit row drops) return
  immediately and ride the next flush. Both are non-load-bearing by
  construction: the timeline journal is observability (emit already
  swallows failures), and a lost intent-commit leaves an open intent
  whose checkpointed record IS the commit marker — the reconciler's
  ``intent_committed`` repair class resolves it, the same crash window
  ``bind.post_checkpoint`` has always exercised.

A failed flush rolls the whole open transaction back: every sync waiter
covered by it gets a StorageError (their write did NOT land), and the
owner's ``on_rollback`` callback drops any caches that may now hold
rolled-back state.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from .. import faults

logger = logging.getLogger(__name__)

# How long a sync writer will wait for its covering group commit before
# giving up (the flusher runs every few ms; hitting this means the
# flusher thread is dead or the disk has wedged outright).
SYNC_WAIT_TIMEOUT_S = 30.0

# Failed-flush error records kept around for late waiters; commits are
# strictly ordered so anything older than this many generations has no
# waiter left.
_ERROR_KEEP_GENS = 64


class GroupCommitError(RuntimeError):
    """The group commit covering a sync write failed (the write rolled
    back with it) or could not be confirmed in time."""


class GroupCommitBatcher:
    """Coalesces transaction commits across writers into one flush per
    window.

    ``commit_fn`` / ``rollback_fn`` are supplied by the owning Storage
    and must take the storage lock themselves; the batcher NEVER holds
    its own condition while calling them (writers hold the storage lock
    when they call :meth:`mark_dirty`, so the inverse ordering would
    deadlock).
    """

    def __init__(
        self,
        commit_fn: Callable[[], None],
        rollback_fn: Callable[[], None],
        window_s: float,
        name: str = "storage",
        lock=None,
        on_commit: Optional[Callable[[], None]] = None,
    ) -> None:
        self._commit_fn = commit_fn
        self._rollback_fn = rollback_fn
        # Post-commit hook, called on the flusher thread AFTER a group
        # commit durably lands (and after waiters are released): the
        # owning Storage publishes its store-change notifications here,
        # so subscribers only ever hear about state that is already on
        # disk. Exceptions are contained — observability must never
        # fail a commit that already succeeded.
        self._on_commit = on_commit
        # The OWNER's statement lock (Storage._lock): writers execute
        # their statements and call mark_dirty under it. The failure
        # path must hold it too — a rollback discards EVERY uncommitted
        # statement, including ones writers executed after the flusher
        # claimed its generation, so the set of generations to fail can
        # only be decided with writers excluded.
        self._owner_lock = lock if lock is not None else threading.Lock()
        self.window_s = max(0.0005, float(window_s))
        self._name = name
        self._cond = threading.Condition()
        self._gen = 0            # generation currently accepting writes
        self._committed_gen = -1  # newest durably committed generation
        self._pending = 0        # writes in the accepting generation
        self._sync_pending = False  # a blocked waiter is in this gen
        self._errors: Dict[int, BaseException] = {}
        self._stopping = False
        # -- stats (write_stats() / the scale harness read these) ------
        self.commits_total = 0
        self.writes_total = 0
        self.sync_waits_total = 0
        self.flush_failures_total = 0
        self.max_batched_writes = 0
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name=f"{name}-group-commit",
        )
        self._thread.start()

    # -- writer side ----------------------------------------------------------

    def mark_dirty(self, sync: bool = False) -> int:
        """Register one executed-but-uncommitted write; returns the
        generation whose commit will cover it. Callers may hold the
        storage lock (the batcher takes only its own condition).

        ``sync=True`` marks a write whose caller will block in
        :meth:`wait`: the flusher commits IMMEDIATELY instead of riding
        out the window, so load-bearing writes pay ~one commit of
        latency, not the window — grouping still happens because writers
        arriving while that commit runs land in the next generation
        together, and async traffic piggybacks for free."""
        with self._cond:
            self._pending += 1
            self.writes_total += 1
            if sync:
                self._sync_pending = True
            gen = self._gen
            self._cond.notify_all()
            return gen

    def wait(self, gen: int, timeout_s: float = SYNC_WAIT_TIMEOUT_S) -> None:
        """Block until generation ``gen`` has durably committed; raises
        GroupCommitError when its flush failed (the write rolled back)
        or the flusher never confirmed it."""
        import time

        from ..tracing import get_tracer

        deadline = time.monotonic() + timeout_s
        # storage_flush_wait rides inside the bind's "checkpoint" span;
        # the latency observatory attributes it innermost-first, so the
        # durability stall shows up as storage_sync, not as mystery
        # checkpoint time. No-op (two monotonic reads) without a trace.
        with get_tracer().span("storage_flush_wait", gen=gen), self._cond:
            self.sync_waits_total += 1
            while self._committed_gen < gen and gen not in self._errors:
                if self._stopping and not self._thread.is_alive():
                    # The flusher drains everything pending before it
                    # exits; a dead flusher with our generation still
                    # unconfirmed means the write never landed.
                    raise GroupCommitError(
                        f"{self._name}: batcher stopped before "
                        f"generation {gen} committed"
                    )
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GroupCommitError(
                        f"{self._name}: group commit for generation "
                        f"{gen} not confirmed within {timeout_s:.0f}s"
                    )
                self._cond.wait(timeout=min(remaining, 0.5))
            err = self._errors.get(gen)
        if err is not None:
            raise GroupCommitError(
                f"{self._name}: group commit failed; write rolled back "
                f"({err})"
            ) from err

    # -- flusher side ----------------------------------------------------------

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while self._pending == 0 and not self._stopping:
                    self._cond.wait()
                if self._stopping and self._pending == 0:
                    return
            # Window: let async traffic pile into this generation — but
            # a sync writer showing up (or already waiting) flushes NOW;
            # its caller is blocked on this commit.
            import time

            end = time.monotonic() + self.window_s
            with self._cond:
                while not self._sync_pending and not self._stopping:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            self._flush_once()

    def _flush_once(self) -> None:
        with self._cond:
            if self._pending == 0:
                return
            gen, batched = self._gen, self._pending
            self._gen += 1
            self._pending = 0
            self._sync_pending = False
        err: Optional[BaseException] = None
        try:
            # Chaos seam (sim/chaos.py): a failed flush here exercises
            # the whole-transaction rollback + sync-waiter error path —
            # the "disk refused the group commit" story. Arm with e.g.
            # ``storage.batch_flush=prob:0.3:7`` for a flaky disk.
            faults.fire("storage.batch_flush")
            self._commit_fn()
        except BaseException as e:  # noqa: BLE001 - surfaced to waiters
            err = e
            self._fail_flush(gen, batched, e)
            return
        with self._cond:
            self.commits_total += 1
            self.max_batched_writes = max(
                self.max_batched_writes, batched
            )
            # NOTE: the successful commit may also have covered
            # statements already executed for the NEXT generation (a
            # writer can slip in between the claim above and the
            # commit). Early durability is harmless; its waiter simply
            # waits one more flush.
            self._committed_gen = gen
            self._cond.notify_all()
        if self._on_commit is not None:
            try:
                self._on_commit()
            except Exception:  # noqa: BLE001 - never fail a landed commit
                logger.exception("%s: post-commit hook failed", self._name)

    def _fail_flush(self, gen: int, batched: int, err: BaseException) -> None:
        """A failed commit rolls back the WHOLE open transaction — not
        just generation ``gen``: writers that executed statements after
        the flusher claimed ``gen`` were assigned ``gen+1``, but their
        statements died in the same rollback. Holding the owner's
        statement lock across rollback + bookkeeping excludes writers,
        so every generation up to the CURRENT accepting one at that
        instant is failed (its waiters get the error instead of a
        silent success from a later, now-empty commit) and a fresh
        generation starts clean."""
        with self._owner_lock:
            try:
                self._rollback_fn()
            except Exception:  # noqa: BLE001 - rollback is best-effort
                logger.exception("%s: rollback after failed group commit "
                                 "also failed", self._name)
            with self._cond:
                self.flush_failures_total += 1
                failed_through = self._gen
                for g in range(gen, failed_through + 1):
                    self._errors[g] = err
                self._gen = failed_through + 1
                self._pending = 0  # those statements died in the rollback
                self._sync_pending = False
                for old in [
                    g for g in self._errors
                    if g < failed_through - _ERROR_KEEP_GENS
                ]:
                    del self._errors[old]
                logger.warning(
                    "%s: group commit of %d write(s) failed "
                    "(generations %d..%d rolled back): %s",
                    self._name, batched, gen, failed_through, err,
                )
                self._committed_gen = failed_through
                self._cond.notify_all()

    def flush(self, timeout_s: float = SYNC_WAIT_TIMEOUT_S) -> None:
        """Commit everything currently pending and wait for it (tests,
        Storage.close())."""
        with self._cond:
            if self._pending == 0:
                return
            gen = self._gen
            # Force an immediate flush: without this the flusher would
            # ride out its whole window first, stalling close() by up
            # to window_s for no one's benefit.
            self._sync_pending = True
            self._cond.notify_all()
        self.wait(gen, timeout_s=timeout_s)

    def stop(self, timeout_s: float = SYNC_WAIT_TIMEOUT_S) -> None:
        """Flush pending writes, then stop the flusher thread."""
        try:
            self.flush(timeout_s=timeout_s)
        except GroupCommitError:
            pass  # surfaced to any sync waiters already
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)

    def stats(self) -> dict:
        with self._cond:
            return {
                "window_s": self.window_s,
                "commits_total": self.commits_total,
                "writes_total": self.writes_total,
                "sync_waits_total": self.sync_waits_total,
                "flush_failures_total": self.flush_failures_total,
                "max_batched_writes": self.max_batched_writes,
                "pending": self._pending,
            }
