"""Checkpoint store: pod -> container -> device bindings, on-disk.

Capability parity with the reference's ``pkg/storage/storage.go`` (BoltDB
single bucket ``root``, key ``namespace/name``, JSON value — SURVEY.md §1
L6). We use SQLite (stdlib, ACID, single file, WAL) as the embedded KV
engine; the DB file lives on a hostPath so state survives agent restarts,
enabling Restore() (which the reference declared but never implemented,
manager.go:17-21).
"""

from __future__ import annotations

import json
import logging
import os
import sqlite3
import threading
import time
from typing import Callable, Iterator, Optional, Tuple

from .. import events as events_mod
from .. import faults
from ..common import StripedLockSet
from ..types import PodInfo
from .batcher import GroupCommitBatcher, GroupCommitError

logger = logging.getLogger(__name__)


class StorageError(Exception):
    pass


# How long SQLite itself waits on a locked database before erroring
# (PRAGMA busy_timeout, milliseconds). A slow WAL checkpoint — or the
# node-doctor reading the db file while a bind commits — must not fail
# the bind.
BUSY_TIMEOUT_MS = 5000
# Belt and braces on top of busy_timeout: one application-level retry
# for transient "database is locked" errors before the write becomes a
# StorageError.
_LOCKED_RETRY_DELAY_S = 0.05

# sqlite3's per-connection compiled-statement cache is keyed by the SQL
# text; the bind checkpoint/mutate path runs the same handful of
# statements thousands of times per churn burst, so the hot SQL lives
# here as module constants (one string object each — guaranteed cache
# hits) and the connection's cache is sized so cold diagnostics queries
# can never evict the hot set. Uses are counted per statement in
# write_stats()["prepared_uses"].
_STMT_CACHE_SIZE = 256
_SQL_SAVE_POD = (
    "INSERT INTO pods(key, value) VALUES(?, ?) "
    "ON CONFLICT(key) DO UPDATE SET value=excluded.value"
)
_SQL_DELETE_POD = "DELETE FROM pods WHERE key=?"
_SQL_INSERT_INTENT = (
    "INSERT INTO bind_intents"
    "(pod_key, container, resource, hash, payload, "
    "created_ts) VALUES(?, ?, ?, ?, ?, ?)"
)
_SQL_DELETE_INTENT = "DELETE FROM bind_intents WHERE id=?"
_SQL_UPSERT_STATE = (
    "INSERT INTO agent_state(key, value, updated_ts) "
    "VALUES(?, ?, ?) ON CONFLICT(key) DO UPDATE SET "
    "value=excluded.value, updated_ts=excluded.updated_ts"
)
_PREPARED = {
    _SQL_SAVE_POD: "save_pod",
    _SQL_DELETE_POD: "delete_pod",
    _SQL_INSERT_INTENT: "insert_intent",
    _SQL_DELETE_INTENT: "delete_intent",
    _SQL_UPSERT_STATE: "upsert_state",
}


_SCHEMA = """
CREATE TABLE IF NOT EXISTS pods (
    key   TEXT PRIMARY KEY,   -- "namespace/name"
    value TEXT NOT NULL       -- PodInfo JSON
);
"""

# Write-ahead bind intent journal (reconciler.py). A bind writes an
# intent row BEFORE its first side effect (virtual-node creation) and
# removes it only after the allocation record has been checkpointed —
# the pods-table record IS the commit marker, so a surviving journal
# row means "this bind never (provably) completed": the reconciler
# replays or rolls it back at the next boot/tick. Kept in the same
# SQLite file so the intent write and the checkpoint share one durable
# store (one fsync domain, one thing to hostPath-mount).
_JOURNAL_SCHEMA = """
CREATE TABLE IF NOT EXISTS bind_intents (
    id         INTEGER PRIMARY KEY AUTOINCREMENT,
    pod_key    TEXT NOT NULL,    -- "namespace/name"
    container  TEXT NOT NULL,
    resource   TEXT NOT NULL,
    hash       TEXT NOT NULL,    -- device-set hash the bind will commit
    payload    TEXT NOT NULL,    -- JSON: device_ids/chip_indexes/planned_link_ids
    created_ts REAL NOT NULL     -- wall clock, for open-intent age display
);
"""

# Durable agent-lifecycle state (drain.py journals its state machine
# here, same crash-consistency discipline as bind intents: the row is
# written BEFORE the side effects of a transition, so an agent killed
# mid-drain resumes the drain — cordon, deadline and all — on restart).
_STATE_SCHEMA = """
CREATE TABLE IF NOT EXISTS agent_state (
    key        TEXT PRIMARY KEY,
    value      TEXT NOT NULL,    -- JSON
    updated_ts REAL NOT NULL
);
"""

# Append-only lifecycle event journal (timeline.py): every state
# transition the agent makes — bind phases, reconciler repairs, drain
# transitions, slice reforms, health/cordon flips, supervisor restarts
# — lands here as one row, ring-capped so churn cannot grow the db
# without bound. AUTOINCREMENT matters: seq numbers stay monotonic per
# agent across the ring trim AND across restarts (sqlite never reuses a
# rowid from sqlite_sequence), so per-node causal order survives both.
# The eviction counter lives in timeline_meta: "how many events has the
# ring dropped" must itself be durable, or a bounded table under churn
# would be indistinguishable from a quiet one.
_TIMELINE_SCHEMA = """
CREATE TABLE IF NOT EXISTS timeline (
    seq   INTEGER PRIMARY KEY AUTOINCREMENT,
    ts    REAL NOT NULL,        -- wall clock at emit
    kind  TEXT NOT NULL,        -- event kind (timeline.py constants)
    keys  TEXT NOT NULL,        -- JSON join keys (pod/slice/chips/trace/node)
    attrs TEXT NOT NULL         -- JSON event detail
);
CREATE TABLE IF NOT EXISTS timeline_meta (
    key   TEXT PRIMARY KEY,
    value TEXT NOT NULL
);
"""


class Storage:
    """Thread-safe persistent map of pod key -> PodInfo.

    Interface parity with the reference Storage (storage.go:15-22):
    save / load / load_or_create / delete / for_each / close.
    """

    def __init__(self, path: str, batch_window_s: float = 0.0,
                 bus=None) -> None:
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        self._path = path
        self._lock = threading.RLock()
        # Optional events.EventBus: store-change notifications (bind
        # commits on STORE_BIND, intent open/close on STORE_INTENT,
        # agent_state writes on STORE_STATE). Notes accumulate under
        # _lock at statement time and publish only AFTER the covering
        # commit lands — inline for unbatched commits, from the
        # group-commit batcher's flush path under batching — so a
        # subscriber never hears about a write that later rolls back.
        # Notifications are delivery HINTS for event-driven loops, not
        # a replication log: consumers re-verify against the store.
        self._bus = bus
        self._pending_notes: list = []
        self._stmt_uses: dict = {}
        # Per-key striping for composite read-modify-writes (mutate()):
        # the sqlite connection itself stays serialized under self._lock,
        # but two RMWs for DIFFERENT pods never wait on each other's
        # load->save window.
        self._key_locks = StripedLockSet(64)
        # Read-through record cache: pod key -> parsed PodInfo snapshot
        # (None = the stored row fails to parse). Once a full scan has
        # populated it, items()/for_each/corrupt_keys serve from memory —
        # GC sweeps, health fan-outs and the sampler join stop re-parsing
        # every row each tick. Our own writes keep it coherent; writes
        # from OTHER connections (node-doctor against the live db) are
        # detected via PRAGMA data_version, which sqlite bumps only for
        # foreign modifications, and drop the cache wholesale.
        self._cache: dict = {}
        self._cache_complete = False
        self._data_version: Optional[int] = None
        self.scans = 0         # full-table SQL scans actually paid
        self.cache_serves = 0  # full iterations answered from the cache
        # Intent ids with a LIVE bind thread in THIS process between
        # journal-write and commit. The reconciler must never roll back
        # an intent that is merely slow (sqlite busy retries, a stalled
        # hostPath, stripe queueing in a rebind burst) rather than
        # crashed: membership here is exact — the bind's finally removes
        # the id on every exit including BaseException, and a real
        # process death takes the set with it, leaving exactly the
        # orphaned rows recovery exists for.
        self._inflight_intents: set = set()
        try:
            self._db = sqlite3.connect(
                path, check_same_thread=False,
                cached_statements=_STMT_CACHE_SIZE,
            )
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute("PRAGMA synchronous=NORMAL")
            self._db.execute(f"PRAGMA busy_timeout={BUSY_TIMEOUT_MS}")
            self._db.execute(_SCHEMA)
            self._db.execute(_JOURNAL_SCHEMA)
            self._db.execute(_STATE_SCHEMA)
            self._db.executescript(_TIMELINE_SCHEMA)
            self._db.commit()
        except sqlite3.Error as e:
            raise StorageError(f"open {path}: {e}") from e
        # -- group-commit write batching (storage/batcher.py) -------------
        # 0 = off (every write commits itself, the historical shape).
        # >0 = statements still execute inline under the lock (reads on
        # this connection stay read-your-writes), but the COMMIT is
        # deferred to one flusher commit per window: load-bearing writes
        # wait for their covering commit (durability unchanged, fsyncs
        # amortized across concurrent writers), non-load-bearing ones
        # (timeline events, intent-commit row drops) ride along free.
        self.commits_total = 0   # commits this connection actually paid
        self.writes_total = 0    # logical write transactions requested
        self._batcher: Optional[GroupCommitBatcher] = None
        if batch_window_s and batch_window_s > 0:
            self._batcher = GroupCommitBatcher(
                self._batch_commit, self._batch_rollback,
                window_s=batch_window_s,
                name=f"storage:{os.path.basename(path)}",
                # The statement lock: the batcher's failure path must
                # exclude writers while it decides which generations a
                # rollback took with it (RLock, so the rollback callback
                # may re-take it).
                lock=self._lock,
                on_commit=self._publish_batch_notes,
            )

    # -- group-commit plumbing (flusher-thread side) --------------------------

    def _batch_commit(self) -> None:
        """One group commit covering every statement executed since the
        last flush; retried once on a transient cross-connection lock."""
        with self._lock:
            for attempt in (1, 2):
                try:
                    self._db.commit()
                    self.commits_total += 1
                    return
                except sqlite3.Error as e:
                    if not (self._is_transient_lock(e) and attempt == 1):
                        raise
                time.sleep(_LOCKED_RETRY_DELAY_S)

    def _batch_rollback(self) -> None:
        """A failed group commit rolls the whole open transaction back;
        in-memory views that may now hold rolled-back state are dropped
        (sync waiters get their error from the batcher)."""
        with self._lock:
            try:
                self._db.rollback()
            except sqlite3.Error:
                pass
            self._cache = {}
            self._cache_complete = False
            self._timeline_rows_cache = None
            self._timeline_cap_stored = None
            # Notes for statements that just rolled back must never
            # publish — the events would describe state that does not
            # exist on disk.
            self._pending_notes = []

    # -- store-change notifications (events.EventBus) -------------------------

    def _note_locked(self, topic: str, kind: str, key: str) -> None:
        """(lock held) Queue one store-change notification for the
        commit that will cover the statement just executed."""
        if self._bus is not None:
            self._pending_notes.append((topic, kind, key))

    def _publish_notes_locked(self) -> None:
        """(lock held) Publish+clear pending notes — the unbatched
        post-commit path (publish only fans out to subscriber queues;
        it cannot re-enter storage)."""
        if not self._pending_notes:
            return
        notes, self._pending_notes = self._pending_notes, []
        for topic, kind, key in notes:
            self._bus.publish(topic, kind=kind, key=key)

    def _publish_batch_notes(self) -> None:
        """Group-commit flush path (batcher ``on_commit``, flusher
        thread): everything the landed commit covered publishes in one
        burst, outside the statement lock."""
        with self._lock:
            notes, self._pending_notes = self._pending_notes, []
        for topic, kind, key in notes:
            self._bus.publish(topic, kind=kind, key=key)

    def _commit_locked(self, sync: bool = True) -> Optional[int]:
        """(lock held) Commit this write, or hand it to the group-commit
        batcher; returns the batch generation to wait on (None when the
        commit already happened). ``sync`` marks a write whose caller
        will block on the commit — the batcher flushes those
        immediately instead of riding out the coalescing window."""
        self.writes_total += 1
        if self._batcher is None:
            self._db.commit()
            self.commits_total += 1
            self._publish_notes_locked()
            return None
        return self._batcher.mark_dirty(sync=sync)

    def _sync_wait(self, what: str, token: Optional[int]) -> None:
        """(lock NOT held) Block until a load-bearing write's covering
        group commit has landed; no-op when the write committed inline."""
        if token is None or self._batcher is None:
            return
        try:
            self._batcher.wait(token)
        except GroupCommitError as e:
            raise StorageError(f"{what}: {e}") from e

    def write_stats(self) -> dict:
        """Write-amplification accounting for the scale harness and
        /metrics: logical write transactions vs sqlite commits paid."""
        with self._lock:
            stats = {
                "batching": self._batcher is not None,
                "writes_total": self.writes_total,
                "commits_total": self.commits_total,
                # Hot-statement reuse counts: every entry here rode the
                # connection's compiled-statement cache (the prepared-
                # statement seam; see _PREPARED).
                "prepared_uses": dict(self._stmt_uses),
            }
        if self._batcher is not None:
            b = self._batcher.stats()
            stats["commits_total"] = b["commits_total"]
            stats["batch"] = b
        writes, commits = stats["writes_total"], stats["commits_total"]
        stats["writes_per_commit"] = (
            round(writes / commits, 3) if commits else None
        )
        return stats

    @staticmethod
    def _is_transient_lock(e: sqlite3.Error) -> bool:
        return isinstance(e, sqlite3.OperationalError) and (
            "database is locked" in str(e) or "database is busy" in str(e)
        )

    def _write(
        self, what: str, sql: str, params: tuple, sync: bool = True,
        note: Optional[tuple] = None,
    ) -> Optional[int]:
        """Execute (+commit, or join the group-commit batch) under the
        lock, retrying ONCE on a transient lock error (a concurrent
        writer on another connection — e.g. a node-doctor run against
        the live db — outlasting busy_timeout). Returns the batch token
        for :meth:`_sync_wait` (None when the commit already ran).
        ``note`` is a ``(topic, kind, key)`` store-change notification
        queued between execute and commit, so it publishes exactly when
        (and only if) the statement's covering commit lands."""
        stmt = _PREPARED.get(sql)
        if stmt is not None:
            self._stmt_uses[stmt] = self._stmt_uses.get(stmt, 0) + 1
        for attempt in (1, 2):
            try:
                self._db.execute(sql, params)
                if note is not None:
                    self._note_locked(*note)
                return self._commit_locked(sync=sync)
            except sqlite3.Error as e:
                transient = self._is_transient_lock(e) and attempt == 1
                if self._batcher is None:
                    # Under batching the open transaction carries OTHER
                    # writers' uncommitted statements: one failed
                    # statement must not roll them back (sqlite keeps
                    # the transaction usable past a statement error).
                    try:
                        self._db.rollback()  # clear the failed statement
                    except sqlite3.Error:
                        pass
                    # Unbatched, pending notes can only be our own (any
                    # earlier write flushed its notes at commit) — drop
                    # them with the rolled-back statement.
                    self._pending_notes = []
                if not transient:
                    raise StorageError(f"{what}: {e}") from e
                logger.warning(
                    "%s hit %s; retrying once after %.0fms",
                    what, e, _LOCKED_RETRY_DELAY_S * 1000,
                )
                time.sleep(_LOCKED_RETRY_DELAY_S)
        return None  # pragma: no cover - loop returns or raises

    # Exceptions meaning "this stored value does not parse as a PodInfo".
    _CORRUPT = (json.JSONDecodeError, KeyError, TypeError, AttributeError)

    # -- record cache ---------------------------------------------------------

    def _check_foreign_writes(self) -> None:
        """(lock held) Drop the cache when another connection modified the
        db file since we last looked. PRAGMA data_version is unchanged by
        this connection's own writes, so the cache survives the agent's
        steady-state write traffic and invalidates exactly when an outside
        writer (e.g. a doctor run) touches the file."""
        try:
            dv = self._db.execute("PRAGMA data_version").fetchone()[0]
        except sqlite3.Error:
            # Can't tell — stay safe and drop the cache.
            self._cache = {}
            self._cache_complete = False
            return
        if dv != self._data_version:
            if self._data_version is not None:
                self._cache = {}
                self._cache_complete = False
            self._data_version = dv

    def invalidate_cache(self) -> None:
        """Drop the read-through record cache (test seam / escape hatch;
        foreign-connection writes are detected automatically)."""
        with self._lock:
            self._cache = {}
            self._cache_complete = False

    # -- CRUD ----------------------------------------------------------------

    def _save_locked(self, pod: PodInfo) -> Optional[int]:
        """(lock held) Execute the save; returns the batch token. The
        caller MUST release the lock before waiting on the token — a
        sync wait under the lock deadlocks the group-commit flusher."""
        value = pod.to_json()
        self._check_foreign_writes()
        token = self._write(
            f"save {pod.key}",
            _SQL_SAVE_POD,
            (pod.key, value),
            note=(events_mod.STORE_BIND, "save", pod.key),
        )
        # Cache a snapshot parsed back from the persisted JSON — never
        # the caller's object, which the caller may keep mutating.
        try:
            self._cache[pod.key] = PodInfo.from_json(value)
        except self._CORRUPT:  # pragma: no cover - to_json round-trips
            self._cache.pop(pod.key, None)
            self._cache_complete = False
        return token

    def save(self, pod: PodInfo) -> None:
        faults.fire("storage.save")
        with self._lock:
            token = self._save_locked(pod)
        # The checkpoint is the bind's durable commit marker: block until
        # the covering group commit lands (outside the lock, so the
        # flusher can take it).
        self._sync_wait(f"save {pod.key}", token)

    def load(self, namespace: str, name: str) -> Optional[PodInfo]:
        """Return the stored PodInfo, or None when absent (reference returns
        a not-found error; None is the idiomatic Python shape)."""
        with self._lock:
            try:
                row = self._db.execute(
                    "SELECT value FROM pods WHERE key=?",
                    (f"{namespace}/{name}",),
                ).fetchone()
            except sqlite3.Error as e:
                raise StorageError(f"load {namespace}/{name}: {e}") from e
        if row is None:
            return None
        try:
            return PodInfo.from_json(row[0])
        except self._CORRUPT as e:
            raise StorageError(
                f"corrupt record for {namespace}/{name}: {e}"
            ) from e

    def load_or_create(self, namespace: str, name: str) -> PodInfo:
        token = None
        with self._lock:
            existing = self.load(namespace, name)
            if existing is not None:
                return existing
            pod = PodInfo(namespace=namespace, name=name)
            faults.fire("storage.save")
            token = self._save_locked(pod)
        # Wait OUTSIDE the lock (see _save_locked): holding it here
        # would block the flusher this wait depends on.
        self._sync_wait(f"save {pod.key}", token)
        return pod

    def mutate(self, namespace: str, name: str, fn) -> PodInfo:
        """Atomic per-key read-modify-write: load-or-create the record,
        apply ``fn(info)``, save. Two mutate() calls for the same pod
        serialize on a striped per-key lock (never on each other's SQL
        alone, which would lose one update); mutations of UNRELATED pods
        proceed in parallel up to the sqlite connection itself."""
        with self._key_locks.acquire(f"{namespace}/{name}"):
            info = self.load_or_create(namespace, name)
            fn(info)
            self.save(info)
            return info

    def delete(self, namespace: str, name: str) -> None:
        faults.fire("storage.delete")
        with self._lock:
            self._check_foreign_writes()
            token = self._write(
                f"delete {namespace}/{name}",
                _SQL_DELETE_POD,
                (f"{namespace}/{name}",),
                note=(events_mod.STORE_BIND, "delete",
                      f"{namespace}/{name}"),
            )
            self._cache.pop(f"{namespace}/{name}", None)
        self._sync_wait(f"delete {namespace}/{name}", token)

    def count(self) -> int:
        """O(1)-per-bind record count — the gauge-update path must not
        deserialize every record just to count them.

        Once the record cache is warm this counts parseable records
        exactly like the pre-cache ``items()`` accounting did (corrupt
        rows excluded); before the first full scan it falls back to SQL
        COUNT(*), which includes a corrupt row until a scanner (GC,
        sampler — seconds after boot) warms the cache."""
        with self._lock:
            self._check_foreign_writes()
            if self._cache_complete:
                return sum(
                    1 for v in self._cache.values() if v is not None
                )
            try:
                return self._db.execute(
                    "SELECT COUNT(*) FROM pods"
                ).fetchone()[0]
            except sqlite3.Error as e:
                raise StorageError(f"count: {e}") from e

    # -- bind intent journal (write-ahead log for the bind transaction) -------

    def journal_intent(
        self,
        pod_key: str,
        container: str,
        resource: str,
        alloc_hash: str,
        payload: dict,
    ) -> int:
        """Record a bind intent BEFORE the bind's first side effect;
        returns the intent id the bind later commits. The payload must
        name everything recovery needs to undo or replay the bind
        (device ids, chip indexes, planned virtual-node link ids)."""
        faults.fire("storage.journal")
        value = json.dumps(payload, sort_keys=True)
        with self._lock:
            self._stmt_uses["insert_intent"] = (
                self._stmt_uses.get("insert_intent", 0) + 1
            )
            for attempt in (1, 2):
                try:
                    cur = self._db.execute(
                        _SQL_INSERT_INTENT,
                        (pod_key, container, resource, alloc_hash, value,
                         time.time()),
                    )
                    self._note_locked(events_mod.STORE_INTENT, "open",
                                      str(cur.lastrowid))
                    token = self._commit_locked()
                    intent_id = cur.lastrowid
                    self._inflight_intents.add(intent_id)
                    break
                except sqlite3.Error as e:
                    transient = self._is_transient_lock(e) and attempt == 1
                    if self._batcher is None:
                        try:
                            self._db.rollback()
                        except sqlite3.Error:
                            pass
                        self._pending_notes = []
                    if not transient:
                        raise StorageError(
                            f"journal intent {pod_key}/{container}: {e}"
                        ) from e
                    time.sleep(_LOCKED_RETRY_DELAY_S)
            else:  # pragma: no cover - loop breaks or raises
                raise StorageError(
                    f"journal intent {pod_key}/{container}: retries "
                    "exhausted"
                )
        # The intent must be DURABLE before the bind's first side effect
        # (that is its whole point): wait out the covering group commit.
        self._sync_wait(f"journal intent {pod_key}/{container}", token)
        return intent_id

    def journal_commit(self, intent_id: int) -> None:
        """Mark a bind intent committed. The checkpointed allocation
        record (pods table) is the durable commit marker, so committing
        an intent simply removes its row — an intent that survives a
        crash is, by construction, one whose bind never provably
        finished."""
        # Deliberately NOT sync under batching: the checkpointed pods-
        # table record is the durable commit marker, so a crash that
        # loses this row drop merely leaves an open intent whose record
        # exists — the reconciler's intent_committed repair class
        # resolves it (the bind.post_checkpoint crash window that has
        # always existed, now a few ms wider).
        with self._lock:
            self._write(
                f"journal commit {intent_id}",
                _SQL_DELETE_INTENT,
                (intent_id,),
                sync=False,
                note=(events_mod.STORE_INTENT, "close", str(intent_id)),
            )
            self._inflight_intents.discard(intent_id)

    # A rolled-back intent leaves the journal the same way a committed
    # one does; the distinct name keeps call sites self-describing.
    journal_remove = journal_commit

    def intent_done(self, intent_id: int) -> None:
        """Drop the in-process in-flight marker WITHOUT touching the
        journal row — the bind path's finally, so a thread that dies on
        an uncaught exception stops shielding its intent from recovery
        (the row itself survives for the reconciler)."""
        with self._lock:
            self._inflight_intents.discard(intent_id)

    def intent_inflight(self, intent_id: int) -> bool:
        """True while a live bind thread in this process owns the
        intent; the reconciler must not resolve such a row no matter
        how slow the bind is going."""
        with self._lock:
            return intent_id in self._inflight_intents

    def intent_open(self, intent_id: int) -> bool:
        """True while the intent row still exists (reconciler re-checks
        under the owner's bind stripe before rolling an intent back)."""
        with self._lock:
            try:
                row = self._db.execute(
                    "SELECT 1 FROM bind_intents WHERE id=?", (intent_id,)
                ).fetchone()
            except sqlite3.Error as e:
                raise StorageError(f"intent_open {intent_id}: {e}") from e
        return row is not None

    def open_intents(self) -> list:
        """All uncommitted bind intents, oldest first, with wall-clock
        age — consumed by the reconciler, /debug/allocations and the
        node-doctor bundle (a stuck intent must be diagnosable from a
        bundle alone)."""
        with self._lock:
            try:
                rows = self._db.execute(
                    "SELECT id, pod_key, container, resource, hash, "
                    "payload, created_ts FROM bind_intents ORDER BY id"
                ).fetchall()
            except sqlite3.Error as e:
                raise StorageError(f"open_intents: {e}") from e
        now = time.time()
        out = []
        for row in rows:
            try:
                payload = json.loads(row[5])
            except ValueError:
                payload = {}
            out.append({
                "id": row[0],
                "pod_key": row[1],
                "container": row[2],
                "resource": row[3],
                "hash": row[4],
                "payload": payload,
                "created_ts": row[6],
                "age_s": round(max(0.0, now - row[6]), 3),
            })
        return out

    def open_intents_brief(self) -> list:
        """open_intents() projected to the public diagnostics shape
        (``{id,pod,container,resource,hash,age_s}``) shared by the
        reconciler's status(), /debug/allocations and the node-doctor
        bundle — one place to evolve the field set, validated by
        sampler.validate_bundle."""
        return [
            {
                "id": i["id"],
                "pod": i["pod_key"],
                "container": i["container"],
                "resource": i["resource"],
                "hash": i["hash"],
                "age_s": i["age_s"],
            }
            for i in self.open_intents()
        ]

    # -- durable agent state (drain lifecycle journal) ------------------------

    def save_state(self, key: str, value: dict) -> None:
        """Persist one JSON state document under ``key`` (upsert).
        Written BEFORE the side effects of the transition it describes —
        the drain orchestrator's crash-consistency contract."""
        faults.fire("storage.state")
        with self._lock:
            token = self._write(
                f"save_state {key}",
                _SQL_UPSERT_STATE,
                (key, json.dumps(value, sort_keys=True), time.time()),
                note=(events_mod.STORE_STATE, "save", key),
            )
        # Lifecycle journals are written BEFORE their side effects run —
        # that ordering only means something if the row is durable first.
        self._sync_wait(f"save_state {key}", token)

    def load_state(self, key: str) -> Optional[dict]:
        """The stored state document, or None when absent/corrupt (a
        corrupt row is logged and treated as absent — lifecycle state is
        always safely re-derivable from a fresh start)."""
        with self._lock:
            try:
                row = self._db.execute(
                    "SELECT value FROM agent_state WHERE key=?", (key,)
                ).fetchone()
            except sqlite3.Error as e:
                raise StorageError(f"load_state {key}: {e}") from e
        if row is None:
            return None
        try:
            value = json.loads(row[0])
        except ValueError:
            logger.warning("corrupt agent_state row %r; treating as absent",
                           key)
            return None
        return value if isinstance(value, dict) else None

    def delete_state(self, key: str) -> None:
        with self._lock:
            token = self._write(
                f"delete_state {key}",
                "DELETE FROM agent_state WHERE key=?",
                (key,),
                note=(events_mod.STORE_STATE, "delete", key),
            )
        self._sync_wait(f"delete_state {key}", token)

    # -- lifecycle timeline journal (timeline.py) ------------------------------

    _TIMELINE_EVICTED_KEY = "timeline_evicted_total"
    _TIMELINE_CAP_KEY = "timeline_cap"
    # In-memory row count for the timeline ring (None = recompute from
    # SQL on next use). Every bind emits events, so the append path
    # must not pay a COUNT(*) b-tree scan per event; all timeline
    # writes go through this connection, so delta-tracking under
    # self._lock stays exact. Any sqlite error resets it to None.
    _timeline_rows_cache: Optional[int] = None
    # Last cap value persisted into timeline_meta (None = not yet
    # written this process). The cap is a process argument, but the
    # offline reader (node-doctor against a dead agent's db) must
    # report the cap the agent actually RAN with, not a compiled-in
    # default — so every append keeps the stored value current.
    _timeline_cap_stored: Optional[int] = None

    def timeline_append(
        self, ts: float, kind: str, keys: dict, attrs: dict, cap: int
    ) -> int:
        """Append one lifecycle event and trim the ring to ``cap`` rows
        (oldest first), bumping the durable eviction counter by however
        many rows the trim dropped. Returns the event's monotonic seq.
        One commit covers append + trim + counter, so a crash can never
        leave the counter disagreeing with the rows."""
        # Timeline events are non-load-bearing by contract (emit swallows
        # failures): under batching they never wait for their commit —
        # the whole churn burst's events amortize into the flusher's
        # window commits.
        keys_json = json.dumps(keys, sort_keys=True, default=str)
        attrs_json = json.dumps(attrs, sort_keys=True, default=str)
        with self._lock:
            for attempt in (1, 2):
                try:
                    if self._batcher is not None:
                        # Multi-statement append inside a SHARED open
                        # transaction: a savepoint scopes the rollback
                        # of a mid-append failure to THIS append, so a
                        # partial trim/counter update can never ride a
                        # later group commit and break the
                        # max(seq)-rows == evicted invariant — without
                        # touching other writers' pending statements.
                        self._db.execute("SAVEPOINT timeline_append")
                    cur = self._db.execute(
                        "INSERT INTO timeline(ts, kind, keys, attrs) "
                        "VALUES(?, ?, ?, ?)",
                        (ts, kind, keys_json, attrs_json),
                    )
                    seq = cur.lastrowid
                    if self._timeline_cap_stored != cap:
                        self._db.execute(
                            "INSERT INTO timeline_meta(key, value) "
                            "VALUES(?, ?) ON CONFLICT(key) DO UPDATE "
                            "SET value=excluded.value",
                            (self._TIMELINE_CAP_KEY, str(cap)),
                        )
                        self._timeline_cap_stored = cap
                    if self._timeline_rows_cache is None:
                        self._timeline_rows_cache = self._db.execute(
                            "SELECT COUNT(*) FROM timeline"
                        ).fetchone()[0]
                    else:
                        self._timeline_rows_cache += 1
                    excess = self._timeline_rows_cache - max(1, cap)
                    if excess > 0:
                        self._db.execute(
                            "DELETE FROM timeline WHERE seq IN ("
                            "SELECT seq FROM timeline ORDER BY seq "
                            "LIMIT ?)",
                            (excess,),
                        )
                        self._db.execute(
                            "INSERT INTO timeline_meta(key, value) "
                            "VALUES(?, ?) ON CONFLICT(key) DO UPDATE SET "
                            "value = CAST(value AS INTEGER) + "
                            "excluded.value",
                            (self._TIMELINE_EVICTED_KEY, str(excess)),
                        )
                        self._timeline_rows_cache -= excess
                    if self._batcher is not None:
                        self._db.execute("RELEASE timeline_append")
                    self._commit_locked(sync=False)
                    return seq
                except sqlite3.Error as e:
                    self._timeline_rows_cache = None
                    self._timeline_cap_stored = None  # write rolled back
                    transient = self._is_transient_lock(e) and attempt == 1
                    if self._batcher is None:
                        try:
                            self._db.rollback()
                        except sqlite3.Error:
                            pass
                    else:
                        # Scoped undo: only this append's statements.
                        try:
                            self._db.execute(
                                "ROLLBACK TO timeline_append"
                            )
                            self._db.execute("RELEASE timeline_append")
                        except sqlite3.Error:
                            pass
                    if not transient:
                        raise StorageError(f"timeline append: {e}") from e
                    time.sleep(_LOCKED_RETRY_DELAY_S)
        raise StorageError(
            "timeline append: retries exhausted"
        )  # pragma: no cover - loop returns

    def timeline_rows(
        self,
        since_seq: Optional[int] = None,
        since_ts: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> list:
        """Journaled events oldest-first (seq order = per-agent causal
        order), each ``{seq, ts, kind, keys, attrs}`` with the JSON
        columns parsed (a corrupt column parses to {} rather than
        killing the read — the journal is triage material and must
        degrade, not disappear). ``limit`` keeps the NEWEST rows."""
        sql = "SELECT seq, ts, kind, keys, attrs FROM timeline"
        where, params = [], []
        if since_seq is not None:
            where.append("seq > ?")
            params.append(since_seq)
        if since_ts is not None:
            where.append("ts >= ?")
            params.append(since_ts)
        if where:
            sql += " WHERE " + " AND ".join(where)
        sql += " ORDER BY seq"
        with self._lock:
            try:
                rows = self._db.execute(sql, tuple(params)).fetchall()
            except sqlite3.Error as e:
                raise StorageError(f"timeline read: {e}") from e
        if limit is not None and limit >= 0:
            rows = rows[-limit:] if limit else []
        out = []
        for seq, ts, kind, keys_json, attrs_json in rows:
            try:
                keys = json.loads(keys_json)
            except ValueError:
                keys = {}
            try:
                attrs = json.loads(attrs_json)
            except ValueError:
                attrs = {}
            out.append({
                "seq": seq, "ts": ts, "kind": kind,
                "keys": keys if isinstance(keys, dict) else {},
                "attrs": attrs if isinstance(attrs, dict) else {},
            })
        return out

    def timeline_count(self) -> int:
        with self._lock:
            if self._timeline_rows_cache is not None:
                return self._timeline_rows_cache
            try:
                count = self._db.execute(
                    "SELECT COUNT(*) FROM timeline"
                ).fetchone()[0]
            except sqlite3.Error as e:
                raise StorageError(f"timeline count: {e}") from e
            self._timeline_rows_cache = count
            return count

    def timeline_meta_value(self, key: str) -> Optional[str]:
        """One timeline_meta value, or None when absent."""
        with self._lock:
            try:
                row = self._db.execute(
                    "SELECT value FROM timeline_meta WHERE key=?",
                    (key,),
                ).fetchone()
            except sqlite3.Error as e:
                raise StorageError(f"timeline meta read: {e}") from e
        return None if row is None else row[0]

    def timeline_set_meta(self, key: str, value: str) -> None:
        """Upsert one timeline_meta value — the never-evicted side
        channel for journal facts that must outlive the ring trim (the
        boot identity the doctor bundle stamps)."""
        with self._lock:
            self._write(
                f"timeline meta {key}",
                "INSERT INTO timeline_meta(key, value) VALUES(?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                (key, value),
                sync=False,  # journal meta: observability, like the ring
            )

    def _timeline_meta_int(self, key: str) -> Optional[int]:
        value = self.timeline_meta_value(key)
        if value is None:
            return None
        try:
            return int(value)
        except ValueError:
            return None

    def timeline_evicted_total(self) -> int:
        """Durable count of events the ring cap has dropped (0 when the
        ring never overflowed)."""
        value = self._timeline_meta_int(self._TIMELINE_EVICTED_KEY)
        return 0 if value is None else value

    def timeline_cap_stored(self) -> Optional[int]:
        """The ring cap the WRITING agent last appended under — what an
        offline reader must report instead of its own default (None
        when no event was ever journaled)."""
        return self._timeline_meta_int(self._TIMELINE_CAP_KEY)

    def for_each(self, fn: Callable[[PodInfo], None]) -> None:
        """Invoke fn on a snapshot of every stored PodInfo.

        Snapshot first so fn may call save/delete without deadlocking or
        invalidating the cursor (the reference iterates inside one Bolt
        transaction and therefore could not; our GC deletes during
        iteration). Corrupt records are logged and skipped — GC must keep
        making progress past one bad row; use load() for loud point reads.
        """
        for _, pod in self.items():
            fn(pod)

    def _rows(self) -> Iterator[Tuple[str, Optional[PodInfo]]]:
        """Snapshot all rows; parse each to PodInfo or None when corrupt.

        Served from the read-through cache once a full scan has warmed it
        (and no foreign connection has written since). The yielded
        PodInfo objects are shared snapshots: callers may read them or
        re-save a fresh load(), but must not mutate them in place."""
        with self._lock:
            self._check_foreign_writes()
            if self._cache_complete:
                self.cache_serves += 1
                snapshot = list(self._cache.items())
            else:
                try:
                    rows = self._db.execute(
                        "SELECT key, value FROM pods"
                    ).fetchall()
                except sqlite3.Error as e:
                    raise StorageError(f"scan: {e}") from e
                self.scans += 1
                snapshot = []
                for key, value in rows:
                    try:
                        snapshot.append((key, PodInfo.from_json(value)))
                    except self._CORRUPT:
                        snapshot.append((key, None))
                # Parsing ran under the lock, so no save/delete raced the
                # rebuild: installing the parsed rows is race-free.
                self._cache = dict(snapshot)
                self._cache_complete = True
        # Lock released before yielding: callers iterate (and may call
        # save/delete) without holding the storage lock hostage.
        yield from snapshot

    def items(self) -> Iterator[Tuple[str, PodInfo]]:
        for key, pod in self._rows():
            if pod is None:
                logger.warning("skipping corrupt storage record %r", key)
            else:
                yield key, pod

    def corrupt_keys(self) -> list:
        """Keys whose records fail to parse (for Restore() reporting)."""
        return [key for key, pod in self._rows() if pod is None]

    def close(self) -> None:
        if self._batcher is not None:
            # Flush-then-stop: pending batched writes (timeline tails,
            # intent-commit drops) land before the connection closes.
            self._batcher.stop()
        with self._lock:
            self._db.close()

    # -- context manager -----------------------------------------------------

    def __enter__(self) -> "Storage":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
