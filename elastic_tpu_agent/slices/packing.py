"""Topology-aware chip packing: ICI-span scoring and canonical ordering.

The packing policy the device-plugin layer applies (GetPreferredAllocation
picks chip sets, the scheduler-spread bind path scores what the external
scheduler chose) lives here, one floor below the plugins: placement is a
*slice* concern — the same scoring that keeps a fractional grant on one
chip keeps a multi-chip grant on an adjacent sub-grid, and the same
canonical ordering that numbers a fresh bind's devices numbers a reformed
slice's. Arax (PAPERS.md) argues the runtime, not the workload, should
own this accelerator mapping; this module is that ownership made
explicit.

Scoring model: chips on one host form the x,y ICI grid of
``tpu.topology.chip_grid``; the cost of a chip set is the total pairwise
Manhattan hop count (``ici_distance``) over it — the metric intra-pod
collectives actually pay. Ties break deterministically (most free
capacity, then lowest chip indexes) so two agents given the same state
pick the same set.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, Optional, Tuple

from ..tpu.topology import chip_grid, ici_distance

# Exhaustive ICI-span packing is exact up to this many candidate chips;
# current TPU-VM hosts top out at 8 (v4/v5p host = 4 chips, v5e host = 8).
EXACT_PACK_MAX_CHIPS = 8


def packing_score(
    chip_indexes: Iterable[int], chips_per_host: int
) -> int:
    """Total pairwise ICI hop count over a chip set (0 for <= 1 chip).

    The packing-score metric: a 2-chip set on adjacent grid slots scores
    1; the same request scattered to opposite corners of a 4-chip host
    scores 2 per pair. Exported per bind as
    ``elastic_tpu_packing_ici_span`` and attached to bind traces, so a
    scheduler that spreads grants across the mesh is visible as a score
    regression, not a vague slowdown.
    """
    chips = sorted(set(chip_indexes))
    if len(chips) <= 1:
        return 0
    grid = chip_grid(max(chips_per_host, max(chips) + 1))
    return sum(
        ici_distance(grid[a], grid[b])
        for a, b in itertools.combinations(chips, 2)
    )


def canonical_chip_order(
    chip_indexes: Iterable[int], chips_per_host: Optional[int] = None
) -> List[int]:
    """Deterministic device ordering: sorted by grid coordinate (row,
    then column), duplicates dropped.

    The container-visible device numbering (``TPU_VISIBLE_CHIPS`` and the
    dense ``/dev/accel<p>`` renumbering) is position-ordered over this
    list, so the same physical chip set always yields the same in-pod
    device numbering — a reformed slice restarts with stable device ids
    no matter what order the scheduler annotation (or a replay) listed
    the chips in. For the row-major host grids ``chip_grid`` emits this
    coincides with ascending chip index, but the contract is the grid
    walk, not the integer sort.
    """
    chips = sorted(set(chip_indexes))
    if not chips:
        return []
    grid = chip_grid(max(chips_per_host or 0, chips[-1] + 1))
    return sorted(chips, key=lambda c: (grid[c][1], grid[c][0], c))


def pick_chip_set(
    by_chip: Dict[int, List[str]],
    need: int,
    chips_per_host: int,
    pinned: Optional[set] = None,
) -> List[int]:
    """Order of chips to draw fake ids from for a request of ``need`` units.

    Picks the minimal number of chips whose free units cover ``need``, and
    among minimal sets the one with the smallest total pairwise ICI hop
    distance over the chosen chips *plus* any ``pinned`` chips the request's
    must-include ids already sit on (then most free capacity, then lowest
    indexes — fully deterministic). Up to EXACT_PACK_MAX_CHIPS candidate
    chips the subset search is exhaustive and exact (<= C(8,k)); beyond
    that (future larger hosts) a greedy nearest-chip build keeps the cost
    O(n^2 * k) at the price of exactness.
    """
    pinned = pinned or set()
    free = sorted(by_chip.items(), key=lambda kv: (-len(kv[1]), kv[0]))
    # minimal chip count k: fullest-first prefix covering `need`
    total, k = 0, 0
    for _, ids in free:
        total += len(ids)
        k += 1
        if total >= need:
            break
    if total < need:
        # Not satisfiable from availables; fall back to fullest-first order
        # (kubelet will fail the admission itself).
        return [c for c, _ in free]
    if k == 1 and not pinned:
        return [c for c, _ in free]
    grid = chip_grid(
        max(chips_per_host, max(by_chip) + 1, max(pinned, default=0) + 1)
    )
    if len(by_chip) > EXACT_PACK_MAX_CHIPS:
        return greedy_chip_set(by_chip, need, grid, pinned)
    best: Optional[tuple] = None
    for combo in itertools.combinations(sorted(by_chip), k):
        cap = sum(len(by_chip[c]) for c in combo)
        if cap < need:
            continue
        pod_chips = set(combo) | pinned
        span = sum(
            ici_distance(grid[a], grid[b])
            for a, b in itertools.combinations(sorted(pod_chips), 2)
        )
        key = (span, -cap, combo)
        if best is None or key < best:
            best = key
    chosen = best[2] if best else tuple(c for c, _ in free[:k])
    return sorted(chosen, key=lambda c: (-len(by_chip[c]), c))


def greedy_chip_set(
    by_chip: Dict[int, List[str]],
    need: int,
    grid: Dict[int, Tuple[int, int]],
    pinned: set,
) -> List[int]:
    """Greedy fallback for hosts with more chips than the exact search
    handles: seed with the pinned chips (else the fullest chip), then
    repeatedly add the chip minimizing added ICI span (ties: most free
    units, then lowest index) until the chosen set covers ``need``."""
    chosen: List[int] = []
    anchor = set(pinned)
    remaining = dict(by_chip)
    covered = 0
    while covered < need and remaining:
        best_key, best_chip = None, None
        for c, ids in remaining.items():
            span = sum(ici_distance(grid[c], grid[a]) for a in anchor)
            key = (span, -len(ids), c)
            if best_key is None or key < best_key:
                best_key, best_chip = key, c
        chosen.append(best_chip)
        anchor.add(best_chip)
        covered += len(remaining.pop(best_chip))
    return chosen
