"""SliceRegistry: who is in which multi-host slice, and at what epoch.

Nothing used to *own* slices: slice_env.py derived env per bind and
forgot it, and a member dying was nobody's problem. The registry is the
mapping layer ROADMAP item 4 calls for — it assembles slice membership
from pod annotations plus the shared apiserver state (never from
agent-to-agent coordination, SURVEY.md §7), normalizes the worker
ordering deterministically so every cooperating agent derives the same
identity env, validates worker-id/hostname consistency across the
cooperating pods, and stamps the slice env (plus slice name and a
reform epoch) at PreStart. The reconciler's elastic-recovery path
(slices/recovery.py) reads and advances the same state.

Membership model: a pod is a member of slice ``S`` iff its
``elasticgpu.io/tpu-slice-id`` annotation equals ``S``; its host is
``hosts[worker_id]`` under its own annotations. Liveness is apiserver
existence — a deleted member (node gone, pod evicted) simply stops
appearing in the list, which is exactly the signal reform keys off.
Apiserver lookups are TTL-cached so the bind path and the reconciler
never turn slice tracking into request amplification.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..common import (
    AnnotationSliceID,
    AnnotationSliceName,
    AnnotationSliceWorkerHosts,
    AnnotationSliceWorkerID,
    EnvSliceEpoch,
    EnvSliceName,
)
from ..slice_env import (
    ordered_worker_hostnames,
    slice_env_for_pod,
    slice_env_from_topology,
    split_hosts,
)
from ..tpu.topology import (
    TopologyInfo,
    parse_accelerator_type,
    topology_for_hosts,
)

logger = logging.getLogger(__name__)

DEFAULT_MEMBERSHIP_TTL_S = 5.0


class SliceMembershipError(RuntimeError):
    """The shared apiserver could not answer a membership query; callers
    must treat membership as UNKNOWN (never as empty — an unreachable
    apiserver must not look like a slice whose members all died)."""


@dataclass
class SliceMember:
    """One cooperating pod's view of itself, read from its annotations."""

    pod_key: str
    node: str
    host: str
    worker_id: int
    hosts: Tuple[str, ...]


@dataclass
class _SliceState:
    """Node-local bookkeeping for one slice this node hosts members of."""

    slice_id: str
    accelerator_type: str = ""
    hosts: Tuple[str, ...] = ()
    epoch: int = 0
    reforms_total: int = 0
    local_pods: Dict[str, int] = field(default_factory=dict)  # pod_key -> wid
    last_validation: List[str] = field(default_factory=list)
    last_error: str = ""


def parse_hosts_annotation(annotations: Dict[str, str]) -> List[str]:
    """The membership claim's host list, via the shared
    :func:`slice_env.split_hosts` grammar: the apiserver-side
    membership parse, PreStart stamping and the stamped-spec parse
    must never disagree about the same list."""
    return split_hosts(annotations.get(AnnotationSliceWorkerHosts, ""))


def member_from_pod(pod: dict) -> Optional[SliceMember]:
    """Parse a pod manifest into its slice membership claim, or None
    when the pod does not claim one (or the claim is malformed)."""
    meta = pod.get("metadata", {}) or {}
    ann = meta.get("annotations", {}) or {}
    slice_id = ann.get(AnnotationSliceID, "")
    if not slice_id:
        return None
    hosts_raw = parse_hosts_annotation(ann)
    try:
        wid = int(ann.get(AnnotationSliceWorkerID, ""))
    except (TypeError, ValueError):
        wid = -1
    if not (0 <= wid < len(hosts_raw)):
        return None
    own_host = hosts_raw[wid]
    hosts, norm_wid = ordered_worker_hostnames(hosts_raw, own_host)
    return SliceMember(
        pod_key=f"{meta.get('namespace', '')}/{meta.get('name', '')}",
        node=pod.get("spec", {}).get("nodeName", ""),
        host=own_host,
        worker_id=norm_wid,
        hosts=tuple(hosts),
    )


class SliceRegistry:
    """Supervised-adjacent slice bookkeeping (no thread of its own: the
    bind path and the reconciler drive it; all entry points are
    thread-safe)."""

    def __init__(
        self,
        node_name: str = "",
        kube_client=None,
        metrics=None,
        events=None,
        membership_ttl_s: float = DEFAULT_MEMBERSHIP_TTL_S,
    ) -> None:
        self._node = node_name
        self._client = kube_client
        self._metrics = metrics
        self._events = events
        self._ttl = membership_ttl_s
        self._lock = threading.Lock()
        self._slices: Dict[str, _SliceState] = {}
        # One (monotonic ts, members-by-slice) snapshot per apiserver
        # list: a node hosting members of M slices serves all M from a
        # single LIST per TTL window instead of M full-cluster lists.
        # SliceMembershipError is never cached (an apiserver blip must
        # not poison a TTL window).
        self._members_snapshot: Optional[
            Tuple[float, Dict[str, List[SliceMember]]]
        ] = None
        # Single-flight: one refresh LIST at a time; TTL-expiry arrivals
        # either ride the stale snapshot or wait on the in-flight LIST
        # instead of stampeding the apiserver (same discipline as the
        # kubelet PodResourcesSnapshotSource).
        self._refresh_cond = threading.Condition(self._lock)
        self._refresh_inflight = False
        self._last_refresh_error = ""

    # -- membership from the shared apiserver ---------------------------------

    def live_members(
        self, slice_id: str, refresh: bool = False, stale_ok: bool = False
    ) -> List[SliceMember]:
        """Cooperating pods of ``slice_id`` that currently exist at the
        apiserver (TTL-cached). Raises SliceMembershipError when the
        apiserver cannot be asked and no fresh-enough cache exists.
        ``stale_ok`` serves ANY existing snapshot without refreshing —
        the bind path's mode, so PreStart never pays a full-cluster
        LIST once one has ever succeeded (the reconciler keeps the
        snapshot current off the bind path). A TTL of 0 means
        always-fresh and overrides ``stale_ok``."""
        now = time.monotonic()
        with self._lock:
            snap = self._members_snapshot
            if not refresh and snap and (
                (stale_ok and self._ttl > 0)
                or now - snap[0] < self._ttl
            ):
                return list(snap[1].get(slice_id, []))
            if self._refresh_inflight:
                if snap is not None and not refresh:
                    # Ride the stale snapshot rather than stampede: the
                    # in-flight LIST is already refreshing the window.
                    return list(snap[1].get(slice_id, []))
                # No data yet (or forced refresh): wait for the LIST in
                # flight instead of issuing a duplicate.
                while self._refresh_inflight:
                    self._refresh_cond.wait(timeout=30.0)
                snap = self._members_snapshot
                if snap is not None and (
                    not refresh or snap[0] >= now
                ):
                    return list(snap[1].get(slice_id, []))
                raise SliceMembershipError(
                    self._last_refresh_error
                    or "membership refresh failed in flight"
                )
            self._refresh_inflight = True
        # From here the in-flight flag is OURS: every exit (success,
        # apiserver failure, or any unexpected exception in parsing)
        # must clear it and wake waiters, or membership queries wedge
        # forever behind a flag nobody owns.
        try:
            if self._client is None:
                raise SliceMembershipError(
                    "no kube client: slice membership is unknowable"
                )
            counter = getattr(self._metrics, "apiserver_pod_lists", None)
            if counter is not None:
                counter.inc()
            try:
                pods = self._client.list_all_pods()
            except Exception as e:  # noqa: BLE001 - surface as UNKNOWN
                with self._lock:
                    # One failed LIST means membership is unknowable
                    # for EVERY slice, not just the one that asked.
                    for state in self._slices.values():
                        state.last_error = f"{type(e).__name__}: {e}"
                raise SliceMembershipError(str(e)) from e
            by_slice: Dict[str, List[SliceMember]] = {}
            for pod in pods:
                if not self._pod_is_live(pod):
                    continue
                member = member_from_pod(pod)
                if member is not None:
                    by_slice.setdefault(
                        self._slice_id_of_pod(pod), []
                    ).append(member)
            for members in by_slice.values():
                members.sort(key=lambda m: (m.worker_id, m.host, m.pod_key))
            with self._lock:
                self._members_snapshot = (time.monotonic(), by_slice)
                # Symmetric with the failure path: a successful LIST
                # answers for every slice, so no state keeps a stale
                # error while served from this healthy snapshot.
                for state in self._slices.values():
                    state.last_error = ""
                self._last_refresh_error = ""
            return list(by_slice.get(slice_id, []))
        except BaseException as e:
            with self._lock:
                self._last_refresh_error = f"{type(e).__name__}: {e}"
            raise
        finally:
            with self._lock:
                self._refresh_inflight = False
                self._refresh_cond.notify_all()

    @staticmethod
    def _pod_is_live(pod: dict) -> bool:
        """A member is live only while its pod can still run: deleting
        (deletionTimestamp) and terminal phases are OUT — a Failed pod
        that kube GC retains must not keep blocking reform while the
        fabric is already missing its worker. A pod its own agent marked
        ``elasticgpu.io/draining`` is out too: that is the PROACTIVE
        loss signal (drain.py) — the host is going away on a deadline,
        and counting it lost now lets the survivor world form BEFORE the
        loss instead of after a divergence pass."""
        from ..common import AnnotationDraining

        meta = pod.get("metadata", {}) or {}
        if meta.get("deletionTimestamp"):
            return False
        if (meta.get("annotations", {}) or {}).get(AnnotationDraining):
            return False
        phase = (pod.get("status", {}) or {}).get("phase", "")
        return phase not in ("Succeeded", "Failed")

    @staticmethod
    def _slice_id_of_pod(pod: dict) -> str:
        return (
            (pod.get("metadata", {}) or {}).get("annotations", {}) or {}
        ).get(AnnotationSliceID, "")

    def live_hosts(self, slice_id: str, refresh: bool = False) -> set:
        """Hostnames that still have a live member pod."""
        return {m.host for m in self.live_members(slice_id, refresh=refresh)}

    # -- consistency validation -----------------------------------------------

    def validate_members(
        self, slice_id: str, hosts: Tuple[str, ...]
    ) -> List[str]:
        """Cross-agent formation check: every cooperating pod must have
        derived the SAME normalized host ordering, and worker ids must be
        distinct per host. Returns human-readable problems (empty = the
        slice is consistently formed). Never raises — an unanswerable
        apiserver yields no verdict, not a failed bind. Runs on the
        BIND path, so it reads stale-tolerantly: any snapshot serves
        (only the very first slice bind on a node ever LISTs inline);
        the reconciler keeps the snapshot fresh from its own thread."""
        try:
            members = self.live_members(slice_id, stale_ok=True)
        except SliceMembershipError:
            return []
        problems: List[str] = []
        seen_ids: Dict[int, SliceMember] = {}
        for m in members:
            if m.hosts != hosts:
                problems.append(
                    f"{m.pod_key} derives hosts {list(m.hosts)} != "
                    f"{list(hosts)}"
                )
            prev = seen_ids.get(m.worker_id)
            if prev is None:
                seen_ids[m.worker_id] = m
            elif prev.host != m.host:
                problems.append(
                    f"worker id {m.worker_id} claimed by both "
                    f"{prev.host!r} and {m.host!r}"
                )
            elif prev.pod_key != m.pod_key:
                # Same slot, same host, two live pods: a duplicated
                # member (both would rendezvous as the same worker).
                problems.append(
                    f"worker id {m.worker_id} claimed by two live pods "
                    f"({prev.pod_key}, {m.pod_key}) on {m.host!r}"
                )
        return problems

    # -- PreStart stamping ----------------------------------------------------

    def pod_env(
        self,
        annotations: Dict[str, str],
        topo: Optional[TopologyInfo] = None,
        host_worker_id: int = 0,
        host_worker_hostnames: Optional[List[str]] = None,
    ) -> Dict[str, str]:
        """The slice env to stamp into a pod's alloc spec.

        Pods without a slice-id annotation keep the historical
        :func:`slice_env_for_pod` behavior verbatim (host-metadata-driven
        single-slice jobs, shape-only annotations). Slice-id pods get the
        registry treatment: deterministic worker ordering, a reform-aware
        world size (a slice the reconciler already re-formed stamps the
        REFORMED hosts, not the stale annotation set — a drift rebind
        must not silently undo a reform), the slice name, and the current
        epoch.
        """
        slice_id = annotations.get(AnnotationSliceID, "")
        if not slice_id:
            return slice_env_for_pod(
                annotations, topo, host_worker_id, host_worker_hostnames
            )
        ann_type = annotations.get(AnnotationSliceName, "")
        parsed = parse_accelerator_type(ann_type) if ann_type else None
        topo_for_pod = parsed if parsed is not None else topo
        hosts_raw = parse_hosts_annotation(annotations)
        try:
            ann_wid = int(annotations.get(AnnotationSliceWorkerID, ""))
        except (TypeError, ValueError):
            ann_wid = host_worker_id
        own_host = ""
        if 0 <= ann_wid < len(hosts_raw):
            own_host = hosts_raw[ann_wid]
        elif host_worker_hostnames and 0 <= host_worker_id < len(
            host_worker_hostnames
        ):
            own_host = host_worker_hostnames[host_worker_id]
        hosts, wid = ordered_worker_hostnames(hosts_raw, own_host)
        if topo_for_pod is None or wid < 0 or not hosts:
            # Unusable claim: stamp what slice_env_for_pod would have and
            # let validation/events surface the malformation.
            logger.warning(
                "slice %s: unusable membership claim (hosts=%s wid=%d); "
                "falling back to annotation-order env", slice_id,
                hosts_raw, ann_wid,
            )
            env = slice_env_for_pod(
                annotations, topo, host_worker_id, host_worker_hostnames
            )
            if env:
                env[EnvSliceName] = slice_id
                env.setdefault(EnvSliceEpoch, "0")
            return env
        reformed = False
        with self._lock:
            state = self._slices.setdefault(slice_id, _SliceState(slice_id))
            state.accelerator_type = (
                ann_type or getattr(topo_for_pod, "accelerator_type", "")
            )
            if state.epoch > 0 and own_host in state.hosts:
                # Reform override: the reconciler owns the current world.
                hosts = list(state.hosts)
                wid = hosts.index(own_host)
                reformed = True
            else:
                state.hosts = tuple(hosts)
            epoch = state.epoch
        topo_eff = topology_for_hosts(topo_for_pod, len(hosts))
        env = slice_env_from_topology(topo_eff, wid, hosts)
        env[EnvSliceName] = slice_id
        env[EnvSliceEpoch] = str(epoch)
        # Formation-time consistency check only: after a reform the
        # cooperating pods' ANNOTATIONS still describe the original
        # world, so re-validating them against the reformed host set
        # would flag every healthy member as inconsistent.
        problems = (
            [] if reformed
            else self.validate_members(slice_id, tuple(hosts))
        )
        with self._lock:
            state = self._slices.get(slice_id)
            if state is None:
                # A reconciler prune raced this first bind: the pod's
                # record isn't in the store yet, so the slice looked
                # inactive while we validated outside the lock. Epoch is
                # still 0 at formation time, so re-creating the state is
                # equivalent to never having lost it.
                state = _SliceState(slice_id)
                state.accelerator_type = (
                    ann_type
                    or getattr(topo_for_pod, "accelerator_type", "")
                )
                if not reformed:
                    state.hosts = tuple(hosts)
                self._slices[slice_id] = state
            state.last_validation = problems
        if problems:
            logger.warning(
                "slice %s formed INCONSISTENTLY: %s", slice_id,
                "; ".join(problems),
            )
            if self._events is not None:
                from ..kube.events import ReasonSliceInconsistent

                self._events.node_event(
                    ReasonSliceInconsistent,
                    f"slice {slice_id}: " + "; ".join(problems[:3]),
                    type_="Warning",
                )
        self._update_members_gauge(slice_id, len(hosts))
        return env

    def record_local_pod(self, slice_id: str, pod_key: str, wid: int) -> None:
        """Remember that ``pod_key`` (bound on THIS node) is a member —
        the /debug and doctor surfaces list local members per slice."""
        with self._lock:
            state = self._slices.setdefault(slice_id, _SliceState(slice_id))
            state.local_pods[pod_key] = wid

    def drop_local_pod(self, slice_id: str, pod_key: str) -> None:
        """Forget one local member whose store record is gone (reconciler
        housekeeping): the slice survives while other local members
        remain, but a reclaimed pod must not be listed as a live member
        on /debug or in the doctor bundle forever."""
        with self._lock:
            state = self._slices.get(slice_id)
            if state is not None:
                state.local_pods.pop(pod_key, None)

    # -- reform bookkeeping (driven by slices/recovery.py) --------------------

    def observe_stamped(
        self,
        slice_id: str,
        hosts: Tuple[str, ...],
        epoch: int,
        accelerator_type: str = "",
    ) -> None:
        """Re-learn durable slice state from a stamped alloc spec.

        The on-disk env survives agent restarts; this in-memory registry
        does not. Every reconcile pass feeds the stamped (hosts, epoch)
        back in, raising the registry's view to at least the stamped
        epoch — so a restart (or an over-eager prune) can never make a
        later reform repeat or regress an epoch the runner already saw,
        and pod_env's reform override stays armed for drift rebinds.
        Never lowers state: a spec not yet restamped by an in-flight
        reform must not drag the registry backwards.
        """
        hosts = tuple(hosts)
        with self._lock:
            state = self._slices.setdefault(slice_id, _SliceState(slice_id))
            if accelerator_type and not state.accelerator_type:
                state.accelerator_type = accelerator_type
            if epoch > state.epoch:
                state.epoch = epoch
                state.hosts = hosts
            elif not state.hosts:
                state.hosts = hosts
            world = len(state.hosts)
        self._update_members_gauge(slice_id, world)

    def current_hosts(self, slice_id: str) -> Tuple[str, ...]:
        with self._lock:
            state = self._slices.get(slice_id)
            return state.hosts if state is not None else ()

    def epoch(self, slice_id: str) -> int:
        with self._lock:
            state = self._slices.get(slice_id)
            return state.epoch if state is not None else 0

    def note_reform(
        self, slice_id: str, new_hosts: Tuple[str, ...]
    ) -> int:
        """Advance the slice to ``new_hosts``; returns the epoch to stamp.

        Idempotent per world: a second member pod of the same slice on
        this node re-forming to the SAME host set reuses the epoch
        instead of bumping it twice (both pods must restart into the
        same generation)."""
        with self._lock:
            state = self._slices.setdefault(slice_id, _SliceState(slice_id))
            if state.hosts == tuple(new_hosts) and state.epoch > 0:
                return state.epoch
            state.hosts = tuple(new_hosts)
            state.epoch += 1
            state.reforms_total += 1
            epoch = state.epoch
        if self._metrics is not None and hasattr(
            self._metrics, "slice_reforms"
        ):
            try:
                self._metrics.slice_reforms.labels(slice=slice_id).inc()
            except Exception:  # noqa: BLE001 - metrics never break reform
                pass
        self._update_members_gauge(slice_id, len(new_hosts))
        return epoch

    def _update_members_gauge(self, slice_id: str, world: int) -> None:
        if self._metrics is not None and hasattr(
            self._metrics, "slice_members"
        ):
            try:
                # BoundedLabeledGauge: cardinality-guarded per-slice series
                self._metrics.slice_members.set(world, slice=slice_id)
            except Exception:  # noqa: BLE001
                pass

    # -- housekeeping ---------------------------------------------------------

    def prune(self, active_slice_ids: set) -> None:
        """Forget slices with no member pod bound on this node any more
        (reconciler calls this with the slice ids it saw in the store);
        their member gauges are removed so a dashboard never shows a
        ghost slice."""
        with self._lock:
            gone = [s for s in self._slices if s not in active_slice_ids]
            for slice_id in gone:
                del self._slices[slice_id]
                if self._members_snapshot is not None:
                    self._members_snapshot[1].pop(slice_id, None)
        for slice_id in gone:
            # Both per-slice series go with the slice: ids are job-unique,
            # so leaving them behind would grow the scrape without bound
            # under job churn (members is additionally cardinality-guarded
            # by BoundedLabeledGauge for the dry-run mode where prune
            # never runs).
            members = getattr(self._metrics, "slice_members", None)
            if members is not None:
                try:
                    members.remove(slice=slice_id)
                except Exception:  # noqa: BLE001 - series may not exist
                    pass
            reforms = getattr(self._metrics, "slice_reforms", None)
            if reforms is not None:
                try:
                    reforms.remove(slice_id)
                except Exception:  # noqa: BLE001 - series may not exist
                    pass

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        """The ``slices`` block of /debug/allocations and the doctor
        bundle: per-slice world, epoch, local members, reform count and
        the last formation-validation verdict."""
        with self._lock:
            return {
                slice_id: {
                    "accelerator_type": state.accelerator_type,
                    "hosts": list(state.hosts),
                    "world_size": len(state.hosts),
                    "epoch": state.epoch,
                    "reforms_total": state.reforms_total,
                    "local_pods": dict(state.local_pods),
                    "validation_problems": list(state.last_validation),
                    "last_error": state.last_error,
                }
                for slice_id, state in self._slices.items()
            }
