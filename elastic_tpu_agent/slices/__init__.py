"""Slice orchestration: topology-aware packing, slice membership, and
elastic multi-host recovery (ROADMAP item 4).

- :mod:`packing` — ICI-span scoring, canonical chip ordering, and the
  chip-set picker behind GetPreferredAllocation and the scheduler-spread
  bind path.
- :mod:`registry` — SliceRegistry: membership from pod annotations plus
  the shared apiserver state, deterministic worker ordering, PreStart
  env stamping with slice name and reform epoch.
- :mod:`recovery` — SliceReformer: the reconciler's repair executor for
  slice-membership divergence (member loss -> re-formed survivors).
"""

from .packing import canonical_chip_order, packing_score, pick_chip_set
from .recovery import SliceReformer
from .registry import SliceMembershipError, SliceRegistry, member_from_pod

__all__ = [
    "SliceMembershipError",
    "SliceReformer",
    "SliceRegistry",
    "canonical_chip_order",
    "member_from_pod",
    "packing_score",
    "pick_chip_set",
]
