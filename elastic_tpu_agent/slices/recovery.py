"""Elastic slice recovery: detect member loss, re-form the survivors.

Before this module, a slice member dying left the survivors hung at the
next collective forever — the env said world size 4, the fabric had 3.
Funky's FPGA orchestration lifecycle (PAPERS.md) is the model: the
runtime drains and re-forms the accelerator group, the workload
checkpoint-restores into the new shape. Here the reconciler treats
slice membership as a divergence class and this module is its repair
executor:

- **detect** (:meth:`SliceReformer.divergence`): the hosts stamped into
  a bound pod's alloc-spec env are the slice the workload believes in;
  the registry's apiserver-derived live membership is the slice that
  exists. A stamped host with no live member pod is a lost member.
- **repair** (:meth:`SliceReformer.reform`): under the owner's bind
  stripe (the same lock live binds take, so a reform can never
  interleave a concurrent rebind's spec write), rewrite every spec of
  the container with the topology env at the surviving world size, a
  re-derived worker id, and a bumped ``ELASTIC_TPU_SLICE_EPOCH``; emit a
  ``TPUSliceReformed`` pod event. The env file is re-injected at the
  container's next start (OCI hook / NRI), and the epoch bump is the
  runner's signal to checkpoint-restore at the new world size.

Growth is handled by the same diff: a replacement member appearing
re-forms the slice back up, epoch bumped again.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..common import EnvSliceEpoch, EnvSliceName
from ..slice_env import (
    ordered_worker_hostnames,
    slice_env_from_topology,
    split_hosts,
)
from ..tpu.topology import parse_accelerator_type, topology_for_hosts
from .registry import SliceMembershipError, SliceRegistry

logger = logging.getLogger(__name__)


class SliceReformer:
    """Reconciler-side executor for slice-membership divergences."""

    def __init__(
        self,
        registry: SliceRegistry,
        plugin,
        metrics=None,
        events=None,
        timeline=None,
    ) -> None:
        self._registry = registry
        self._plugin = plugin
        self._metrics = metrics
        self._events = events
        self._timeline = timeline

    @property
    def registry(self) -> SliceRegistry:
        return self._registry

    def _spec_plugin(self):
        """Any per-resource plugin (they share the alloc-spec dir)."""
        return getattr(self._plugin, "core", None)

    # -- detect ---------------------------------------------------------------

    def stamped_view(
        self, records: Dict[str, object]
    ) -> Optional[Tuple[str, List[str], int, int, dict, bool]]:
        """(slice_id, stamped_hosts, stamped_worker_id, stamped_epoch,
        spec, torn) from the container's on-disk alloc specs, or None
        when no spec carries a slice stamp (not a slice pod, or the spec
        is gone — the artifact walk repairs that separately).

        ALL sibling specs are read (a core+memory container has one per
        resource): the highest-epoch stamp wins, and ``torn`` reports a
        sibling disagreeing about world or epoch — a crash between
        ``restamp_spec_env_locked``'s per-file writes, which must be a
        repairable divergence, not dict-iteration-order luck."""
        plugin = self._spec_plugin()
        if plugin is None:
            return None
        views = []
        for record in records.values():
            spec = plugin.read_alloc_spec(record.device.hash)
            if spec is None:
                continue
            env = spec.get("env", {}) or {}
            slice_id = env.get(EnvSliceName, "")
            hosts = split_hosts(env.get("TPU_WORKER_HOSTNAMES", ""))
            if not slice_id or not hosts:
                continue
            try:
                wid = int(env.get("TPU_WORKER_ID", ""))
            except ValueError:
                wid = -1
            try:
                epoch = int(env.get(EnvSliceEpoch, "0"))
            except ValueError:
                epoch = 0
            views.append((slice_id, hosts, wid, epoch, spec))
        if not views:
            return None
        best = max(views, key=lambda v: v[3])
        torn = any(
            v[0] != best[0] or v[1] != best[1] or v[3] != best[3]
            for v in views
        )
        return best + (torn,)

    def observe(self, stamped: Tuple) -> None:
        """Feed a stamped view back into the registry (see
        :meth:`SliceRegistry.observe_stamped`): the spec is the durable
        record of the current world + epoch; the registry re-learns it
        every reconcile pass so an agent restart never forgets a reform."""
        slice_id, hosts, _wid, epoch, spec = stamped[:5]
        self._registry.observe_stamped(
            slice_id, tuple(hosts), epoch,
            accelerator_type=spec.get("env", {}).get(
                "TPU_ACCELERATOR_TYPE", ""
            ),
        )

    def divergence(
        self,
        owner,
        records: Dict[str, object],
        live_hosts_cache: Optional[Dict[str, set]] = None,
        stamped: Optional[tuple] = None,
    ) -> Optional[dict]:
        """Compare the container's stamped slice against live membership;
        returns the reform work order, or None when consistent (or not a
        slice pod). Raises SliceMembershipError when membership is
        unknowable — the caller must skip, not treat it as loss.
        ``stamped`` lets the caller pass a pre-read (and pre-observed)
        :meth:`stamped_view` instead of re-reading the specs."""
        if stamped is None:
            stamped = self.stamped_view(records)
            if stamped is None:
                return None
            # Registry re-learn before any verdict: the stamped epoch is
            # the durable floor a reform must bump past, restart or not.
            self.observe(stamped)
        slice_id, hosts, wid, stamped_epoch, spec, torn = stamped
        if not (0 <= wid < len(hosts)):
            return None  # malformed stamp: validation's problem, not ours
        own_host = hosts[wid]
        if live_hosts_cache is not None and slice_id in live_hosts_cache:
            live = live_hosts_cache[slice_id]
        else:
            live = self._registry.live_hosts(slice_id)
            if live_hosts_cache is not None:
                live_hosts_cache[slice_id] = live
        if own_host not in live:
            # Our own member pod is invisible at the apiserver while the
            # sitter still sees it live — a watch/list race. Reforming
            # ourselves out of our own slice can never be right; wait.
            return None
        canonical, _ = ordered_worker_hostnames(hosts)
        if live == set(hosts) and hosts == canonical and not torn:
            return None
        # The reformed ordering is the SAME pure function of the host
        # set that formation uses (ordered_worker_hostnames): a joining
        # replacement's fresh agent derives its world from its own
        # annotations, so survivors appending joiners at the tail would
        # permanently disagree with the joiner about who is worker 0 —
        # both orderings must collapse to one function of the set,
        # coordination-free. Survivors still keep their RELATIVE order
        # (formation order is already canonical; removing/inserting
        # sorted elements preserves it), and the epoch bump makes any id
        # shift a checkpoint-restore, not a silent renumber. The same
        # work order heals a torn restamp (sibling specs at different
        # worlds/epochs after a mid-reform crash) and a non-canonical
        # stamp: for an unchanged world note_reform reuses the epoch and
        # the repair just re-stamps every sibling into ONE generation.
        new_hosts, new_wid = ordered_worker_hostnames(
            list(live), own_host
        )
        if not new_hosts or new_wid < 0:
            return None
        return {
            "slice_id": slice_id,
            "stamped_hosts": hosts,
            "new_hosts": new_hosts,
            "lost": sorted(set(hosts) - live),
            "joined": sorted(live - set(hosts)),
            "own_host": own_host,
            "new_worker_id": new_wid,
            "torn": torn,
            "accelerator_type": spec.get("env", {}).get(
                "TPU_ACCELERATOR_TYPE", ""
            ),
        }

    # -- repair ---------------------------------------------------------------

    def reform(self, owner, records: Dict[str, object], div: dict) -> int:
        """Execute one reform for one container; returns the new epoch.

        The registry advances first (idempotently per world), so every
        member container on this node restamps into the SAME epoch, and
        any concurrent rebind's ``pod_env`` stamp already sees the
        reformed world.
        """
        from ..plugins import tpushare

        slice_id = div["slice_id"]
        new_hosts = tuple(div["new_hosts"])
        epoch = self._registry.note_reform(slice_id, new_hosts)
        topo = parse_accelerator_type(div.get("accelerator_type", ""))
        env_updates = {}
        if topo is not None:
            topo_eff = topology_for_hosts(topo, len(new_hosts))
            env_updates.update(slice_env_from_topology(
                topo_eff, div["new_worker_id"], list(new_hosts)
            ))
        else:
            # No parseable shape (shouldn't happen for a stamped slice):
            # still re-emit the membership env — world size and identity
            # are what the survivors' rendezvous needs most.
            env_updates["TPU_WORKER_ID"] = str(div["new_worker_id"])
            env_updates["TPU_WORKER_HOSTNAMES"] = ",".join(new_hosts)
        env_updates[EnvSliceName] = slice_id
        env_updates[EnvSliceEpoch] = str(epoch)
        plugin = self._spec_plugin()
        with tpushare.bind_lock(owner.pod_key):
            restamped = plugin.restamp_spec_env_locked(
                owner, records, env_updates
            )
        if not restamped:
            # Specs vanished/corrupted between detection and repair: no
            # env changed, so succeeding here (epoch counted, event
            # emitted) would tell the runner a world it never received.
            # Raising routes this into slice_reform_failures and the
            # next pass re-detects whatever state remains.
            raise RuntimeError(
                f"slice {slice_id}: no alloc spec restamped for "
                f"{owner.pod_key} (specs vanished mid-pass)"
            )
        self._registry.record_local_pod(
            slice_id, owner.pod_key, div["new_worker_id"]
        )
        if self._timeline is not None:
            from ..timeline import KIND_SLICE_REFORMED

            self._timeline.emit(
                KIND_SLICE_REFORMED,
                keys={"pod": owner.pod_key, "container": owner.container,
                      "slice": slice_id},
                epoch=epoch, world=len(new_hosts),
                worker_id=div["new_worker_id"],
                lost=div["lost"], joined=div["joined"],
                hosts=",".join(new_hosts), torn=div.get("torn", False),
            )
        if self._events is not None:
            from ..kube.events import ReasonSliceReformed

            detail = []
            if div["lost"]:
                detail.append(f"lost {','.join(div['lost'])}")
            if div["joined"]:
                detail.append(f"joined {','.join(div['joined'])}")
            self._events.pod_event(
                owner.namespace, owner.name, ReasonSliceReformed,
                f"slice {slice_id} re-formed at world size "
                f"{len(new_hosts)} (epoch {epoch}"
                + (", " + "; ".join(detail) if detail else "")
                + f"); this worker is now id {div['new_worker_id']} — "
                "restart resumes from checkpoint at the new world size",
                type_="Warning",
            )
        logger.warning(
            "slice %s re-formed for %s: world %d -> %d (epoch %d, "
            "worker %d)", slice_id, owner.pod_key,
            len(div["stamped_hosts"]), len(new_hosts), epoch,
            div["new_worker_id"],
        )
        return epoch


__all__ = ["SliceReformer", "SliceMembershipError"]
