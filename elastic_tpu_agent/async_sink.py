"""Shared async fire-and-forget sink for observability writers.

Both apiserver-facing observability paths (ElasticTPU CRD publication,
k8s Event emission) have the same constraints: they must stay off the
bind-latency hot path (BASELINE.md SLO), must never raise into callers,
and must self-disable after consecutive failures so a missing CRD or
denied RBAC can't spam the apiserver forever. This worker implements
that contract once.

Flow control:
- the worker drains in BATCHES (everything queued when it wakes) and
  writes for the same coalescing ``key`` collapse to the newest one, so
  a storm of updates for one object costs one apiserver write; each
  superseded op is counted in ``merged`` (the coalescing win is itself
  observable);
- an optional ``flush_window_s`` makes the worker LINGER after waking
  so ops submitted close together coalesce before the drain — at fleet
  churn a bind's event + CRD create + CRD status land in one window and
  same-key ops dedup instead of each paying an apiserver round-trip;
- the queue is BOUNDED: past ``max_queue`` the oldest entry is dropped
  (newer state wins for observability) and counted in ``dropped``;
- failures back off on ONE shared clock: a failed flush attempt bumps
  the streak ONCE, re-queues the unwritten ops, and sleeps a jittered
  exponential backoff before retrying — under a dead apiserver the sink
  no longer machine-guns each queued op independently (which both
  hammered the apiserver and burned the whole failure budget on one
  batch); the sink disables after ``max_failures`` consecutive failed
  flush attempts;
- ``stop()`` DRAINS: everything submitted before the call is written
  (or dropped by the bound) before the worker exits — queued
  Bound/Released records no longer die with the daemon thread.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional, Tuple

from . import faults
from .common import JitteredBackoff

logger = logging.getLogger(__name__)

MAX_CONSECUTIVE_FAILURES = 5
DEFAULT_MAX_QUEUE = 4096
# Shared backoff clock for failed flush attempts (jittered exponential;
# common.JitteredBackoff): min keeps tests and transient blips quick,
# max keeps a dead apiserver from being polled hot.
DEFAULT_BACKOFF_MIN_S = 0.2
DEFAULT_BACKOFF_MAX_S = 15.0


def drop_hook(metrics) -> Optional[Callable[[], None]]:
    """The on_drop callback for a metrics object carrying the shared
    elastic_tpu_observability_dropped_total counter (one place, so every
    AsyncSink consumer wires the metric identically)."""
    if metrics is not None and hasattr(metrics, "observability_dropped"):
        return metrics.observability_dropped.inc
    return None


def register_sink_metrics(sink: "AsyncSink", metrics) -> None:
    """Export a sink's queue depth / failure streak / disabled flag as
    labeled gauges (metrics.AgentMetrics.register_sink) so the
    self-disabling observability paths are themselves observable. One
    place, same rationale as drop_hook."""
    if metrics is not None and hasattr(metrics, "register_sink"):
        try:
            metrics.register_sink(sink)
        except Exception:  # noqa: BLE001 - metrics must not break sinks
            logger.exception("sink metric registration failed for %s",
                             sink.name)


class AsyncSink:
    """Single worker thread draining a bounded, coalescing op queue;
    self-disables after ``max_failures`` consecutive errors."""

    def __init__(
        self,
        name: str,
        max_failures: int = MAX_CONSECUTIVE_FAILURES,
        max_queue: int = DEFAULT_MAX_QUEUE,
        on_drop: Optional[Callable[[], None]] = None,
        flush_window_s: float = 0.0,
        backoff_min_s: float = DEFAULT_BACKOFF_MIN_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
    ) -> None:
        self._name = name
        self._max_failures = max_failures
        self._max_queue = max_queue
        self._on_drop = on_drop
        # Coalescing window: after waking with work, linger this long so
        # ops submitted close together batch/dedup into one drain
        # (0 = drain immediately, the historical shape).
        self._flush_window_s = max(0.0, flush_window_s)
        # ONE backoff clock for the whole flush: a dead apiserver costs
        # one failed attempt + one (growing) sleep per cycle, not one
        # hot failure per queued op.
        self._backoff = JitteredBackoff(backoff_min_s, backoff_max_s)
        # Invoked once per successfully drained op (request-amplification
        # accounting; metrics.AgentMetrics.register_sink points it at the
        # per-sink elastic_tpu_sink_writes_total counter). Note ops are
        # thunks: a batched op (boot inventory publish) counts once.
        self.on_write: Optional[Callable[[], None]] = None
        self._writes = 0
        # Insertion-ordered op store: coalescing keys map to their newest
        # op in O(1); un-keyed ops get a unique sequence number. Dict
        # order gives O(1) drop-oldest and preserves submit order.
        self._items: "dict[object, Callable]" = {}
        self._seq = 0
        self._failures = 0
        self._disabled = False
        self._stopping = False
        self._busy = False
        self._dropped = 0
        self._merged = 0
        # Per-op failure counts for the ops currently cycling through
        # failed flushes: an op that keeps failing while LATER ops would
        # succeed (a deterministic 4xx, not a dead apiserver) is dropped
        # after max_failures of its OWN failures instead of head-of-line
        # blocking the queue until the whole sink disables. Pruned on
        # success/drop and at requeue, so it only ever holds the keys of
        # currently-failing ops.
        self._op_failures: "dict[object, int]" = {}
        self._cond = threading.Condition()
        self._worker_error: Optional[BaseException] = None
        self._thread = self._spawn_worker()

    def _spawn_worker(self) -> threading.Thread:
        t = threading.Thread(target=self._worker, daemon=True, name=self._name)
        t.start()
        return t

    @property
    def name(self) -> str:
        return self._name

    @property
    def disabled(self) -> bool:
        return self._disabled

    @property
    def dropped(self) -> int:
        """Ops discarded by the queue bound since start."""
        return self._dropped

    @property
    def merged(self) -> int:
        """Queued ops superseded by a newer same-key submission before
        they were drained (each one is an apiserver write the coalescing
        saved)."""
        return self._merged

    @property
    def writes_total(self) -> int:
        """Ops successfully drained since start (racy read — a gauge/
        introspection feed, not an invariant)."""
        return self._writes

    @property
    def queue_depth(self) -> int:
        """Ops currently queued (racy read — it feeds a gauge)."""
        return len(self._items)

    @property
    def consecutive_failures(self) -> int:
        """Current failure streak (resets on success; the sink disables
        itself at max_failures)."""
        return self._failures

    def submit(self, op: Callable, key: Optional[object] = None) -> None:
        """Enqueue a thunk; non-blocking, never raises. A non-None ``key``
        coalesces: any queued op with the same key is superseded."""
        if self._disabled:
            return
        with self._cond:
            if self._stopping:
                return
            if key is None:
                self._seq += 1
                key = ("_seq", self._seq)
            elif self._items.pop(key, None) is not None:
                # superseding moves the write to the newest position
                self._merged += 1
            if len(self._items) >= self._max_queue:
                oldest = next(iter(self._items))
                del self._items[oldest]  # drop-oldest: newer state wins
                self._dropped += 1
                if self._on_drop is not None:
                    try:
                        self._on_drop()
                    except Exception:  # noqa: BLE001
                        pass
                if self._dropped in (1, 100) or self._dropped % 1000 == 0:
                    logger.warning(
                        "%s queue full (%d): dropped %d op(s) so far",
                        self._name, self._max_queue, self._dropped,
                    )
            self._items[key] = op
            self._cond.notify_all()

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until queued work has drained (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._items or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def stop(self, timeout: float = 30.0) -> None:
        """Drain-then-stop: the worker writes everything already queued,
        then exits. The timeout only guards a wedged apiserver op (the
        thread is a daemon and dies with the process in that case)."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            logger.warning(
                "%s worker still draining after %.1fs; abandoning "
                "(%d op(s) may be lost)",
                self._name, timeout, len(self._items),
            )

    # -- supervision (supervisor.py) ------------------------------------------

    def run_supervised(self, stop: threading.Event) -> None:
        """Supervisor target: watch the internal worker thread; if it died
        on an uncaught exception, re-raise that error so the supervisor's
        restart/backoff/circuit-breaker accounting applies, and respawn
        the worker on the next (supervisor-driven) invocation. Returns
        cleanly on global stop or owner ``stop()`` (drain-exit)."""
        with self._cond:
            if not self._thread.is_alive() and not self._stopping:
                self._worker_error = None
                self._thread = self._spawn_worker()
        while not stop.is_set():
            self._thread.join(timeout=0.5)
            if not self._thread.is_alive():
                if self._stopping:
                    return  # drain-exit: the owner stopped this sink
                err = self._worker_error
                raise err if err is not None else RuntimeError(
                    f"{self._name} worker exited without stop"
                )

    def _worker(self) -> None:
        try:
            self._worker_body()
        except BaseException as e:  # noqa: BLE001 - recorded for supervision
            # A dead worker would silently stop draining the queue; record
            # the death so run_supervised() can surface it and respawn.
            # DieThread (fault injection) lands here too — deliberately.
            with self._cond:
                self._worker_error = e
                self._busy = False
                self._cond.notify_all()  # un-wedge flush()ers

    def _wait_until(self, end: float) -> None:
        """Sleep on the condition until ``end`` (monotonic) or stop; a
        plain sleep would ignore stop(), a single cond.wait would be cut
        short by every submit."""
        with self._cond:
            while not self._stopping:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return
                self._cond.wait(timeout=remaining)

    def _count_drop(self, n: int = 1) -> None:
        self._dropped += n
        if self._on_drop is not None:
            for _ in range(n):
                try:
                    self._on_drop()
                except Exception:  # noqa: BLE001
                    pass

    def _worker_body(self) -> None:
        while True:
            with self._cond:
                while not self._items and not self._stopping:
                    self._cond.wait()
                if not self._items:  # stopping and drained
                    self._cond.notify_all()
                    return
            # Coalescing window: linger so a burst submitted together is
            # drained together — same-key ops dedup in the queue instead
            # of each paying an apiserver write. Skipped when stopping
            # (drain fast) or disabled (nothing will be written anyway).
            if (
                self._flush_window_s > 0
                and not self._stopping and not self._disabled
            ):
                self._wait_until(time.monotonic() + self._flush_window_s)
            # Failpoint BEFORE the batch is claimed: a raise/die-thread
            # here leaves every queued op intact for the respawned worker
            # (the chaos suite asserts nothing is dropped across a worker
            # crash). Only this worker pops, so the re-lock is race-free.
            faults.fire(f"sink.{self._name}")
            with self._cond:
                batch = list(self._items.items())
                self._items = {}
                self._busy = True
            failed_at: Optional[int] = None
            error: Optional[Exception] = None
            i = 0
            while i < len(batch):
                key, op = batch[i]
                if self._disabled:
                    # claimed-after-disable: dropped like submit refuses,
                    # but COUNTED — this is where losses are largest
                    self._count_drop(len(batch) - i)
                    i = len(batch)
                    break
                try:
                    op()
                except Exception as e:  # noqa: BLE001 - must not wedge
                    fails = self._op_failures.get(key, 0) + 1
                    if fails >= self._max_failures:
                        # This op ITSELF keeps failing while the flush
                        # around it may be fine (deterministic apiserver
                        # rejection): drop it and keep draining, rather
                        # than head-of-line blocking the queue until the
                        # whole sink disables.
                        self._op_failures.pop(key, None)
                        self._count_drop()
                        logger.warning(
                            "%s op dropped after %d failed attempts "
                            "(last: %s)", self._name, fails, e,
                        )
                        i += 1
                        continue
                    self._op_failures[key] = fails
                    failed_at, error = i, e
                    break
                self._op_failures.pop(key, None)
                self._failures = 0
                self._backoff.reset()
                self._writes += 1
                cb = self.on_write
                if cb is not None:
                    try:
                        cb()
                    except Exception:  # noqa: BLE001
                        pass
                i += 1
            if failed_at is None:
                with self._cond:
                    self._busy = False
                    if not self._items:
                        self._cond.notify_all()
                continue
            # Failed flush: ONE streak bump for the whole attempt (not
            # one per queued op), the unwritten tail re-queued for the
            # retry (ops superseded by a newer same-key submission while
            # we held the batch stay superseded), and one shared backoff
            # clock before the next attempt.
            self._failures += 1
            disable = self._failures >= self._max_failures
            with self._cond:
                if disable:
                    self._disabled = True
                    # the unwritten tail dies with the sink: counted
                    self._count_drop(len(batch) - failed_at)
                    self._op_failures.clear()
                else:
                    requeue = {}
                    for key, op in batch[failed_at:]:
                        if key in self._items:
                            self._merged += 1
                        else:
                            requeue[key] = op
                    self._items = {**requeue, **self._items}
                    # failure counters only for ops still in play
                    self._op_failures = {
                        k: v for k, v in self._op_failures.items()
                        if k in self._items
                    }
                    # Re-apply the queue bound: the requeue merged with
                    # ops submitted during the flush/backoff, and the
                    # documented memory bound must hold through failure
                    # cycles too (drop-oldest, counted as ever).
                    excess = len(self._items) - self._max_queue
                    if excess > 0:
                        for old in list(self._items)[:excess]:
                            del self._items[old]
                        self._count_drop(excess)
                self._busy = False
                if not self._items:
                    self._cond.notify_all()
            if disable:
                logger.warning(
                    "%s disabled after %d consecutive failed flushes "
                    "(last: %s; %d op(s) dropped)",
                    self._name, self._failures, error,
                    len(batch) - failed_at,
                )
                continue
            delay = self._backoff.next_delay()
            logger.warning(
                "%s flush failed (%s); retrying %d queued op(s) in "
                "%.1fs (streak %d/%d)",
                self._name, error, len(batch) - failed_at, delay,
                self._failures, self._max_failures,
            )
            self._wait_until(time.monotonic() + delay)
