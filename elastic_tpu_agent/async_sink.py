"""Shared async fire-and-forget sink for observability writers.

Both apiserver-facing observability paths (ElasticTPU CRD publication,
k8s Event emission) have the same constraints: they must stay off the
bind-latency hot path (BASELINE.md SLO), must never raise into callers,
and must self-disable after consecutive failures so a missing CRD or
denied RBAC can't spam the apiserver forever. This worker implements
that contract once.
"""

from __future__ import annotations

import logging
import queue
import threading
import time

logger = logging.getLogger(__name__)

_STOP = object()

MAX_CONSECUTIVE_FAILURES = 5


class AsyncSink:
    """Single worker thread draining a queue of thunks; self-disables
    after ``max_failures`` consecutive errors."""

    def __init__(
        self, name: str, max_failures: int = MAX_CONSECUTIVE_FAILURES
    ) -> None:
        self._name = name
        self._max_failures = max_failures
        self._queue: "queue.Queue" = queue.Queue()
        self._failures = 0
        self._disabled = False
        self._stopping = False
        self._pending = 0
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name=name
        )
        self._thread.start()

    @property
    def disabled(self) -> bool:
        return self._disabled

    def submit(self, op) -> None:
        """Enqueue a thunk; non-blocking, never raises."""
        if self._disabled or self._stopping:
            return
        with self._cond:
            if self._stopping:
                return
            self._pending += 1
            # put() under the lock (unbounded queue, never blocks): a put
            # outside it could land after stop()'s drain and strand _pending.
            self._queue.put(op)

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until queued work has drained (tests / shutdown)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
        return True

    def stop(self, timeout: float = 5.0) -> None:
        # Refuse new work before flushing so a submit() racing with stop()
        # cannot land behind the _STOP sentinel and strand _pending > 0.
        with self._cond:
            self._stopping = True
        self.flush(timeout=timeout)
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # Worker is wedged on a slow op; it is a daemon thread and dies
            # with the process. (No queue drain is needed: submit() enqueues
            # under the lock after re-checking _stopping, so nothing can land
            # behind the _STOP sentinel.)
            logger.warning("%s worker did not stop within %.1fs", self._name,
                           timeout)

    def _worker(self) -> None:
        while True:
            op = self._queue.get()
            if op is _STOP:
                return
            try:
                if not self._disabled:
                    op()
                    self._failures = 0
            except Exception as e:  # noqa: BLE001 - observability must not wedge
                self._failures += 1
                if self._failures >= self._max_failures:
                    self._disabled = True
                    logger.warning(
                        "%s disabled after %d consecutive failures (last: %s)",
                        self._name, self._failures, e,
                    )
                else:
                    logger.warning("%s write failed (%s); continuing",
                                   self._name, e)
            finally:
                with self._cond:
                    self._pending -= 1
                    if self._pending <= 0:
                        self._cond.notify_all()
