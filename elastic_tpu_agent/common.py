"""Constants and small shared helpers.

Capability parity with the reference's ``pkg/common`` (SURVEY.md §1 L7):
resource names, annotation keys, granularity constants, signal handling.
Resource and annotation *keys* keep the ``elasticgpu.io`` group so the
external elastic scheduler contract carries over unchanged; the resources
themselves are TPU-native.
"""

from __future__ import annotations

import contextlib
import datetime
import faulthandler
import os
import random
import signal
import sys
import threading
import time
import zlib

# -- Extended resource names (TPU-native; reference: vendor types.go:105-112) --
ResourceTPUCore = "elasticgpu.io/tpu-core"
ResourceTPUMemory = "elasticgpu.io/tpu-memory"

# Core-share granularity: 100 units per chip => 1% steps
# (reference: pkg/common/const.go:4 GPUPercentEachCard).
TPUPercentEachChip = 100

# Memory-share granularity: 1 fake device per MiB of HBM
# (reference: gpushare.go:161).
BytesPerMemoryUnit = 1024 * 1024

# -- Scheduler contract: pod annotations (reference: const.go:5-6) -----------
AnnotationAssumed = "elasticgpu.io/assumed"
AnnotationContainerPrefix = "elasticgpu.io/container-"

# Cross-component trace continuity: whoever admits/schedules the pod may
# stamp a correlation id here; the agent that binds it adopts the id for
# its bind trace (tracing.Tracer.adopt_id), so one trace id follows the
# pod from apiserver admission to the node that bound it. Optional — an
# unstamped pod just gets a node-local id as before.
AnnotationTraceID = "elasticgpu.io/trace-id"

# Multi-host slice annotations (TPU-native addition; SURVEY.md §2 note on
# slice enablement / BASELINE config 5).
AnnotationSliceName = "elasticgpu.io/tpu-slice"
AnnotationSliceWorkerID = "elasticgpu.io/tpu-slice-worker-id"
AnnotationSliceWorkerHosts = "elasticgpu.io/tpu-slice-hosts"
# Job-unique slice identity (slices/registry.py): pods carrying the same
# id are members of ONE logical slice — the registry groups membership,
# validates consistency and drives elastic reform by this key. The
# `tpu-slice` annotation above names the SHAPE (accelerator type); this
# one names the instance.
AnnotationSliceID = "elasticgpu.io/tpu-slice-id"

# Slice-orchestrator env stamped alongside the TPU_* topology contract
# (slices/registry.py): the slice's identity, and a generation counter
# the runner can watch — the reconciler bumps it when it re-forms the
# slice at a new world size, signalling checkpoint-restore.
EnvSliceName = "ELASTIC_TPU_SLICE_NAME"
EnvSliceEpoch = "ELASTIC_TPU_SLICE_EPOCH"

# -- Graceful drain lifecycle (drain.py) --------------------------------------
# Operator-requested drain: the node annotation an admin (or an external
# controller) sets to ask this node's agent to cordon + drain; removing
# it cancels/re-admits.
AnnotationDrain = "elasticgpu.io/drain"
# Stamped by a DRAINING agent onto its resident slice-member pods so
# cooperating agents' registries see the member as already lost and
# re-form the survivor world BEFORE the host actually dies (the
# proactive half of elastic recovery; slices/registry.py counts an
# annotated pod as not-live).
AnnotationDraining = "elasticgpu.io/draining"
# Env restamped into resident pods' alloc specs when a drain starts: the
# trigger (maintenance:<event> | preemption[:...] | operator:<source>)
# and the hard wall-clock deadline (unix seconds) after which bindings
# are reclaimed. The runner treats the signal as "checkpoint now".
EnvDrain = "ELASTIC_TPU_DRAIN"
EnvDrainDeadline = "ELASTIC_TPU_DRAIN_DEADLINE"

# -- Dynamic fractional re-partitioning (repartition.py) ----------------------
# Opt-in contract: pods carrying this annotation (truthy) let the agent
# renegotiate their ELASTIC_TPU_CORE_UNITS / HBM quota live — grow from a
# co-located idle pod's slack, shrink back under pressure — and accept
# the throttle -> evict escalation when they sustain overcommit.
AnnotationRepartition = "elasticgpu.io/repartition"
# Env restamped into a sustained-overcommitter's alloc specs when the
# alarm escalates to a throttle: the reason, and the wall-clock deadline
# (unix seconds) past which the binding is reclaimed if the pod is still
# over its (clamped) quota. Removed when the pod returns within grant.
EnvThrottle = "ELASTIC_TPU_THROTTLE"
EnvThrottleDeadline = "ELASTIC_TPU_THROTTLE_DEADLINE"
# Subdirectory of the alloc-spec dir where opted-in workloads publish
# self-measured utilization ({"ts", "duty_cycle_percent"} keyed by the
# allocation hash). ONE spelling shared by the writer
# (workloads/telemetry.write_usage_report), the reader (sampler) and
# the reclaim path (tpushare.remove_alloc_spec).
UsageReportSubdir = "usage"

# -- Migration handshake (migration.py + workloads/lifecycle.py) --------------
# Subdirectory of the alloc-spec dir where workloads acknowledge a
# checkpoint-restore signal: an atomic ``ack/<alloc hash>.json``
# ({"ts", "step", "checkpoint_dir", "digest", ...}) written by the pod's
# lifecycle watcher the moment its checkpoint is durable. The agent's
# MigrationCoordinator consumes acks to complete drains early, gate QoS
# eviction and verify resumes.
AckSubdir = "ack"
# Subdirectory where a workload's flight recorder publishes its rolling
# summary ({"ts", "tokens_per_s", ...} keyed by allocation hash;
# workloads/telemetry.write_flight_summary). The sampler reads fresh
# summaries so elastic_tpu_workload_tokens_per_second{pod} reaches
# /metrics — achieved throughput next to granted/used percent.
FlightSummarySubdir = "flight"
# Every per-allocation sidecar file family living under the alloc-spec
# dir: ONE list shared by the spec reclaim path
# (tpushare.remove_alloc_spec) and the reconciler's orphan-spec sweep,
# so a new sidecar kind can never be added to one reclaimer and leak
# through the other.
AllocSidecarSubdirs = (UsageReportSubdir, AckSubdir, FlightSummarySubdir)
# Env restamped into a REPLACEMENT pod's alloc specs by the destination
# agent when a published MigrationRecord names a checkpoint the workload
# should resume from: the checkpoint directory, the acked step, and the
# source bind's trace id (so the resume ack joins the same story).
EnvRestoreDir = "ELASTIC_TPU_RESTORE_DIR"
EnvRestoreStep = "ELASTIC_TPU_RESTORE_STEP"
EnvRestoreTrace = "ELASTIC_TPU_RESTORE_TRACE"
# Pre-copy cutover signal (migration.py -> workloads/lifecycle.py): a
# draining workload that streams delta checkpoints (kind="precopy" acks)
# keeps training until the coordinator stamps this env into its alloc
# specs — the value is the cutover generation ("<drain trigger>:<round>")
# so repeated cutovers within one agent lifetime each fire their own
# signal edge. On the edge the workload pauses, ships the FINAL delta
# and writes its ordinary kind="checkpoint" ack; downtime is the final
# delta, not the full state.
EnvCutover = "ELASTIC_TPU_CUTOVER"

# -- Container env contract ---------------------------------------------------
# Env carrying the allocation hash into the container; the OCI hook resolves
# it back to physical chips (reference used "GPU", main.go:200 — we accept
# both; see native/elastic_tpu_hook.cc).
EnvAllocationHash = "TPU"
EnvAllocationHashCompat = "GPU"
# Visibility env consumed by libtpu/JAX inside the container. Both spellings
# are emitted everywhere (alloc env, spec files, native toolkit): older
# libtpu releases read TPU_VISIBLE_DEVICES, newer ones TPU_VISIBLE_CHIPS.
EnvTPUVisibleChips = "TPU_VISIBLE_CHIPS"
EnvTPUVisibleDevices = "TPU_VISIBLE_DEVICES"

# -- Virtual device node naming ----------------------------------------------
# /dev/elastic-tpu-<hash>-<i> -> /dev/accel<chip_index>
# (reference scheme: /dev/elastic-gpu-<id> -> /dev/nvidiaN, gpushare.go:9-16)
VirtualDevPrefix = "elastic-tpu-"

# Host /dev as mounted into the agent container (deploy manifest hostPath).
HostDevRoot = os.environ.get("ELASTIC_TPU_HOST_DEV", "/host/dev")

# Sentinel index for delete paths that ignore the index
# (reference: common.go:4 UselessNumber).
USELESS_NUMBER = -1

# TPU-relay (PJRT plugin) environment: registration happens at jax
# IMPORT regardless of the selected platform, and a wedged relay hangs
# it nondeterministically — CPU-pinned processes (tests and their real
# subprocesses, the driver's dryrun) strip these before importing jax.
# One list, imported by every strip site (tests/conftest.py,
# __graft_entry__.py): a new relay var added to one copy but not the
# other would bring the hang back.
RELAY_ENV_PREFIXES = ("AXON_", "PALLAS_AXON_", "TPU_")
RELAY_ENV_VARS = ("PJRT_LIBRARY_PATH", "_AXON_REGISTERED")


def strip_relay_env(environ=None) -> None:
    """Remove the relay plugin's env vars in place (default:
    os.environ). Call BEFORE the first jax import of a CPU-pinned
    process."""
    env = os.environ if environ is None else environ
    for k in list(env):
        if k.startswith(RELAY_ENV_PREFIXES) or k in RELAY_ENV_VARS:
            env.pop(k)

NEVER_STOP: "threading.Event" = threading.Event()  # never set: wait forever


class Clock:
    """Injectable time source — the seam that keeps time-dependent
    subsystems (the lifecycle timeline, drain phase accounting) testable
    without sleep-based polling: production code takes a ``clock``
    argument defaulting to :data:`SYSTEM_CLOCK`; tests hand in a
    :class:`ManualClock` and *advance* it, so "an hour passed" is one
    method call instead of a wall-clock wait."""

    def time(self) -> float:
        """Wall-clock seconds (``time.time()``)."""
        return time.time()

    def monotonic(self) -> float:
        """Monotonic seconds (``time.monotonic()``)."""
        return time.monotonic()


SYSTEM_CLOCK = Clock()


class ManualClock(Clock):
    """A clock that only moves when told to (tests). Starts at a fixed,
    recognizably-fake wall time so an un-injected SYSTEM_CLOCK sneaking
    into a code path under test shows up as a wildly different ts."""

    def __init__(self, start: float = 1_000_000_000.0) -> None:
        self._time = start
        self._monotonic = 0.0

    def time(self) -> float:
        return self._time

    def monotonic(self) -> float:
        return self._monotonic

    def advance(self, seconds: float) -> None:
        self._time += seconds
        self._monotonic += seconds


def container_annotation(container: str) -> str:
    """Annotation key holding the chip indexes for one container,
    e.g. elasticgpu.io/container-train -> "0,1"."""
    return AnnotationContainerPrefix + container


def install_dump_signal(log_dir: str = "/var/log") -> None:
    """SIGUSR1 -> dump all thread stacks to a timestamped log file
    (reference: SIGUSR1 goroutine dump, pkg/common/util.go:58-97)."""

    def _dump(signum, frame):  # noqa: ARG001
        ts = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
        path = os.path.join(log_dir, f"thread-stacks-{ts}.log")
        try:
            with open(path, "w") as f:
                faulthandler.dump_traceback(file=f)
        except OSError:
            faulthandler.dump_traceback(file=sys.stderr)

    signal.signal(signal.SIGUSR1, _dump)


def wait_for_exit_signal() -> int:
    """Block until SIGTERM/SIGINT/SIGQUIT; return the signal number
    (reference: pkg/common/util.go:52-66)."""
    received: list = []
    ev = threading.Event()

    def _handler(signum, frame):  # noqa: ARG001
        received.append(signum)
        ev.set()

    for s in (signal.SIGTERM, signal.SIGINT, signal.SIGQUIT):
        signal.signal(s, _handler)
    ev.wait()
    return received[0] if received else 0


class StripedLockSet:
    """A fixed array of locks indexed by a stable hash of a string key.

    The concurrency primitive behind the bind pipeline: kubelet drives
    Allocate/PreStartContainer from a gRPC thread pool, so core+memory
    sibling pairs for ONE container (same pod key) must serialize while
    unrelated pods proceed in parallel. Striping (rather than a lock per
    key) keeps memory bounded under pod churn; crc32 (not ``hash()``)
    keys the stripes so the mapping is stable across processes and
    PYTHONHASHSEED — reproducible in benchmarks and debuggable from a
    stack dump.

    ``stripes=1`` degenerates to a single global lock (the pre-striping
    behavior; bench.py uses it as the same-run baseline).
    """

    def __init__(self, stripes: int = 64) -> None:
        self._locks = tuple(
            threading.Lock() for _ in range(max(1, stripes))
        )
        self._stats_lock = threading.Lock()
        self.acquires_total = 0
        self.contended_total = 0
        self.wait_seconds_total = 0.0

    @property
    def stripes(self) -> int:
        return len(self._locks)

    def lock_for(self, key: str) -> "threading.Lock":
        return self._locks[zlib.crc32(key.encode("utf-8")) % len(self._locks)]

    def acquire_key(self, key: str) -> float:
        """Block until the stripe for ``key`` is held; returns the seconds
        spent waiting (0.0 when uncontended) so callers can export
        contention. Pair with release_key(key)."""
        lock = self.lock_for(key)
        contended = not lock.acquire(blocking=False)
        wait_s = 0.0
        if contended:
            t0 = time.monotonic()
            lock.acquire()
            wait_s = time.monotonic() - t0
        with self._stats_lock:
            self.acquires_total += 1
            if contended:
                self.contended_total += 1
                self.wait_seconds_total += wait_s
        return wait_s

    def release_key(self, key: str) -> None:
        self.lock_for(key).release()

    @contextlib.contextmanager
    def acquire(self, key: str):
        """Context-manager form of acquire_key/release_key; yields the
        wait seconds."""
        wait_s = self.acquire_key(key)
        try:
            yield wait_s
        finally:
            self.release_key(key)

    def stats(self) -> dict:
        with self._stats_lock:
            return {
                "stripes": len(self._locks),
                "acquires_total": self.acquires_total,
                "contended_total": self.contended_total,
                "wait_seconds_total": round(self.wait_seconds_total, 6),
            }


class JitteredBackoff:
    """Exponential backoff with 0.5x–1.5x jitter, shared by every retry
    loop (sitter list/watch, subsystem supervision). The jitter matters
    at fleet scale: one agent per node means a dead shared dependency
    (apiserver) gets hit by every node in lockstep without it."""

    def __init__(self, min_s: float, max_s: float, rng=None) -> None:
        self._min = min_s
        self._max = max_s
        self._rng = rng if rng is not None else random.Random()
        self._current = min_s

    def next_delay(self) -> float:
        """Jittered delay to sleep now; doubles the base for next time."""
        delay = self._current * (0.5 + self._rng.random())
        self._current = min(self._current * 2, self._max)
        return delay

    def reset(self) -> None:
        self._current = self._min


class FileWatcher:
    """Poll-based watch for file creation/replacement.

    Replaces the reference's fsnotify watcher (util.go:99-114) for the one
    thing it was used for: noticing that kubelet.sock was re-created after a
    kubelet restart (SURVEY.md §3.4). Polling by (st_ino, st_dev, st_ctime)
    is dependency-free and race-robust; 1s cadence matches the reference's
    reaction latency.
    """

    def __init__(self, path: str) -> None:
        self._path = path
        self._sig = self._stat_sig()

    def _stat_sig(self):
        try:
            st = os.stat(self._path)
            return (st.st_ino, st.st_dev, st.st_ctime_ns)
        except OSError:
            return None

    def changed(self) -> bool:
        """True when the file appeared, vanished, or was replaced since the
        last call that returned True (or construction)."""
        sig = self._stat_sig()
        if sig != self._sig:
            self._sig = sig
            return True
        return False


def read_rss_bytes() -> int:
    """This process's resident set size, from ``/proc/self/statm``
    (field 2 is resident pages). Stub-safe: any failure — non-Linux,
    locked-down /proc — reads as 0, never an exception, so the
    ``elastic_tpu_agent_rss_bytes`` gauge and the doctor bundle can
    carry it unconditionally."""
    try:
        with open("/proc/self/statm") as f:
            resident_pages = int(f.read().split()[1])
        return resident_pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001 - a memory gauge must never raise
        return 0
