"""ElasticTPU CRD types + typed client.

Capability parity with the reference's vendored ElasticGPU CRD API and
generated clientset (SURVEY.md §2 #19, vendor/elasticgpu.io/elastic-gpu):
the agent can read/create cluster-level ElasticTPU inventory objects. As
in the reference (where all CRD-writing paths were commented out,
plugins/nvidia.go:28-137), the CRD surface is optional — the core
allocation path never depends on it — but here it actually works and is
exercised by tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .kube.client import KubeClient, KubeError

GROUP = "elasticgpu.io"
VERSION = "v1alpha1"
PLURAL = "elastictpus"
KIND = "ElasticTPU"
NodeLabel = "elasticgpu.io/node"
# Stamped on MigrationRecord objects (migration.py) so destination
# agents can LIST just the records with a labelSelector instead of
# downloading the cluster-wide per-allocation collection — the same
# reason NodeLabel exists for node-scoped lists.
MigrationLabel = "elasticgpu.io/migration"

# Canonical phases (reference types.go:49-57).
PhasePending = "Pending"
PhaseAvailable = "Available"
PhaseBound = "Bound"
PhaseReleased = "Released"
PhaseFailed = "Failed"
# TPU-native addition (migration.py): a MigrationRecord — the source
# agent verified a resident's checkpoint durable before reclaiming its
# chips, and the record tells whichever node binds the replacement pod
# where to restore from. Deleted by the destination once the resume is
# verified.
PhaseMigrated = "Migrated"


@dataclass
class ElasticTPU:
    name: str
    node_name: str = ""
    capacity: Dict[str, str] = field(default_factory=dict)
    chip_indexes: List[int] = field(default_factory=list)
    accelerator_type: str = ""
    claim_namespace: str = ""
    claim_name: str = ""
    claim_container: str = ""
    phase: str = PhasePending
    message: str = ""
    # MigrationRecord payload (phase Migrated, migration.py): checkpoint
    # location/step/digest, source node, last topology env and the bind
    # trace id — everything the destination agent needs to stamp the
    # restore env and verify the resume. None on ordinary objects.
    migration: Optional[Dict] = None
    # Server-assigned; must round-trip into updates (a real apiserver
    # rejects RV-less PUTs on custom resources).
    resource_version: str = ""

    def to_manifest(self) -> dict:
        metadata: dict = {"name": self.name}
        if self.resource_version:
            metadata["resourceVersion"] = self.resource_version
        labels: dict = {}
        if self.node_name:
            # Node-scoped label so agents can list with a labelSelector
            # instead of downloading the cluster-wide collection.
            labels[NodeLabel] = self.node_name
        if self.migration is not None:
            labels[MigrationLabel] = "true"
        if labels:
            metadata["labels"] = labels
        spec = {
            "nodeName": self.node_name,
            "capacity": dict(self.capacity),
            "source": {
                "physicalTPU": {"chipIndexes": list(self.chip_indexes)},
                "tpuShare": {
                    "acceleratorType": self.accelerator_type,
                },
            },
            "claimRef": {
                "namespace": self.claim_namespace,
                "name": self.claim_name,
                "container": self.claim_container,
            },
        }
        if self.migration is not None:
            spec["migration"] = dict(self.migration)
        return {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": KIND,
            "metadata": metadata,
            "spec": spec,
            "status": {"phase": self.phase, "message": self.message},
        }

    @classmethod
    def from_manifest(cls, m: dict) -> "ElasticTPU":
        spec = m.get("spec", {}) or {}
        source = spec.get("source", {}) or {}
        claim = spec.get("claimRef", {}) or {}
        status = m.get("status", {}) or {}
        return cls(
            name=m.get("metadata", {}).get("name", ""),
            node_name=spec.get("nodeName", ""),
            capacity=dict(spec.get("capacity", {}) or {}),
            chip_indexes=list(
                (source.get("physicalTPU", {}) or {}).get("chipIndexes", [])
            ),
            accelerator_type=(
                (source.get("tpuShare", {}) or {}).get("acceleratorType", "")
            ),
            claim_namespace=claim.get("namespace", ""),
            claim_name=claim.get("name", ""),
            claim_container=claim.get("container", ""),
            phase=status.get("phase", PhasePending),
            message=status.get("message", ""),
            migration=(
                dict(spec["migration"])
                if isinstance(spec.get("migration"), dict) else None
            ),
            resource_version=m.get("metadata", {}).get("resourceVersion", ""),
        )


class ElasticTPUClient:
    """Typed CRUD over the CRD endpoint (generated-clientset equivalent)."""

    def __init__(self, kube: KubeClient) -> None:
        self._kube = kube
        self._base = f"/apis/{GROUP}/{VERSION}/{PLURAL}"

    def create(self, obj: ElasticTPU, update_existing: bool = True) -> ElasticTPU:
        """Create; on 409 AlreadyExists, update in place by default (the
        agent republishes its chip inventory on every boot).

        The CRD declares the status subresource (deploy/elastic-tpu-crd.yaml),
        so a real apiserver strips ``status`` from main-endpoint writes; the
        requested phase is applied with a second PUT to ``/status``."""
        r = self._kube._post(self._base, obj.to_manifest())
        if r.status_code == 409 and update_existing:
            existing = self.get(obj.name)
            if existing is not None:
                # Updates must carry the server's current resourceVersion.
                obj.resource_version = existing.resource_version
            r = self._kube._put(
                f"{self._base}/{obj.name}", obj.to_manifest()
            )
        if r.status_code not in (200, 201):
            raise KubeError(f"create elastictpu {obj.name}: {r.status_code}")
        created = ElasticTPU.from_manifest(r.json())
        self._put_status(created, obj.phase, obj.message)
        return created

    def _put_status(self, obj: ElasticTPU, phase: str, message: str) -> None:
        """PUT to /status using obj's resourceVersion; obj is refreshed with
        the server's new state on success."""
        obj.phase, obj.message = phase, message
        r = self._kube._put(
            f"{self._base}/{obj.name}/status", obj.to_manifest()
        )
        if r.status_code != 200:
            raise KubeError(
                f"update elastictpu {obj.name} status: {r.status_code}"
            )
        obj.resource_version = (
            r.json().get("metadata", {}).get("resourceVersion", "")
        )

    def get(self, name: str) -> Optional[ElasticTPU]:
        r = self._kube._get(f"{self._base}/{name}")
        if r.status_code == 404:
            return None
        if r.status_code != 200:
            raise KubeError(f"get elastictpu {name}: {r.status_code}")
        return ElasticTPU.from_manifest(r.json())

    def list(self, node_name: str = "") -> List[ElasticTPU]:
        params = (
            {"labelSelector": f"{NodeLabel}={node_name}"} if node_name else None
        )
        r = self._kube._get(self._base, params=params)
        if r.status_code != 200:
            raise KubeError(f"list elastictpus: {r.status_code}")
        items = [
            ElasticTPU.from_manifest(m) for m in r.json().get("items", [])
        ]
        if node_name:
            # Belt-and-braces for objects created before the label existed.
            items = [i for i in items if i.node_name == node_name]
        return items

    def list_migrations(self) -> List[ElasticTPU]:
        """Only the MigrationRecord objects (labelSelector-scoped):
        the destination-role discovery LIST must not scale with the
        fleet's per-allocation object count."""
        r = self._kube._get(
            self._base, params={"labelSelector": f"{MigrationLabel}=true"}
        )
        if r.status_code != 200:
            raise KubeError(f"list migration records: {r.status_code}")
        return [
            ElasticTPU.from_manifest(m) for m in r.json().get("items", [])
        ]

    def delete(self, name: str) -> None:
        r = self._kube._delete(f"{self._base}/{name}")
        if r.status_code not in (200, 404):
            raise KubeError(f"delete elastictpu {name}: {r.status_code}")

    def update_status(self, name: str, phase: str, message: str = "") -> None:
        obj = self.get(name)
        if obj is None:
            raise KubeError(f"elastictpu {name} not found")
        self._put_status(obj, phase, message)
