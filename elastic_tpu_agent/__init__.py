"""elastic-tpu-agent: a TPU-native Kubernetes node agent.

Built from scratch with the capability set of elastic-ai/elastic-gpu-agent
(see SURVEY.md): a privileged DaemonSet that discovers Cloud TPU chips /
TensorCores / HBM, registers ``elasticgpu.io/tpu-core`` and
``elasticgpu.io/tpu-memory`` as fractional extended resources through the
kubelet device-plugin v1beta1 API, binds allocations to pods placed by an
external elastic scheduler via pod annotations, materializes hash-named
virtual device nodes, injects TPU device nodes + env through an OCI prestart
hook, persists bindings for restart recovery, and garbage-collects leaked
allocations.

Layer map (mirrors reference SURVEY.md §1, re-designed TPU-first):

  cli.py        L1  process entry (flags, signals)
  manager.py    L2  lifecycle wiring + Restore()
  plugins/      L3  kubelet device-plugin servers (the core)
  tpu/          L4  physical device layer (chip discovery + /dev nodes)
  kube/         L5  k8s adapters (pod informer + device->pod locator)
  storage/      L6  checkpoint persistence (pod->container->device map)
  types.py      L7  Device / PodInfo value types
  native/ (C/C++, repo root)  L8  container-runtime integration
  deploy/ (repo root)         L9  DaemonSet + RBAC + CRD
"""

__version__ = "0.1.0"
