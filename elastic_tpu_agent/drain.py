"""Graceful drain lifecycle: maintenance, preemption, operator drains.

Before this module the agent *detected* trouble — tpu/tpuvm.py polls the
GCE maintenance-event metadata endpoint — but the only response was
flipping every chip unhealthy, which stranded resident workloads with no
checkpoint signal and left slice peers to discover the loss after the
fact (ROADMAP item 5). Funky's cloud-native FPGA orchestration
(PAPERS.md) models the missing piece: accelerator lifecycle states —
cordon, checkpoint, migrate, reclaim — owned by the runtime layer; Arax
argues the mapping layer, not the application, should own that
placement-and-recovery contract.

This orchestrator is that layer, a per-node lifecycle state machine::

    Active -> Cordoned -> Draining -> Drained | Reclaimed
       ^__________________________________________|   (trigger cleared)

driven by three trigger sources, polled each tick:

- **maintenance**: the GCE maintenance-event value
  (``operator.maintenance_event()``; MIGRATE/TERMINATE announcements).
- **preemption**: the metadata ``preempted`` endpoint
  (``operator.preempted()``) plus a test-injectable notice
  (``faults.check("drain.preempt-notice")`` — arm with
  ``drain.preempt-notice=notice:1``).
- **operator-requested**: the ``elasticgpu.io/drain`` node annotation,
  or the local :meth:`request_drain` admin seam.

On trigger, the node drains gracefully instead of failing:

1. **Cordon** — devices go unschedulable in ListAndWatch (kubelet stops
   NEW placements) *without* failing health: no ChipUnhealthy events,
   no CRD Failed, no eviction hooks; resident bindings ride on.
2. **Signal** — every resident pod's alloc specs are restamped (under
   the owner's bind stripe, the SliceReformer mechanism) with
   ``ELASTIC_TPU_DRAIN=<trigger>`` and a deadline-bearing
   ``ELASTIC_TPU_DRAIN_DEADLINE``; ``TPUNodeDraining`` events fire on
   the node and each resident pod.
3. **Proactive reform** — resident slice-member pods are annotated
   ``elasticgpu.io/draining`` at the shared apiserver, so cooperating
   agents' registries count this host as lost and re-form the survivor
   world BEFORE the host dies (slices/recovery.py does the restamping
   on each survivor) instead of after a divergence pass.
4. **Checkpoint-wait, then reclaim** — residents that exit take their
   bindings with them (normal GC); at the hard deadline whatever
   remains is reclaimed through the reconciler's existing repair
   classes (``reclaimed_pod``), leaving zero orphan artifacts.
5. **Cancel / re-admit** — a maintenance event clearing (or the drain
   annotation being removed) mid-drain uncordons, strips the drain
   signal from surviving specs, clears the draining pod annotations and
   returns to Active. Preemption never un-rings.

Every transition is journaled in Storage (``agent_state`` table) BEFORE
its side effects — the same crash-consistency discipline as bind
intents — so an agent killed at any drain failpoint
(``drain.pre_cordon`` / ``drain.post_signal`` / ``drain.pre_reclaim``)
resumes the drain, cordon and deadline included, on restart.

Supervised DEGRADED like the reconciler: a broken drain loop must not
take binding down with it; /healthz and the doctor bundle surface the
loss of lifecycle handling.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from . import faults
from .common import (
    SYSTEM_CLOCK,
    AnnotationDrain,
    AnnotationDraining,
    AnnotationSliceID,
    EnvDrain,
    EnvDrainDeadline,
)
from .types import PodContainer

logger = logging.getLogger(__name__)

# Lifecycle states (the `elastic_tpu_drain_state` gauge exports the code).
ACTIVE = "active"
CORDONED = "cordoned"
DRAINING = "draining"
DRAINED = "drained"      # every resident exited before the deadline
RECLAIMED = "reclaimed"  # deadline expired; bindings force-reclaimed

STATE_CODES = {ACTIVE: 0, CORDONED: 1, DRAINING: 2, DRAINED: 3, RECLAIMED: 4}

# Phase labels of the elastic_tpu_drain_phase_seconds histogram: how
# long cordon->every-resident-signalled took, and how long from the
# signal to the outcome (graceful exit vs deadline reclaim). PR 8 only
# counted drain totals; per-phase latency is what answers "are residents
# actually checkpointing, or are we always reclaiming at the deadline?".
PHASE_SIGNAL = "cordon_to_signaled"
PHASE_DRAINED = "signaled_to_drained"
PHASE_RECLAIMED = "signaled_to_reclaimed"

# Trigger kinds (the `trigger` label of elastic_tpu_drains_total; the
# full trigger string carries detail, e.g. "maintenance:TERMINATE_...").
TRIGGER_MAINTENANCE = "maintenance"
TRIGGER_PREEMPTION = "preemption"
TRIGGER_OPERATOR = "operator"

DEFAULT_DEADLINE_S = 300.0
# A spot/preemptible host gives roughly this much warning before the
# platform reclaims it (GCE's notice window): a preemption-triggered
# drain clamps its budget to the notice — a 300s --drain-deadline is a
# promise the host cannot keep, and cutover MUST beat the reclaim.
DEFAULT_PREEMPTION_NOTICE_S = 30.0
DEFAULT_PERIOD_S = 2.0
# How long one GET /api/v1/nodes/<name> answer (the drain-annotation
# read) stays fresh: the tick period is 2s but a fleet of agents must
# not turn annotation polling into steady apiserver load — the sibling
# trigger sources are TTL-cached the same way (maintenance/preempted).
DEFAULT_NODE_POLL_TTL_S = 10.0

_STATE_KEY = "drain"


class DrainOrchestrator:
    """Per-node graceful-drain state machine (one instance per agent)."""

    def __init__(
        self,
        operator,
        plugin,
        storage,
        sitter,
        reconciler,
        kube_client=None,
        events=None,
        metrics=None,
        node_name: str = "",
        deadline_s: float = DEFAULT_DEADLINE_S,
        preemption_notice_s: float = DEFAULT_PREEMPTION_NOTICE_S,
        period_s: float = DEFAULT_PERIOD_S,
        node_poll_ttl_s: float = DEFAULT_NODE_POLL_TTL_S,
        rng=None,
        timeline=None,
        clock=None,
        lag_tracker=None,
        bus=None,
        event_safety_net_factor: float = 1.0,
    ) -> None:
        self._operator = operator
        self._plugin = plugin
        self._storage = storage
        self._sitter = sitter
        self._reconciler = reconciler
        self._client = kube_client
        self._events = events
        self._metrics = metrics
        self._node = node_name
        self.deadline_s = deadline_s
        self.preemption_notice_s = max(0.0, float(preemption_notice_s))
        self.period_s = period_s
        self.node_poll_ttl_s = node_poll_ttl_s
        self._node_ann_asserted = False
        self._node_ann_next_poll = 0.0
        self._rng = rng if rng is not None else random.Random()
        self._timeline = timeline
        self._clock = clock if clock is not None else SYSTEM_CLOCK
        # Event bus (events.py): record deletes / agent_state writes /
        # pod deltas wake a mid-drain tick immediately (a drain whose
        # last resident exits converges on the event, not the next
        # period). The IDLE tick keeps its base period regardless of
        # the factor — maintenance triggers come from the metadata
        # poll, which no bus event can carry — and mid-drain stretched
        # waits are capped at the reclaim deadline (see run()).
        self._bus = bus
        self.event_safety_net_factor = max(1.0, float(
            event_safety_net_factor
        ))
        self._event_sub = None
        if bus is not None:
            from . import events as bus_events

            self._event_sub = bus.subscribe(
                "drain",
                (bus_events.POD_DELTA, bus_events.STORE_BIND,
                 bus_events.STORE_STATE),
            )
        self.event_ticks_total = 0
        # Wall-clock phase anchors ("cordon", "signaled"), journaled so
        # a mid-drain restart keeps measuring from the real start; the
        # observed set is journaled too — a restart after Drained must
        # not observe the phase twice.
        self._phase_ts: Dict[str, float] = {}
        self._phases_observed: List[str] = []
        self._lock = threading.Lock()
        self.state = ACTIVE
        self.trigger = ""
        self.deadline_ts: Optional[float] = None
        self._drain_requested = False
        self._maint_active = False  # first-trip edge for the event/gauge
        self._last_maint_value: Optional[str] = None  # for status()
        self._drains_total = 0
        # Outcome of the last COMPLETED drain (satellite of ISSUE 14):
        # "resident exited" used to read as Drained even when the pod
        # crashed pre-checkpoint. With the migration coordinator wired
        # (manager sets .migration), completion classifies into
        # drained_acked (every stamped resident acknowledged a durable
        # checkpoint) vs drained_exited (exit proves nothing) vs
        # reclaimed/cancelled — in status() and
        # elastic_tpu_drains_total{trigger,outcome}.
        self.outcome = ""
        self._acked_pods: List[str] = []
        self.migration = None  # MigrationCoordinator (manager-wired)
        self._reclaimed_pods: List[str] = []
        self._stamped_pods: List[str] = []
        self._annotated_pods: List[Tuple[str, str]] = []  # (ns, name)
        self._last_error: Optional[str] = None
        self._resumed = False
        # DetectionLagTracker (latency.py): the drain is the one loop
        # with a real two-stage story — origin (GCE announcement /
        # preemption notice, stamped by the operator) -> detected
        # (first-trip edge) -> repaired (drain actually started).
        self._lag = lag_tracker

    def _origin_ts(self, kind: str) -> Optional[float]:
        """Injection origin from the operator when it records one (the
        stub does); real GCE metadata carries no origin timestamp."""
        fn = getattr(self._operator, "origin_ts", None)
        if fn is None:
            return None
        try:
            return fn(kind)
        except Exception:  # noqa: BLE001 - accounting never breaks a poll
            return None

    # -- admin seam -----------------------------------------------------------

    def request_drain(self, reason: str = "admin") -> None:
        """Local operator-requested drain (the admin-endpoint seam; the
        node-annotation path is polled from the apiserver)."""
        with self._lock:
            self._drain_requested = True
            self._drain_reason = reason

    def cancel_request(self) -> None:
        with self._lock:
            self._drain_requested = False

    # -- trigger polling ------------------------------------------------------

    def _maintenance_value(self) -> Optional[str]:
        fn = getattr(self._operator, "maintenance_event", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:  # noqa: BLE001 - a broken poll must not wedge
            logger.exception("maintenance poll failed")
            return None

    def _note_maintenance(self, value: Optional[str]) -> bool:
        """Satellite contract: the FIRST sighting of an announced event
        emits TPUMaintenanceImminent and raises the gauge, whether or not
        a drain is already running; clearing drops the gauge. ``None``
        (endpoint unreachable) is UNKNOWABLE: the gauge and the
        fired-once edge keep their last known state — a metadata blip
        must neither tell dashboards the event is over nor re-fire the
        imminent event when the endpoint comes back still announcing."""
        if value is None:
            return False
        announced = value not in ("", "NONE")
        if announced and not self._maint_active:
            logger.warning("host maintenance imminent: %s", value)
            if self._lag is not None:
                self._lag.detected(
                    "drain", TRIGGER_MAINTENANCE, key=self._node,
                    origin_ts=self._origin_ts("maintenance"),
                )
            if self._events is not None:
                from .kube.events import ReasonMaintenanceImminent

                try:
                    self._events.node_event(
                        ReasonMaintenanceImminent,
                        f"GCE announces host maintenance: {value}; "
                        "cordoning and draining this node's TPU workloads",
                        type_="Warning",
                    )
                except Exception:  # noqa: BLE001 - observability only
                    logger.exception("maintenance event emit failed")
        if self._metrics is not None and hasattr(
            self._metrics, "maintenance_imminent"
        ):
            try:
                self._metrics.maintenance_imminent.set(1 if announced else 0)
            except Exception:  # noqa: BLE001
                pass
        self._maint_active = announced
        return announced

    def _poll_trigger(self) -> Optional[str]:
        """The currently-asserted trigger (None = all quiet). Checked
        both to start a drain and to notice mid-drain that the cause
        went away (cancel/re-admit)."""
        maint = self._maintenance_value()
        self._last_maint_value = maint
        maint_announced = self._note_maintenance(maint)
        # Preemption OUTRANKS maintenance: when both assert, the drain
        # must carry the non-cancelable trigger — otherwise a
        # maintenance-labelled drain would cancel when its event clears
        # even though the host is still being preempted.
        # Test-injectable preemption notice (chaos matrix): consuming the
        # notice LATCHES preemption — a real GCE notice never un-rings.
        if faults.check("drain.preempt-notice"):
            setter = getattr(self._operator, "set_preempted", None)
            if setter is not None:
                setter(True)
            return f"{TRIGGER_PREEMPTION}:notice"
        preempted = getattr(self._operator, "preempted", None)
        if preempted is not None:
            try:
                if preempted():
                    if self._lag is not None:
                        # Dedup in the tracker keys on the origin, so
                        # the latched notice re-asserting every poll
                        # records exactly one detection.
                        self._lag.detected(
                            "drain", TRIGGER_PREEMPTION, key=self._node,
                            origin_ts=self._origin_ts("preempted"),
                        )
                    return TRIGGER_PREEMPTION
            except Exception:  # noqa: BLE001
                logger.exception("preemption poll failed")
        if maint_announced:
            return f"{TRIGGER_MAINTENANCE}:{maint}"
        if maint is None and self.trigger.startswith(TRIGGER_MAINTENANCE):
            # The endpoint is UNREACHABLE (not answering "NONE"): with a
            # maintenance drain in flight, unknowable must not read as
            # cleared — a transient metadata failure (cached under the
            # error backoff) would otherwise cancel the drain and
            # re-admit workloads onto a host GCE is about to take away.
            # Same discipline as the apiserver-blip guard below.
            return self.trigger
        with self._lock:
            if self._drain_requested:
                return f"{TRIGGER_OPERATOR}:{getattr(self, '_drain_reason', 'admin')}"
        if self._client is not None and self._node:
            now = time.monotonic()
            if now >= self._node_ann_next_poll or self.node_poll_ttl_s <= 0:
                try:
                    node = self._client.get_node(self._node)
                except Exception:  # noqa: BLE001 - apiserver blip
                    # Unanswerable must not CANCEL an annotation-driven
                    # drain mid-flight; the cached verdict stands and
                    # the next tick retries (no TTL advance on failure
                    # would hammer a dead apiserver — advance it).
                    self._node_ann_next_poll = now + self.node_poll_ttl_s
                    if self.trigger.startswith(
                        TRIGGER_OPERATOR + ":annotation"
                    ):
                        return self.trigger
                else:
                    ann = (
                        ((node or {}).get("metadata") or {})
                        .get("annotations") or {}
                    )
                    self._node_ann_asserted = str(
                        ann.get(AnnotationDrain, "")
                    ).lower() in ("true", "1", "yes", "drain")
                    self._node_ann_next_poll = now + self.node_poll_ttl_s
            if self._node_ann_asserted:
                return f"{TRIGGER_OPERATOR}:annotation"
        return None

    @staticmethod
    def _cancelable(trigger: str) -> bool:
        """Maintenance and operator drains cancel when their cause
        clears; a preemption notice never un-rings."""
        return not trigger.startswith(TRIGGER_PREEMPTION)

    # -- residents ------------------------------------------------------------

    def _spec_plugin(self):
        return getattr(self._plugin, "core", None)

    def _residents(self) -> Optional[List[Tuple[PodContainer, Dict]]]:
        """(owner, records-by-resource) for every container this node
        still holds bindings for — or None when storage cannot answer
        (callers must NOT treat unknowable as zero residents: that
        would complete a drain as Drained while bindings still exist,
        permanently skipping the deadline reclaim)."""
        out: List[Tuple[PodContainer, Dict]] = []
        try:
            items = list(self._storage.items())
        except Exception:  # noqa: BLE001 - storage blip: retry next tick
            logger.exception("drain: resident enumeration failed")
            return None
        for _key, info in items:
            for container, by_resource in info.allocations.items():
                if by_resource:
                    out.append((
                        PodContainer(info.namespace, info.name, container),
                        dict(by_resource),
                    ))
        return out

    # -- journaled transitions ------------------------------------------------

    def _journal(self) -> None:
        """Persist the CURRENT state (called before the transition's
        side effects run, so a crash replays into this state)."""
        self._storage.save_state(_STATE_KEY, {
            "state": self.state,
            "trigger": self.trigger,
            "deadline_ts": self.deadline_ts,
            "stamped_pods": list(self._stamped_pods),
            "annotated_pods": [list(p) for p in self._annotated_pods],
            "reclaimed_pods": list(self._reclaimed_pods),
            "drains_total": self._drains_total,
            "outcome": self.outcome,
            "acked_pods": list(self._acked_pods),
            "phase_ts": dict(self._phase_ts),
            "phases_observed": list(self._phases_observed),
        })

    def _set_state(self, state: str, **timeline_attrs) -> None:
        prev = self.state
        self.state = state
        if self._metrics is not None and hasattr(self._metrics, "drain_state"):
            try:
                self._metrics.drain_state.set(STATE_CODES[state])
            except Exception:  # noqa: BLE001
                pass
        if prev != state and self._timeline is not None:
            from .timeline import KIND_DRAIN_TRANSITION

            self._timeline.emit(
                KIND_DRAIN_TRANSITION,
                **{"state": state, "from": prev,
                   "trigger": self.trigger,
                   "deadline_ts": self.deadline_ts,
                   **timeline_attrs},
            )

    def _observe_phase(self, phase: str, since_anchor: str) -> None:
        """Observe one drain-phase duration exactly once per drain
        (restart-safe: the anchor timestamps and the observed set ride
        the journal). Falls back to the cordon anchor when the signal
        anchor never landed — a drain whose residents could never be
        signalled is exactly the pathological reclaim the histogram
        exists to expose, and must not be the one drain it omits."""
        anchor = self._phase_ts.get(since_anchor)
        if anchor is None:
            anchor = self._phase_ts.get("cordon")
        if anchor is None or phase in self._phases_observed:
            return
        self._phases_observed.append(phase)
        if self._metrics is not None and hasattr(
            self._metrics, "drain_phase_seconds"
        ):
            try:
                self._metrics.drain_phase_seconds.labels(
                    phase=phase
                ).observe(max(0.0, self._clock.time() - anchor))
            except Exception:  # noqa: BLE001
                pass

    def resume(self) -> None:
        """Re-enter the journaled lifecycle after a restart (or a
        supervisor respawn of this loop): re-apply the cordon for any
        non-Active state and let tick() continue from there — the
        deadline is wall-clock, so an agent down past it reclaims on its
        first tick back. Idempotent."""
        try:
            st = self._storage.load_state(_STATE_KEY)
        except Exception:  # noqa: BLE001 - unreadable journal: start clean
            logger.exception("drain: state journal unreadable; starting "
                             "Active")
            st = None
        if not st:
            self._resumed = True
            return
        with self._lock:
            # Trigger/deadline restored BEFORE the state flip so the
            # timeline's resumed transition carries the real context.
            self.trigger = st.get("trigger", "")
            self.deadline_ts = st.get("deadline_ts")
            self._stamped_pods = list(st.get("stamped_pods", []))
            self._annotated_pods = [
                tuple(p) for p in st.get("annotated_pods", [])
            ]
            self._reclaimed_pods = list(st.get("reclaimed_pods", []))
            self._drains_total = int(st.get("drains_total", 0))
            self.outcome = st.get("outcome", "")
            self._acked_pods = list(st.get("acked_pods", []))
            self._phase_ts = dict(st.get("phase_ts", {}))
            self._phases_observed = list(st.get("phases_observed", []))
            self._set_state(st.get("state", ACTIVE), resumed=True)
            resumed_state = self.state
        if resumed_state != ACTIVE:
            logger.warning(
                "drain: resuming journaled state %r (trigger %r, "
                "deadline %s)", resumed_state, self.trigger,
                self.deadline_ts,
            )
            self._plugin.set_cordoned(True)
            if resumed_state in (CORDONED, DRAINING):
                # A crash between the DRAINING journal write and the
                # stamping pass loses nothing: re-signal is idempotent.
                self._signal_residents()
        else:
            self._plugin.set_cordoned(False)
        self._resumed = True

    # -- the lifecycle --------------------------------------------------------

    def _drain_budget_s(self, trigger: str) -> float:
        """The drain/pre-copy budget for this trigger: the configured
        deadline, CLAMPED to the preemption notice window when the host
        itself is going away — a deadline longer than the notice is a
        promise the platform will break mid-checkpoint."""
        if (
            trigger.split(":", 1)[0] == TRIGGER_PREEMPTION
            and self.preemption_notice_s > 0.0
        ):
            return min(self.deadline_s, self.preemption_notice_s)
        return self.deadline_s

    def _start_drain(self, trigger: str) -> None:
        now = self._clock.time()
        budget_s = self._drain_budget_s(trigger)
        with self._lock:
            self.trigger = trigger
            self.deadline_ts = now + budget_s
            self._drains_total += 1
            self._stamped_pods = []
            self._annotated_pods = []
            self._reclaimed_pods = []
            self.outcome = ""
            self._acked_pods = []
            self._phase_ts = {"cordon": now}
            self._phases_observed = []
            self._set_state(CORDONED)
            self._journal()  # BEFORE any side effect
        faults.fire("drain.pre_cordon")
        self._plugin.set_cordoned(True)
        logger.warning(
            "drain: node cordoned (trigger %s, deadline in %.0fs%s)",
            trigger, budget_s,
            (" — clamped to the preemption notice"
             if budget_s < self.deadline_s else ""),
        )
        if self._events is not None:
            from .kube.events import ReasonNodeDraining

            try:
                self._events.node_event(
                    ReasonNodeDraining,
                    f"draining TPU workloads ({trigger}): chips "
                    "unschedulable, residents signalled to checkpoint; "
                    f"bindings reclaimed in {budget_s:.0f}s",
                    type_="Warning",
                )
            except Exception:  # noqa: BLE001
                logger.exception("drain event emit failed")
        with self._lock:
            self._set_state(DRAINING)
            self._journal()
        self._signal_residents()
        if self._lag is not None:
            # Repair = residents signalled: from here the workload knows
            # and acts; the checkpoint handshake is its own story.
            cls = trigger.split(":", 1)[0]
            origin = self._origin_ts(
                "preempted" if cls == TRIGGER_PREEMPTION else "maintenance"
            ) if cls in (TRIGGER_PREEMPTION, TRIGGER_MAINTENANCE) else None
            self._lag.repaired(
                "drain", cls, key=self._node, origin_ts=origin
            )
        faults.fire("drain.post_signal")

    def _signal_residents(self, residents=None) -> None:
        """Stamp the deadline-bearing drain signal into every resident
        container's alloc specs (under the owner's bind stripe — the
        SliceReformer restamp mechanism) and proactively mark resident
        slice members draining at the apiserver. Idempotent and cheap
        to re-run (the restamp skips files whose env already carries
        the signal): resume() and every DRAINING tick repeat it,
        catching pods that bound mid-cordon and specs a drift rebind
        rebuilt without the signal."""
        from .plugins import restamp_owner_env

        plugin = self._spec_plugin()
        if plugin is None:
            return
        if residents is None:
            residents = self._residents()
        if residents is None:
            return  # storage unanswerable: retry next tick
        env = {
            EnvDrain: self.trigger,
            EnvDrainDeadline: str(int(self.deadline_ts or 0)),
        }
        stamped = set(self._stamped_pods)
        annotated = set(self._annotated_pods)
        for owner, records in residents:
            try:
                n = restamp_owner_env(plugin, owner, records, env)
            except Exception:  # noqa: BLE001 - next tick retries
                logger.exception(
                    "drain: signal restamp for %s failed", owner.pod_key
                )
                continue
            if n and owner.pod_key not in stamped:
                stamped.add(owner.pod_key)
                if self._events is not None:
                    from .kube.events import ReasonNodeDraining

                    try:
                        self._events.pod_event(
                            owner.namespace, owner.name, ReasonNodeDraining,
                            f"node draining ({self.trigger}): checkpoint "
                            "now — TPU bindings are reclaimed at "
                            f"{EnvDrainDeadline}={env[EnvDrainDeadline]}",
                            type_="Warning",
                        )
                    except Exception:  # noqa: BLE001
                        pass
            # Proactive slice notification: peers must see this member as
            # lost BEFORE the host dies, so the survivor world forms
            # ahead of the loss instead of after a divergence pass.
            key = (owner.namespace, owner.name)
            if key in annotated or self._client is None:
                continue
            pod = self._sitter.get_pod(owner.namespace, owner.name)
            ann = ((pod or {}).get("metadata") or {}).get("annotations") or {}
            if not ann.get(AnnotationSliceID):
                continue
            try:
                self._client.patch_pod_annotations(
                    owner.namespace, owner.name,
                    {AnnotationDraining: "true"},
                )
                annotated.add(key)
            except Exception:  # noqa: BLE001 - next tick retries
                logger.warning(
                    "drain: draining-annotation patch for %s failed "
                    "(retried next tick)", owner.pod_key,
                )
        with self._lock:
            self._stamped_pods = sorted(stamped)
            self._annotated_pods = sorted(annotated)
            if "signaled" not in self._phase_ts and stamped >= {
                owner.pod_key for owner, _ in residents
            }:
                # Every CURRENT resident carries the signal: the
                # signalled phase anchor (an empty node signals
                # vacuously; later-appearing residents re-stamp without
                # moving the anchor — the phase measures the first full
                # coverage).
                self._phase_ts["signaled"] = self._clock.time()
                self._observe_phase(PHASE_SIGNAL, "cordon")
            self._journal()

    def started_ts(self) -> Optional[float]:
        """Wall-clock anchor of the current drain (the cordon phase
        stamp; journaled, so restart-stable). The migration coordinator
        accepts only acks at/after this as 'answered the signal'."""
        with self._lock:
            return self._phase_ts.get("cordon")

    def _classify_outcome(self) -> Tuple[str, List[str]]:
        """(outcome, acked_pods) for a drain completing as Drained:
        drained_acked only when EVERY stamped resident acknowledged a
        durable checkpoint after the cordon (via the migration
        coordinator — an exit alone proves nothing; the pod may have
        crashed pre-checkpoint, which is exactly what the old 'exited ⇒
        Drained' reading hid from operators)."""
        acked: List[str] = []
        if self.migration is not None:
            started = self._phase_ts.get("cordon")
            acked = [
                k for k in self._stamped_pods
                if self.migration.acked_since(k, started)
            ]
        if not self._stamped_pods:
            # a drain of an empty node neither saved nor lost work —
            # it must not pollute either real outcome
            return "drained_empty", acked
        if set(acked) >= set(self._stamped_pods):
            return "drained_acked", acked
        return "drained_exited", acked

    def _count_outcome(self, outcome: str, trigger: str = "") -> None:
        if self._metrics is not None and hasattr(
            self._metrics, "drains_total"
        ):
            try:
                self._metrics.drains_total.labels(
                    trigger=(trigger or self.trigger).split(":", 1)[0],
                    outcome=outcome,
                ).inc()
            except Exception:  # noqa: BLE001
                pass

    def _cancel_drain(self) -> None:
        """The trigger cleared mid-drain (maintenance event withdrawn,
        drain annotation removed): re-admit the node. Journal FIRST —
        resume() re-derives cordon state from the journaled state, so a
        crash mid-cancel converges to Active + uncordoned. The stamped/
        annotated lists stay in the journal as the PENDING-CLEANUP
        record: signal removal and annotation clearing are retried from
        Active ticks (and across restarts) until they succeed — a
        storage blip or apiserver failure here must not leave residents
        checkpointing toward a deadline that no longer exists, or a
        live slice member counted lost forever."""
        logger.warning("drain: trigger %r cleared; re-admitting node",
                       self.trigger)
        cancelled_trigger = self.trigger
        stamped = list(self._stamped_pods)
        with self._lock:
            was_completed = self.state in (DRAINED, RECLAIMED)
            self._set_state(ACTIVE)
            self.trigger = ""
            self.deadline_ts = None
            if not was_completed:
                # a drain that already completed keeps its real outcome;
                # only an in-flight drain cancels
                self.outcome = "cancelled"
            self._journal()  # stamped/annotated kept: cleanup is owed
        if not was_completed:
            self._count_outcome("cancelled", trigger=cancelled_trigger)
        self._plugin.set_cordoned(False)
        self._finish_cancel_cleanup()
        if self._events is not None:
            from .kube.events import ReasonDrainCancelled

            try:
                self._events.node_event(
                    ReasonDrainCancelled,
                    f"drain cancelled ({cancelled_trigger} cleared): "
                    f"chips re-schedulable, drain signal removed from "
                    f"{len(stamped)} resident pod(s)",
                )
            except Exception:  # noqa: BLE001
                pass

    def _finish_cancel_cleanup(self) -> None:
        """Retryable post-cancel cleanup: strip the drain env from every
        resident spec and clear the draining annotations, dropping each
        item from the journaled pending lists only once it provably
        succeeded (a 404 on the patch = the pod is gone = done)."""
        from .plugins import restamp_owner_env

        if self._stamped_pods:
            plugin = self._spec_plugin()
            residents = self._residents() if plugin is not None else []
            if residents is not None:
                cleaned = True
                for owner, records in residents:
                    try:
                        restamp_owner_env(
                            plugin, owner, records, {},
                            remove_keys=(EnvDrain, EnvDrainDeadline),
                        )
                    except Exception:  # noqa: BLE001 - retried next tick
                        cleaned = False
                        logger.exception(
                            "drain: signal removal for %s failed "
                            "(retried)", owner.pod_key,
                        )
                if cleaned:
                    with self._lock:
                        self._stamped_pods = []
                        self._journal()
        # With no client the annotation debt stays journaled untouched —
        # it is owed for whenever a client exists again (an agent can
        # restart into a working kubeconfig).
        if self._annotated_pods and self._client is not None:
            remaining = []
            for ns, name in self._annotated_pods:
                try:
                    self._client.patch_pod_annotations(
                        ns, name, {AnnotationDraining: None}
                    )
                except Exception:  # noqa: BLE001 - retried next tick
                    logger.warning(
                        "drain: draining-annotation clear for %s/%s "
                        "failed (retried)", ns, name,
                    )
                    remaining.append((ns, name))
            with self._lock:
                self._annotated_pods = sorted(remaining)
                self._journal()

    def _reclaim(self) -> None:
        """Deadline expired: reclaim every remaining binding through the
        reconciler's repair machinery (counted under reclaimed_pod),
        leaving zero orphan artifacts. The pods themselves may still
        exist at the apiserver — eviction is the node controller's job —
        so the reconciler suppresses unbound-assignment replays for this
        node while reclaimed (suppress_replays)."""
        faults.fire("drain.pre_reclaim")
        residents = self._residents()
        if residents is None:
            return  # storage unanswerable: reclaim retries next tick
        keys = sorted({owner.pod_key for owner, _ in residents})
        report = {}
        if keys:
            logger.warning(
                "drain: deadline expired with %d resident pod(s); "
                "reclaiming bindings: %s", len(keys), keys,
            )
            report = self._reconciler.drain_reclaim(keys)
            if self._metrics is not None and hasattr(
                self._metrics, "drain_reclaimed_pods"
            ):
                try:
                    self._metrics.drain_reclaimed_pods.inc(
                        report.get("reclaimed_pods", 0)
                    )
                except Exception:  # noqa: BLE001
                    pass
        # Only pods whose records are actually GONE count as reclaimed;
        # a pod whose teardown failed stays a resident and the state
        # stays DRAINING, so the past-deadline tick retries it — no
        # RECLAIMED/DRAINING flap, no per-cycle NodeDrained event spam,
        # and status() never claims a still-live binding was reclaimed.
        after = self._residents()
        remaining = (
            {owner.pod_key for owner, _ in after}
            if after is not None else set(keys)
        )
        done = [k for k in keys if k not in remaining]
        with self._lock:
            # union: a straggler bind reclaimed after re-entering
            # draining must not erase the first wave from the record
            self._reclaimed_pods = sorted(
                set(self._reclaimed_pods) | set(done)
            )
            if remaining:
                self._journal()  # progress recorded; retry next tick
            else:
                _, acked = self._classify_outcome()
                self.outcome = "reclaimed"
                self._acked_pods = sorted(acked)
                prev = self.state
                self._set_state(RECLAIMED, reclaimed_pods=sorted(done))
                self._observe_phase(PHASE_RECLAIMED, "signaled")
                self._journal()
                if prev != RECLAIMED:
                    self._count_outcome("reclaimed")
        if remaining:
            logger.warning(
                "drain: %d resident(s) survived the reclaim (%s); "
                "retried next tick", len(remaining), sorted(remaining),
            )
            return
        if self._events is not None:
            from .kube.events import ReasonNodeDrained

            try:
                self._events.node_event(
                    ReasonNodeDrained,
                    "drain deadline expired: reclaimed TPU bindings of "
                    f"{len(keys)} resident pod(s) "
                    f"({report.get('reclaimed_pods', 0)} records, "
                    f"{report.get('sweep_failures', 0)} sweep failures)",
                    type_="Warning",
                )
            except Exception:  # noqa: BLE001
                pass

    def _finish_drained(self) -> None:
        with self._lock:
            outcome, acked = self._classify_outcome()
            self.outcome = outcome
            self._acked_pods = sorted(acked)
            prev = self.state
            self._set_state(
                DRAINED, outcome=outcome, acked_pods=sorted(acked)
            )
            self._observe_phase(PHASE_DRAINED, "signaled")
            self._journal()
        if prev != DRAINED:
            self._count_outcome(outcome)
        logger.info(
            "drain: all residents gone before the deadline (%s: %d/%d "
            "acknowledged a durable checkpoint)", outcome, len(acked),
            len(self._stamped_pods),
        )
        if self._events is not None:
            from .kube.events import ReasonNodeDrained

            if outcome == "drained_acked":
                detail = ("every resident's checkpoint was verified "
                          "durable before its bindings went")
            elif outcome == "drained_empty":
                detail = "no resident workloads were bound"
            else:
                detail = (
                    f"residents exited but only {len(acked)}/"
                    f"{len(self._stamped_pods)} acknowledged a "
                    "checkpoint — unverified exits may have lost work"
                )
            try:
                self._events.node_event(
                    ReasonNodeDrained,
                    f"drain complete ({self.trigger}, {outcome}): "
                    f"{detail}; node remains cordoned until the "
                    "trigger clears",
                )
            except Exception:  # noqa: BLE001
                pass

    # -- reconciler integration -----------------------------------------------

    def suppress_replays(self) -> bool:
        """True while reclaimed bindings must STAY reclaimed: kubelet's
        pod-resources view still lists the drained assignments (the pods
        may not be evicted yet), and without this the reconciler's
        unbound-assignment replay would faithfully re-bind everything
        the drain just tore down."""
        with self._lock:
            if self.state == RECLAIMED:
                return True
            return (
                self.state == DRAINING
                and self.deadline_ts is not None
                and self._clock.time() >= self.deadline_ts
            )

    # -- the tick -------------------------------------------------------------

    def tick(self) -> str:
        """One state-machine step; returns the (possibly new) state."""
        faults.fire("drain.tick")
        trigger = self._poll_trigger()
        state = self.state
        if (
            state != ACTIVE
            and trigger is not None
            and not self._cancelable(trigger)
            and self._cancelable(self.trigger)
        ):
            # A preemption notice arriving MID-drain upgrades the
            # lifecycle to non-cancelable: "preemption never un-rings"
            # must hold even when maintenance rang first — otherwise the
            # maintenance event clearing (or its endpoint blipping in
            # just the wrong tick) would re-admit workloads onto a host
            # GCE is about to preempt.
            logger.warning(
                "drain: trigger upgraded %r -> %r (non-cancelable)",
                self.trigger, trigger,
            )
            upgraded_from = self.trigger
            # The upgraded drain inherits the SHORTER horizon: the
            # preemption notice started ticking NOW, so the existing
            # (maintenance-sized) deadline is clamped to the notice
            # window — never extended.
            clamp_ts = self._clock.time() + self._drain_budget_s(trigger)
            with self._lock:
                self.trigger = trigger
                if self.deadline_ts is None or clamp_ts < self.deadline_ts:
                    self.deadline_ts = clamp_ts
                self._journal()
            if self._timeline is not None:
                from .timeline import KIND_DRAIN_TRANSITION

                self._timeline.emit(
                    KIND_DRAIN_TRANSITION, state=self.state,
                    trigger=trigger, upgraded_from=upgraded_from,
                    deadline_ts=self.deadline_ts,
                )
        if state == ACTIVE:
            if trigger is not None:
                self._start_drain(trigger)
            elif self._stamped_pods or self._annotated_pods:
                # cleanup owed by a cancelled drain (journaled pending
                # lists): retry until every spec and annotation is clean
                self._finish_cancel_cleanup()
        elif state in (CORDONED, DRAINING):
            if trigger is None and self._cancelable(self.trigger):
                self._cancel_drain()
            else:
                if state == CORDONED:
                    # A crash landed between the CORDONED and DRAINING
                    # journal writes: finish the entry sequence.
                    with self._lock:
                        self._set_state(DRAINING)
                        self._journal()
                # ONE storage snapshot per tick; None = unknowable, and
                # unknowable must never complete the drain as Drained
                # (that would skip the deadline reclaim forever).
                residents = self._residents()
                self._signal_residents(residents)
                if residents is None:
                    pass  # storage blip: retry next tick
                elif not residents:
                    self._finish_drained()
                elif (
                    self.deadline_ts is not None
                    and self._clock.time() >= self.deadline_ts
                ):
                    self._reclaim()
        elif state in (DRAINED, RECLAIMED):
            if trigger is None and self._cancelable(self.trigger):
                # The cause cleared after the drain completed (host
                # migrated back, annotation removed): re-admit.
                self._cancel_drain()
            else:
                # A PreStart bind can race the final empty-residents
                # snapshot (kubelet completed Allocate pre-cordon, the
                # bind committed just after). A completed drain must
                # keep checking: such a straggler is re-signalled and
                # falls back under the deadline reclaim instead of
                # surviving unstranded-but-unsignalled until the host
                # dies.
                residents = self._residents()
                if residents:
                    logger.warning(
                        "drain: %d resident(s) appeared after the drain "
                        "completed; re-entering draining", len(residents),
                    )
                    with self._lock:
                        self._set_state(DRAINING)
                        self._journal()
                    self._signal_residents(residents)
        return self.state

    def run(self, stop: threading.Event) -> None:
        """Supervised loop (DEGRADED): resume the journaled lifecycle,
        then tick at a jittered period (0.75x-1.25x, so a fleet never
        polls the metadata server in lockstep)."""
        self.resume()
        consecutive_failures = 0
        while True:
            delay = self.period_s * (0.75 + 0.5 * self._rng.random())
            sub = self._event_sub
            with self._lock:
                state, deadline_ts = self.state, self.deadline_ts
            if (
                sub is not None and state != ACTIVE
                and self._bus.healthy()
            ):
                # Mid-lifecycle the resident set drives the state
                # machine, and resident changes arrive as store events
                # — the sweep can stretch. Never past the reclaim
                # deadline though: the deadline is a contract, not a
                # divergence events could flag.
                delay *= self.event_safety_net_factor
                if deadline_ts is not None:
                    to_deadline = deadline_ts - self._clock.time()
                    delay = max(0.05, min(delay, to_deadline + 0.05))
            if sub is None:
                if stop.wait(delay):
                    return
            else:
                end = time.monotonic() + delay
                while True:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        break  # periodic tick
                    trigger = sub.wait_trigger(stop, remaining)
                    if trigger == "stop":
                        return
                    if trigger == "poll":
                        break
                    if state == ACTIVE:
                        # No lifecycle in progress: pod/bind churn is
                        # irrelevant here and must not turn the idle
                        # metadata poll into an event-rate hammer —
                        # drain the burst and keep waiting out the
                        # SAME period (events never starve the tick).
                        sub.drain()
                        if stop.wait(0.05):
                            return
                        continue
                    if stop.wait(0.01):  # coalesce the burst
                        return
                    sub.drain()
                    self.event_ticks_total += 1
                    break
            try:
                self.tick()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001
                # One-off failures (apiserver blip, sqlite lock) are
                # absorbed; persistent ones escalate to the supervisor —
                # same discipline as the reconciler loop.
                consecutive_failures += 1
                with self._lock:
                    self._last_error = f"{type(e).__name__}: {e}"
                if consecutive_failures >= 3:
                    raise
                logger.exception(
                    "drain tick failed (%d consecutive; escalating to "
                    "the supervisor at 3)", consecutive_failures,
                )

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        """The ``drain`` block of /debug/allocations and the doctor
        bundle: state, trigger, deadline, and which pods were signalled
        / reclaimed — drain-stuck triage must work from a bundle alone."""
        # The drain loop's last polled value, NOT a live metadata fetch:
        # /debug/allocations and the doctor bundle must never pay the
        # metadata timeout (or race the drain thread through the
        # operator's unsynchronized poll cache) from a handler thread.
        maint = self._last_maint_value
        with self._lock:
            deadline_in = (
                round(self.deadline_ts - self._clock.time(), 3)
                if self.deadline_ts is not None else None
            )
            return {
                "state": self.state,
                "trigger": self.trigger,
                "deadline_ts": self.deadline_ts,
                "deadline_in_s": deadline_in,
                "deadline_s": self.deadline_s,
                "preemption_notice_s": self.preemption_notice_s,
                "drains_total": self._drains_total,
                "outcome": self.outcome,
                "acked_pods": list(self._acked_pods),
                "stamped_pods": list(self._stamped_pods),
                "annotated_pods": [
                    f"{ns}/{name}" for ns, name in self._annotated_pods
                ],
                "reclaimed_pods": list(self._reclaimed_pods),
                "maintenance_event": maint,
                "last_error": self._last_error,
            }
