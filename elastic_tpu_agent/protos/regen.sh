#!/bin/sh
# Regenerate protobuf message modules into elastic_tpu_agent/gen/.
# Only messages are generated (protoc --python_out); gRPC service stubs are
# hand-wired in elastic_tpu_agent/rpc.py against grpcio's generic API, so
# grpcio-tools is not required in the image.
set -e
cd "$(dirname "$0")"
protoc --python_out=../gen deviceplugin.proto podresources.proto \
    podresources_v1.proto ttrpc.proto nri.proto
echo "generated: ../gen/deviceplugin_pb2.py ../gen/podresources_pb2.py" \
     "../gen/podresources_v1_pb2.py ../gen/ttrpc_pb2.py ../gen/nri_pb2.py"
