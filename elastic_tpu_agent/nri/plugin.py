"""The NRI plugin: containerd-native device injection for elastic TPU pods.

This is the containerd/GKE counterpart of the OCI hook chain
(native/hook.cc + native/toolkit.cc): containerd does not read OCI
hooks.d, so on containerd nodes the agent registers as an external NRI
plugin on ``/var/run/nri/nri.sock`` and answers CreateContainer events
with a ContainerAdjustment carrying exactly what the toolkit would have
injected — dense ``/dev/accel<p>`` device nodes (major:minor resolved by
stat of the allocation spec's host device paths), the spec's env
(TPU_VISIBLE_CHIPS, HBM quota, slice topology), and bind mounts for the
spec file and optionally ``libtpu.so``.

Reference parity: the reference activates injection by *replacing the
host's nvidia prestart hook binary* (``/root/reference/tools/install.sh:2-5``,
exec'd from ``cmd/elastic-gpu-hook/main.go:224-257``). There is no TPU
binary to swap and GKE's containerd ignores hooks.d, so speaking NRI is
the TPU-native equivalent of that activation mechanism.

Protocol (interop with github.com/containerd/nri): the plugin dials the
runtime's socket, multiplexes two ttrpc connections over it (conn 1:
runtime calls the Plugin service on us; conn 2: we call the Runtime
service), registers itself, then the runtime drives
Configure -> Synchronize -> per-event RPCs. Transport lives in
``nri/mux.py`` + ``nri/ttrpc.py``; message shapes in ``protos/nri.proto``.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
from typing import Callable, Dict, List, Optional

from ..common import EnvAllocationHash, EnvAllocationHashCompat
from ..gen import nri_pb2 as pb
from . import mux as nri_mux
from . import ttrpc

logger = logging.getLogger(__name__)

DEFAULT_NRI_SOCKET = "/var/run/nri/nri.sock"

PLUGIN_SERVICE = "nri.pb.v1alpha1.Plugin"
RUNTIME_SERVICE = "nri.pb.v1alpha1.Runtime"

# ConfigureResponse.events bit for an Event enum value (upstream pkg/api:
# bit (event-1)).
def event_mask(*events: int) -> int:
    mask = 0
    for e in events:
        mask |= 1 << (e - 1)
    return mask


# Where the spec file and libtpu land inside the container. The spec mount
# mirrors toolkit.cc step 3 (it *copies* the spec into the rootfs; NRI can
# only mount, same outcome for in-container tooling).
SPEC_MOUNT_DEST = "/run/elastic-tpu/spec.json"
DEFAULT_LIBTPU_DEST = "/lib/libtpu.so"

_BIND_OPTS = ["bind", "ro", "nosuid", "nodev"]


def hash_from_env(env: List[str]) -> Optional[str]:
    """Extract the allocation hash from container env (``TPU=<hash>`` with
    ``GPU=`` accepted for compatibility — same contract as the OCI hook,
    native/hook.cc HashFromEnv)."""
    for key in (EnvAllocationHash + "=", EnvAllocationHashCompat + "="):
        for entry in env:
            if entry.startswith(key):
                value = entry[len(key):]
                if value:
                    return value
    return None


def adjustment_from_spec(
    spec: Dict,
    stat_fn: Callable = os.stat,
    dev_root: str = "/dev",
    libtpu_path: str = "",
    libtpu_dest: str = DEFAULT_LIBTPU_DEST,
    spec_path: str = "",
) -> pb.ContainerAdjustment:
    """Build the ContainerAdjustment equivalent to a toolkit.cc injection.

    - one chardev per spec chip, densely renumbered ``/dev/accel<p>``
      (toolkit.cc step 2), major:minor from stat of the host node;
    - the spec's env verbatim (toolkit.cc step 3's env file, but injected
      as real process env — strictly better);
    - a read-only bind mount of the spec file (step 3's rootfs copy);
    - optionally a read-only bind mount of libtpu.so (step 4's copy).

    ``dev_root`` maps the spec's host paths into this process's mount view
    (the agent sees the host's /dev at /host/dev in the DaemonSet).
    """
    adjust = pb.ContainerAdjustment()
    adjust.annotations["elastic-tpu.elasticgpu.io/hash"] = spec.get("hash", "")
    for p, host_path in enumerate(spec.get("device_paths", [])):
        view = host_path
        if dev_root != "/dev" and host_path.startswith("/dev/"):
            view = os.path.join(dev_root, host_path[len("/dev/"):])
        st = stat_fn(view)
        rdev = getattr(st, "st_rdev", 0)
        adjust.linux.devices.append(
            pb.LinuxDevice(
                path=f"/dev/accel{p}",
                type="c",
                major=os.major(rdev),
                minor=os.minor(rdev),
                file_mode=pb.OptionalFileMode(value=0o660),
            )
        )
    for key in sorted(spec.get("env", {})):
        adjust.env.append(pb.KeyValue(key=key, value=spec["env"][key]))
    if spec_path:
        adjust.mounts.append(
            pb.Mount(
                destination=SPEC_MOUNT_DEST,
                type="bind",
                source=spec_path,
                options=list(_BIND_OPTS),
            )
        )
    if libtpu_path:
        adjust.mounts.append(
            pb.Mount(
                destination=libtpu_dest,
                type="bind",
                source=libtpu_path,
                options=list(_BIND_OPTS),
            )
        )
    return adjust


class NRIPlugin:
    """External NRI plugin: dial, register, serve CreateContainer.

    Runs the whole lifetime in ``run(stop)`` with reconnect + backoff —
    containerd restarts must not strand the injection path (the same
    resilience the device-plugin servers get from their fsnotify
    re-register loop, plugins/base.py).
    """

    RECONNECT_MIN_S = 1.0
    RECONNECT_MAX_S = 30.0

    def __init__(
        self,
        socket_path: str = DEFAULT_NRI_SOCKET,
        alloc_spec_dir: str = "/host/var/lib/elastic-tpu/alloc",
        host_alloc_dir: str = "",
        plugin_name: str = "elastic-tpu",
        plugin_idx: str = "10",
        dev_root: str = "/dev",
        libtpu_path: str = "",
        libtpu_dest: str = DEFAULT_LIBTPU_DEST,
        stat_fn: Callable = os.stat,
        metrics=None,
    ) -> None:
        self._socket_path = socket_path
        self._alloc_dir = alloc_spec_dir
        # Specs are READ through the agent's mount view (alloc_spec_dir,
        # typically /host/var/lib/...), but the adjustment's Mount.source
        # is resolved by runc in the HOST mount namespace — it must be the
        # host-side path or every TPU container create fails on a
        # nonexistent bind source.
        self._host_alloc_dir = host_alloc_dir or alloc_spec_dir
        self._name = plugin_name
        self._idx = plugin_idx
        self._dev_root = dev_root
        self._libtpu = libtpu_path
        self._libtpu_dest = libtpu_dest
        self._stat = stat_fn
        self._metrics = metrics
        self._mux: Optional[nri_mux.Mux] = None
        self._server: Optional[ttrpc.Server] = None
        self._runtime: Optional[ttrpc.Client] = None
        self._mux_lock = threading.Lock()
        self._stopping = False
        # container id -> set of chip indexes injected at create time;
        # feeds evict_for_chips when a chip dies. Pruned on removal and
        # REBUILT from the runtime's Synchronize snapshot on every
        # (re)connect, so containers created under a previous session —
        # and removals missed while disconnected — are both covered.
        self._bound_chips: Dict[str, set] = {}
        # chip -> health reason, sticky until clear_failed_chips(); lets
        # evictions that failed (runtime down, RPC error) retry after the
        # next Synchronize instead of being dropped on the transition
        self._failed_chips: Dict[int, str] = {}
        self._evicted: set = set()  # container ids already evicted
        self._bound_lock = threading.Lock()
        # serializes whole flush passes: concurrent flushes (health hook
        # racing the reconnect-retry thread) would both snapshot victims
        # before either records _evicted and double-evict
        self._flush_lock = threading.Lock()
        # observability for tests / metrics
        self.configured = threading.Event()
        self.synchronized = threading.Event()
        self.injected_count = 0

    # -- spec loading ---------------------------------------------------------

    def _spec_path(self, alloc_hash: str) -> str:
        return os.path.join(self._alloc_dir, f"{alloc_hash}.json")

    def _load_spec(self, alloc_hash: str) -> Dict:
        # basename() defuses a hostile hash like "../x" before it becomes
        # a path component.
        path = self._spec_path(os.path.basename(alloc_hash))
        with open(path) as f:
            return json.load(f)

    # -- Plugin service handlers ----------------------------------------------

    def _on_configure(self, req: pb.ConfigureRequest) -> pb.ConfigureResponse:
        logger.info(
            "NRI: configured by %s %s", req.runtime_name, req.runtime_version
        )
        self.configured.set()
        return pb.ConfigureResponse(
            events=event_mask(pb.CREATE_CONTAINER, pb.REMOVE_CONTAINER)
        )

    def _on_synchronize(
        self, req: pb.SynchronizeRequest
    ) -> pb.SynchronizeResponse:
        # Existing containers were created before this session; their
        # device nodes were injected then (adjustments only exist at
        # create time). REBUILD the eviction-tracking map from this
        # authoritative snapshot: containers from a previous agent/NRI
        # session stay evictable, and removals missed while disconnected
        # stop lingering.
        bound: Dict[str, set] = {}
        for c in req.containers:
            alloc_hash = hash_from_env(list(c.env))
            if alloc_hash is None:
                continue
            try:
                spec = self._load_spec(alloc_hash)
            except (OSError, ValueError):
                logger.warning(
                    "NRI: pre-existing TPU container %s/%s has no alloc "
                    "spec (hash %s)", c.pod_sandbox_id[:8], c.name,
                    alloc_hash,
                )
                continue
            bound[c.id] = set(spec.get("chip_indexes", []))
        with self._bound_lock:
            self._bound_chips = bound
            self._evicted &= set(bound)
            retry_needed = bool(self._failed_chips)
        if bound:
            logger.info(
                "NRI: tracking %d pre-existing TPU container(s)", len(bound)
            )
        self.synchronized.set()
        if retry_needed:
            # Evictions pending from before the reconnect: retry off the
            # serve thread (the runtime is still waiting for THIS
            # response; calling it inline could deadlock the handshake).
            threading.Thread(
                target=self._flush_evictions, daemon=True,
                name="nri-evict-retry",
            ).start()
        return pb.SynchronizeResponse(more=req.more)

    def _on_create_container(
        self, req: pb.CreateContainerRequest
    ) -> pb.CreateContainerResponse:
        alloc_hash = hash_from_env(list(req.container.env))
        if alloc_hash is None:
            return pb.CreateContainerResponse()  # not ours: no adjustment
        try:
            spec = self._load_spec(alloc_hash)
        except (OSError, ValueError) as e:
            # Fail the create rather than let a TPU pod start deviceless —
            # kubelet will retry and the error names the missing spec
            # (the OCI toolkit fails the prestart the same way).
            raise RuntimeError(
                f"allocation spec for hash {alloc_hash!r} unreadable: {e}"
            )
        adjust = adjustment_from_spec(
            spec,
            stat_fn=self._stat,
            dev_root=self._dev_root,
            libtpu_path=self._libtpu,
            libtpu_dest=self._libtpu_dest,
            spec_path=os.path.join(
                self._host_alloc_dir,
                f"{os.path.basename(alloc_hash)}.json",
            ),
        )
        self.injected_count += 1
        with self._bound_lock:
            self._bound_chips[req.container.id] = set(
                spec.get("chip_indexes", [])
            )
            born_dead = bool(
                set(spec.get("chip_indexes", [])) & set(self._failed_chips)
            )
        if born_dead:
            # The chip failed between Allocate and CreateContainer (the
            # spec predates the failure): evict immediately, off the
            # serve thread — the runtime is still waiting for THIS
            # response.
            threading.Thread(
                target=self._flush_evictions, daemon=True,
                name="nri-evict-born-dead",
            ).start()
        if self._metrics is not None and hasattr(self._metrics, "nri_injections"):
            self._metrics.nri_injections.inc()
        logger.info(
            "NRI: injected %d device(s) for %s/%s (hash %s)",
            len(adjust.linux.devices), req.pod.namespace, req.pod.name,
            alloc_hash,
        )
        return pb.CreateContainerResponse(adjust=adjust)

    def _on_shutdown(self, req: pb.Empty) -> pb.Empty:  # noqa: ARG002
        logger.info("NRI: runtime requested shutdown")
        # End the session only after the response frame is written; run()
        # decides whether to reconnect.
        if self._server is not None:
            self._server.stop_after_reply()
        return pb.Empty()

    def _on_noop_update(
        self, req: pb.UpdateContainerRequest  # noqa: ARG002
    ) -> pb.UpdateContainerResponse:
        return pb.UpdateContainerResponse()

    def _on_noop_stop(
        self, req: pb.StopContainerRequest  # noqa: ARG002
    ) -> pb.StopContainerResponse:
        return pb.StopContainerResponse()

    def _on_state_change(self, req: pb.StateChangeEvent) -> pb.Empty:
        if req.event == pb.REMOVE_CONTAINER and req.container.id:
            with self._bound_lock:
                self._bound_chips.pop(req.container.id, None)
                self._evicted.discard(req.container.id)  # no leak
        return pb.Empty()

    # -- chip-failure eviction ------------------------------------------------

    EVICT_RPC_TIMEOUT_S = 10.0

    def evict_for_chips(self, chips: set, reasons=None) -> int:
        """Record ``chips`` as failed and evict every tracked container
        whose injected devices include one of them (kubelet then
        restarts the pod, landing it on healthy chips — the dead chip is
        no longer advertised). Returns the number of evictions
        containerd ACCEPTED in this call; containers that could not be
        evicted now (no live session, RPC failure) retry automatically
        after the next Synchronize because the failed-chip set is sticky
        until clear_failed_chips().

        Rationale: a container bound to a dead chip holds a device node
        that will never work again — the bind is immutable post-create,
        so eviction is the only recovery containerd offers. Gated behind
        the agent's --nri-evict-on-chip-failure flag (policy, default
        off)."""
        reasons = reasons or {}
        with self._bound_lock:
            for c in chips:
                self._failed_chips[c] = reasons.get(c, "chip unhealthy")
        return self._flush_evictions()

    def clear_failed_chips(self, chips: set) -> None:
        """Chip recovered: stop evicting (new) containers bound to it."""
        with self._bound_lock:
            for c in chips:
                self._failed_chips.pop(c, None)

    def _flush_evictions(self) -> int:
        with self._flush_lock:
            return self._flush_evictions_locked()

    def _flush_evictions_locked(self) -> int:
        with self._bound_lock:
            failed_chips = dict(self._failed_chips)
            victims = {
                cid: sorted(set(bound) & set(failed_chips))
                for cid, bound in self._bound_chips.items()
                if set(bound) & set(failed_chips)
                and cid not in self._evicted
            }
        if not victims:
            return 0
        with self._mux_lock:
            client = self._runtime
        if client is None:
            logger.warning(
                "NRI: no live session; %d eviction(s) pending until "
                "reconnect", len(victims),
            )
            return 0
        evictions = [
            pb.ContainerEviction(
                container_id=cid,
                reason=(
                    "TPU chip(s) "
                    + ",".join(
                        f"{c} ({failed_chips[c]})" for c in hit
                    )
                    + " failed; device is unrecoverable in-place"
                ),
            )
            for cid, hit in sorted(victims.items())
        ]
        try:
            resp = client.call(
                RUNTIME_SERVICE, "UpdateContainers",
                pb.UpdateContainersRequest(evict=evictions),
                pb.UpdateContainersResponse,
                timeout_s=self.EVICT_RPC_TIMEOUT_S,
            )
        except (
            ttrpc.TtrpcError, ttrpc.ChannelClosed, ttrpc.ChannelTimeout
        ) as e:
            logger.warning(
                "NRI: eviction request failed (%s); will retry after the "
                "next session sync", e,
            )
            return 0
        failed_ids = {u.container_id for u in resp.failed}
        ok = 0
        with self._bound_lock:
            for ev in evictions:
                if ev.container_id in failed_ids:
                    logger.warning(
                        "NRI: eviction of %s failed", ev.container_id
                    )
                else:
                    self._evicted.add(ev.container_id)
                    ok += 1
                    logger.info(
                        "NRI: evicted %s (%s)", ev.container_id, ev.reason
                    )
        return ok

    # -- connection lifecycle -------------------------------------------------

    def _register_handlers(self, server: ttrpc.Server) -> None:
        server.register(
            PLUGIN_SERVICE, "Configure", pb.ConfigureRequest,
            self._on_configure,
        )
        server.register(
            PLUGIN_SERVICE, "Synchronize", pb.SynchronizeRequest,
            self._on_synchronize,
        )
        server.register(
            PLUGIN_SERVICE, "CreateContainer", pb.CreateContainerRequest,
            self._on_create_container,
        )
        server.register(
            PLUGIN_SERVICE, "Shutdown", pb.Empty, self._on_shutdown,
        )
        server.register(
            PLUGIN_SERVICE, "UpdateContainer", pb.UpdateContainerRequest,
            self._on_noop_update,
        )
        server.register(
            PLUGIN_SERVICE, "StopContainer", pb.StopContainerRequest,
            self._on_noop_stop,
        )
        server.register(
            PLUGIN_SERVICE, "StateChange", pb.StateChangeEvent,
            self._on_state_change,
        )

    def serve_once(self) -> None:
        """One connection lifetime: dial, register, serve until close."""
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.connect(self._socket_path)
        mux = nri_mux.Mux(sock)
        # Open both logical conns BEFORE the reader starts — frames for
        # unopened conns are dropped (mux.py).
        plugin_ch = mux.open(nri_mux.PLUGIN_SERVICE_CONN)
        runtime_ch = mux.open(nri_mux.RUNTIME_SERVICE_CONN)
        with self._mux_lock:
            self._mux = mux
        mux.start()
        server = ttrpc.Server(plugin_ch)
        self._server = server
        self._register_handlers(server)
        serve_thread = threading.Thread(
            target=server.serve_forever, name="nri-plugin-serve", daemon=True
        )
        serve_thread.start()
        try:
            client = ttrpc.Client(runtime_ch)
            client.call(
                RUNTIME_SERVICE, "RegisterPlugin",
                pb.RegisterPluginRequest(
                    plugin_name=self._name, plugin_idx=self._idx
                ),
                pb.Empty,
            )
            logger.info(
                "NRI: registered as %s-%s on %s",
                self._idx, self._name, self._socket_path,
            )
            with self._mux_lock:
                self._runtime = client  # live session: evictions possible
            serve_thread.join()  # session lifetime
        except ttrpc.ChannelClosed:
            pass  # runtime went away mid-handshake; run() retries
        finally:
            # Every exit — including a registration rejection (TtrpcError)
            # propagating to run()'s retry loop — must close the mux, or
            # each reconnect attempt would leak the socket plus the reader
            # and serve threads left blocked on it.
            mux.close()  # unblocks serve_forever via ChannelClosed
            serve_thread.join(timeout=5.0)
            with self._mux_lock:
                self._mux = None
                self._server = None
                self._runtime = None

    def _close_mux(self) -> None:
        with self._mux_lock:
            if self._mux is not None:
                self._mux.close()

    def run(self, stop: threading.Event) -> None:
        """Serve with reconnect + exponential backoff until ``stop``."""
        backoff = self.RECONNECT_MIN_S
        while not stop.is_set() and not self._stopping:
            try:
                self.serve_once()
                backoff = self.RECONNECT_MIN_S  # had a real session
            except OSError as e:
                logger.warning(
                    "NRI: connect to %s failed: %s (retry in %.0fs)",
                    self._socket_path, e, backoff,
                )
            except Exception:  # noqa: BLE001 - never kill the agent
                logger.exception("NRI: session failed")
            if stop.wait(backoff):
                return
            backoff = min(backoff * 2, self.RECONNECT_MAX_S)

    def start(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(
            target=self.run, args=(stop,), daemon=True, name="nri-plugin"
        )
        t.start()
        return t

    def stop(self) -> None:
        self._stopping = True
        self._close_mux()
