"""containerd NRI (Node Resource Interface) plugin.

This is the containerd/GKE activation path for the injection chain
(docs/operations.md "containerd / GKE activation"): containerd does not
read OCI hooks.d, so instead of the hook binary the agent speaks NRI —
it subscribes to CreateContainer events and returns a ContainerAdjustment
carrying the devices, env, and mounts recorded in the allocation spec.

Reference parity: the reference activates its injection by *replacing the
host's nvidia hook binary* (tools/install.sh:2-5); there is no TPU binary
to replace, and GKE's containerd ignores hooks.d, so NRI is the
TPU-native equivalent mechanism.
"""

from .plugin import NRIPlugin, adjustment_from_spec  # noqa: F401
