"""Minimal ttrpc (containerd's lightweight RPC) — the unary subset NRI uses.

Wire format (interop contract with github.com/containerd/ttrpc, which is
what a real containerd speaks on the NRI socket):

  10-byte message header, big-endian:
      uint32  payload length
      uint32  stream id        (client streams are odd, starting at 1)
      uint8   type             (1 = request, 2 = response)
      uint8   flags            (0 for unary)
  followed by ``length`` bytes of payload — a serialized ``ttrpc.Request``
  or ``ttrpc.Response`` message (protos/ttrpc.proto).

Both ends of an NRI connection are unary-only, so streaming message types
are not implemented; an incoming frame with an unknown type is answered
with a failed Response (the containerd server does the same for protocol
errors it can attribute to a stream).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from ..gen import ttrpc_pb2

logger = logging.getLogger(__name__)

_HEADER = struct.Struct(">IIBB")

MESSAGE_TYPE_REQUEST = 0x1
MESSAGE_TYPE_RESPONSE = 0x2

# containerd's default; frames beyond it are a protocol error.
MAX_MESSAGE_SIZE = 4 << 20

# google.rpc codes used on this path.
CODE_OK = 0
CODE_UNKNOWN = 2
CODE_UNIMPLEMENTED = 12


class TtrpcError(Exception):
    """Remote returned a non-OK status."""

    def __init__(self, code: int, message: str):
        super().__init__(f"ttrpc status {code}: {message}")
        self.code = code
        self.message = message


class ChannelClosed(Exception):
    """The underlying byte stream ended."""


class ChannelTimeout(Exception):
    """A bounded read ran out of time (the channel itself is still up)."""


class Channel:
    """Byte-stream interface ttrpc runs over (a socket or one mux conn)."""

    def sendall(self, data: bytes) -> None:
        raise NotImplementedError

    def recv_exact(self, n: int, timeout: Optional[float] = None) -> bytes:
        """Return exactly n bytes; raise ChannelClosed on EOF or
        ChannelTimeout when a non-None timeout elapses first."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class SocketChannel(Channel):
    """Channel over a plain (unix) socket."""

    def __init__(self, sock):
        self._sock = sock

    def sendall(self, data: bytes) -> None:
        try:
            self._sock.sendall(data)
        except OSError as e:
            raise ChannelClosed(str(e))

    def recv_exact(self, n: int, timeout: Optional[float] = None) -> bytes:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        buf = b""
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    self._sock.settimeout(None)
                    raise ChannelTimeout(f"recv timed out after {timeout}s")
                self._sock.settimeout(remaining)
            try:
                chunk = self._sock.recv(n - len(buf))
            except socket.timeout:
                self._sock.settimeout(None)
                raise ChannelTimeout(f"recv timed out after {timeout}s")
            except OSError as e:
                raise ChannelClosed(str(e))
            finally:
                if deadline is not None:
                    try:
                        self._sock.settimeout(None)
                    except OSError:
                        pass
            if not chunk:
                raise ChannelClosed("socket closed")
            buf += chunk
        return buf

    def close(self) -> None:
        # shutdown() before close(): a close alone neither wakes a reader
        # thread blocked in recv (it holds a kernel reference to the file,
        # deferring release) nor sends FIN to the peer — both ends of the
        # NRI socket would hang forever instead of reconnecting.
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def write_frame(ch: Channel, stream_id: int, mtype: int, payload: bytes) -> None:
    ch.sendall(_HEADER.pack(len(payload), stream_id, mtype, 0) + payload)


def read_frame(
    ch: Channel, timeout: Optional[float] = None
) -> Tuple[int, int, int, bytes]:
    """-> (stream_id, type, flags, payload)"""
    hdr = ch.recv_exact(_HEADER.size, timeout=timeout)
    length, stream_id, mtype, flags = _HEADER.unpack(hdr)
    if length > MAX_MESSAGE_SIZE:
        raise ChannelClosed(f"oversized ttrpc frame ({length} bytes)")
    payload = ch.recv_exact(length, timeout=timeout) if length else b""
    return stream_id, mtype, flags, payload


class Client:
    """Unary ttrpc client. One in-flight call at a time per caller thread;
    responses are matched by stream id so interleaving is still safe."""

    def __init__(self, channel: Channel):
        self._ch = channel
        self._next_stream = 1
        self._lock = threading.Lock()

    def call(self, service: str, method: str, request, response_cls,
             timeout_s: Optional[float] = None):
        """Unary call; a non-None ``timeout_s`` bounds the wait for the
        response (raising ChannelTimeout) so callers on latency-critical
        threads can't wedge on a stalled runtime. A late response for
        the abandoned stream id is skipped by a later call's sid match."""
        import time as _time

        req = ttrpc_pb2.Request(
            service=service,
            method=method,
            payload=request.SerializeToString(),
            timeout_nano=(
                int(timeout_s * 1e9) if timeout_s is not None else 0
            ),
        )
        with self._lock:
            stream_id = self._next_stream
            self._next_stream += 2  # client streams stay odd
            write_frame(
                self._ch, stream_id, MESSAGE_TYPE_REQUEST,
                req.SerializeToString(),
            )
            deadline = (
                None if timeout_s is None
                else _time.monotonic() + timeout_s
            )
            while True:
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise ChannelTimeout(
                            f"{service}/{method} timed out after "
                            f"{timeout_s}s"
                        )
                try:
                    sid, mtype, _flags, payload = read_frame(
                        self._ch, timeout=remaining
                    )
                except ChannelTimeout:
                    # A timeout may strike MID-FRAME (header consumed,
                    # payload pending): the stream is no longer aligned
                    # and reusing it would parse payload bytes as a
                    # header. Poison the channel — the owner's reconnect
                    # loop builds a fresh session.
                    self._ch.close()
                    raise
                if mtype != MESSAGE_TYPE_RESPONSE or sid != stream_id:
                    logger.warning(
                        "ttrpc client: unexpected frame sid=%d type=%d", sid,
                        mtype,
                    )
                    continue
                resp = ttrpc_pb2.Response.FromString(payload)
                if resp.status.code != CODE_OK:
                    raise TtrpcError(resp.status.code, resp.status.message)
                out = response_cls()
                out.ParseFromString(resp.payload)
                return out


# handler: (request_bytes) -> response_message
Handler = Callable[[bytes], "object"]


class Server:
    """Unary ttrpc server dispatching to registered method handlers."""

    def __init__(self, channel: Channel):
        self._ch = channel
        self._handlers: Dict[Tuple[str, str], Tuple[Handler, type]] = {}
        self._wlock = threading.Lock()
        self._stop_after_reply = False

    def register(self, service: str, method: str, request_cls,
                 handler: Callable) -> None:
        """handler(request_msg) -> response protobuf message."""
        self._handlers[(service, method)] = (handler, request_cls)

    def stop_after_reply(self) -> None:
        """Make serve_forever return once the in-flight response is written
        — lets a Shutdown handler end the session without racing its own
        response frame out of the connection."""
        self._stop_after_reply = True

    def serve_forever(self) -> None:
        """Blocking dispatch loop; returns when the channel closes."""
        while True:
            try:
                sid, mtype, _flags, payload = read_frame(self._ch)
            except ChannelClosed:
                return
            if mtype != MESSAGE_TYPE_REQUEST:
                logger.warning("ttrpc server: dropping frame type=%d", mtype)
                continue
            try:
                req = ttrpc_pb2.Request.FromString(payload)
            except Exception:
                self._respond_error(sid, CODE_UNKNOWN, "malformed request")
                continue
            key = (req.service, req.method)
            entry = self._handlers.get(key)
            if entry is None:
                self._respond_error(
                    sid, CODE_UNIMPLEMENTED,
                    f"{req.service}/{req.method} not implemented",
                )
                continue
            handler, request_cls = entry
            try:
                msg = request_cls()
                msg.ParseFromString(req.payload)
                result = handler(msg)
                resp = ttrpc_pb2.Response(
                    status=ttrpc_pb2.Status(code=CODE_OK),
                    payload=result.SerializeToString(),
                )
            except Exception as e:  # handler fault -> status, keep serving
                logger.exception("ttrpc handler %s/%s failed", *key)
                resp = ttrpc_pb2.Response(
                    status=ttrpc_pb2.Status(code=CODE_UNKNOWN, message=str(e))
                )
            try:
                with self._wlock:
                    write_frame(
                        self._ch, sid, MESSAGE_TYPE_RESPONSE,
                        resp.SerializeToString(),
                    )
            except ChannelClosed:
                return  # peer went away mid-response; session over
            if self._stop_after_reply:
                return

    def _respond_error(self, sid: int, code: int, message: str) -> None:
        resp = ttrpc_pb2.Response(
            status=ttrpc_pb2.Status(code=code, message=message)
        )
        try:
            with self._wlock:
                write_frame(
                    self._ch, sid, MESSAGE_TYPE_RESPONSE,
                    resp.SerializeToString(),
                )
        except ChannelClosed:
            pass
