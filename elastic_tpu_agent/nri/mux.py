"""NRI connection multiplexer.

containerd's NRI socket carries TWO logical ttrpc connections over one
unix socket (github.com/containerd/nri pkg/net/multiplex wire format):

  8-byte frame header, big-endian:
      uint32  connection id
      uint32  payload length
  followed by ``length`` payload bytes belonging to that logical stream.

Connection ids (pkg/api):
  1  Plugin service  — runtime is the ttrpc client, plugin the server
  2  Runtime service — plugin is the ttrpc client, runtime the server
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, Optional

from .ttrpc import Channel, ChannelClosed, ChannelTimeout, SocketChannel

_FRAME = struct.Struct(">II")

PLUGIN_SERVICE_CONN = 1
RUNTIME_SERVICE_CONN = 2

# Same bound the mux applies upstream; a frame larger than this means the
# two ends disagree about the protocol.
_MAX_FRAME = 1 << 24


class MuxChannel(Channel):
    """One logical byte stream inside a Mux."""

    def __init__(self, mux: "Mux", conn_id: int):
        self._mux = mux
        self._id = conn_id
        self._buf = bytearray()
        self._cond = threading.Condition()
        self._closed = False

    # -- reader-thread side --

    def _feed(self, data: bytes) -> None:
        with self._cond:
            self._buf.extend(data)
            self._cond.notify_all()

    def _shutdown(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- Channel interface --

    def sendall(self, data: bytes) -> None:
        self._mux._send(self._id, data)

    def recv_exact(self, n: int, timeout: Optional[float] = None) -> bytes:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            while len(self._buf) < n:
                if self._closed:
                    raise ChannelClosed("mux closed")
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise ChannelTimeout(
                            f"mux recv timed out after {timeout}s"
                        )
                    self._cond.wait(timeout=remaining)
            out = bytes(self._buf[:n])
            del self._buf[:n]
            return out

    def close(self) -> None:
        self._mux.close()


class Mux:
    """Demultiplexes a socket into MuxChannels; one reader thread."""

    def __init__(self, sock):
        self._ch = SocketChannel(sock)
        self._conns: Dict[int, MuxChannel] = {}
        self._wlock = threading.Lock()
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="nri-mux-reader", daemon=True
        )

    def open(self, conn_id: int) -> MuxChannel:
        if conn_id not in self._conns:
            self._conns[conn_id] = MuxChannel(self, conn_id)
        return self._conns[conn_id]

    def start(self) -> None:
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                hdr = self._ch.recv_exact(_FRAME.size)
                conn_id, length = _FRAME.unpack(hdr)
                if length > _MAX_FRAME:
                    raise ChannelClosed(f"oversized mux frame ({length})")
                payload = self._ch.recv_exact(length) if length else b""
                conn = self._conns.get(conn_id)
                if conn is not None:
                    conn._feed(payload)
                # frames for unopened conns are dropped (same as upstream)
        except ChannelClosed:
            pass
        finally:
            self.close()

    def _send(self, conn_id: int, data: bytes) -> None:
        with self._wlock:
            self._ch.sendall(_FRAME.pack(conn_id, len(data)) + data)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._ch.close()
        for conn in self._conns.values():
            conn._shutdown()

    @property
    def closed(self) -> bool:
        return self._closed
