"""Hand-wired gRPC service plumbing for the kubelet APIs.

grpcio-tools is not available in the runtime image, so instead of generated
``*_pb2_grpc.py`` stubs we bind the kubelet method paths explicitly against
grpcio's generic handler API. Method paths are part of the kubelet wire
contract: ``/v1beta1.Registration/Register``, ``/v1beta1.DevicePlugin/*``,
``/v1alpha1.PodResourcesLister/List`` (reference consumed the same services
via generated Go stubs — SURVEY.md §2 components 3/9).
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

import grpc

from . import faults
from .gen import deviceplugin_pb2 as dp
from .gen import podresources_pb2 as pr
from .gen import podresources_v1_pb2 as prv1

# -- kubelet filesystem contract (upstream constants) -------------------------
DEVICE_PLUGIN_VERSION = "v1beta1"
DEVICE_PLUGIN_DIR = "/var/lib/kubelet/device-plugins"
KUBELET_SOCKET_NAME = "kubelet.sock"
POD_RESOURCES_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"

HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"

# pod-resources List is a full-node dump; match the reference's 16 MiB cap
# (locator.go:34).
MAX_MSG_BYTES = 16 * 1024 * 1024

_CHANNEL_OPTS = [
    ("grpc.max_receive_message_length", MAX_MSG_BYTES),
    ("grpc.max_send_message_length", MAX_MSG_BYTES),
]


def unix_target(path: str) -> str:
    return f"unix:{path}"


def dial(path: str, timeout_s: float = 5.0) -> grpc.Channel:
    """Dial a unix socket and block until connected (the reference's
    dial-probe, base.go:185-196); raises on timeout."""
    ch = grpc.insecure_channel(unix_target(path), options=_CHANNEL_OPTS)
    grpc.channel_ready_future(ch).result(timeout=timeout_s)
    return ch


# -- DevicePlugin service (server side) ---------------------------------------


class DevicePluginServicer:
    """Override the five kubelet RPCs (reference base impls: base.go:64-96)."""

    def GetDevicePluginOptions(self, request, context):  # noqa: N802
        return dp.DevicePluginOptions()

    def ListAndWatch(self, request, context):  # noqa: N802
        raise NotImplementedError

    def GetPreferredAllocation(self, request, context):  # noqa: N802
        return dp.PreferredAllocationResponse()

    def Allocate(self, request, context):  # noqa: N802
        raise NotImplementedError

    def PreStartContainer(self, request, context):  # noqa: N802
        return dp.PreStartContainerResponse()


def add_device_plugin_servicer(server: grpc.Server, servicer: DevicePluginServicer) -> None:
    handlers = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=dp.Empty.FromString,
            response_serializer=dp.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=dp.Empty.FromString,
            response_serializer=dp.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=dp.PreferredAllocationRequest.FromString,
            response_serializer=dp.PreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=dp.AllocateRequest.FromString,
            response_serializer=dp.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=dp.PreStartContainerRequest.FromString,
            response_serializer=dp.PreStartContainerResponse.SerializeToString,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("v1beta1.DevicePlugin", handlers),)
    )


class DevicePluginClient:
    """Client for a device-plugin server (used by the fake kubelet in tests
    and by bench.py to play the kubelet role)."""

    def __init__(self, channel: grpc.Channel) -> None:
        p = "/v1beta1.DevicePlugin/"
        self._options = channel.unary_unary(
            p + "GetDevicePluginOptions",
            request_serializer=dp.Empty.SerializeToString,
            response_deserializer=dp.DevicePluginOptions.FromString,
        )
        self._law = channel.unary_stream(
            p + "ListAndWatch",
            request_serializer=dp.Empty.SerializeToString,
            response_deserializer=dp.ListAndWatchResponse.FromString,
        )
        self._alloc = channel.unary_unary(
            p + "Allocate",
            request_serializer=dp.AllocateRequest.SerializeToString,
            response_deserializer=dp.AllocateResponse.FromString,
        )
        self._prestart = channel.unary_unary(
            p + "PreStartContainer",
            request_serializer=dp.PreStartContainerRequest.SerializeToString,
            response_deserializer=dp.PreStartContainerResponse.FromString,
        )
        self._preferred = channel.unary_unary(
            p + "GetPreferredAllocation",
            request_serializer=dp.PreferredAllocationRequest.SerializeToString,
            response_deserializer=dp.PreferredAllocationResponse.FromString,
        )

    def get_options(self) -> dp.DevicePluginOptions:
        return self._options(dp.Empty())

    def list_and_watch(self) -> Iterable[dp.ListAndWatchResponse]:
        return self._law(dp.Empty())

    def allocate(self, device_ids: Iterable[str]) -> dp.AllocateResponse:
        return self._alloc(
            dp.AllocateRequest(
                container_requests=[
                    dp.ContainerAllocateRequest(devicesIDs=list(device_ids))
                ]
            )
        )

    def pre_start_container(self, device_ids: Iterable[str]) -> dp.PreStartContainerResponse:
        return self._prestart(
            dp.PreStartContainerRequest(devicesIDs=list(device_ids))
        )

    def get_preferred_allocation(
        self, available: Iterable[str], must_include: Iterable[str], size: int
    ) -> dp.PreferredAllocationResponse:
        return self._preferred(
            dp.PreferredAllocationRequest(
                container_requests=[
                    dp.ContainerPreferredAllocationRequest(
                        available_deviceIDs=list(available),
                        must_include_deviceIDs=list(must_include),
                        allocation_size=size,
                    )
                ]
            )
        )


# -- Registration service ------------------------------------------------------


class RegistrationClient:
    """Register a plugin endpoint with kubelet (reference: base.go:141-160)."""

    def __init__(self, kubelet_socket: str) -> None:
        self._socket = kubelet_socket

    def register(
        self,
        endpoint: str,
        resource_name: str,
        pre_start_required: bool = True,
        timeout_s: float = 10.0,
    ) -> None:
        ch = dial(self._socket, timeout_s)
        try:
            method = ch.unary_unary(
                "/v1beta1.Registration/Register",
                request_serializer=dp.RegisterRequest.SerializeToString,
                response_deserializer=dp.Empty.FromString,
            )
            method(
                dp.RegisterRequest(
                    version=DEVICE_PLUGIN_VERSION,
                    endpoint=endpoint,
                    resource_name=resource_name,
                    options=dp.DevicePluginOptions(
                        pre_start_required=pre_start_required
                    ),
                ),
                timeout=timeout_s,
            )
        finally:
            ch.close()


def add_registration_servicer(
    server: grpc.Server, register_fn: Callable[[dp.RegisterRequest], None]
) -> None:
    """Server side of Registration — the agent never serves this (kubelet
    does); the fake kubelet in tests does."""

    def _register(request, context):  # noqa: ARG001
        register_fn(request)
        return dp.Empty()

    handlers = {
        "Register": grpc.unary_unary_rpc_method_handler(
            _register,
            request_deserializer=dp.RegisterRequest.FromString,
            response_serializer=dp.Empty.SerializeToString,
        )
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("v1beta1.Registration", handlers),)
    )


# -- PodResourcesLister service ------------------------------------------------


class PodResourcesClient:
    """List() of pod->container->devices (reference: podresources/client.go
    + locator.go:32-41). Lazily re-dials on failure.

    Speaks BOTH kubelet API versions: probes ``v1`` first (served since
    k8s 1.20, adds GetAllocatableResources) and falls back to ``v1alpha1``
    when the kubelet answers UNIMPLEMENTED — the reference spoke only
    v1alpha1 (pkg/podresources/v1alpha1/api.proto). The negotiated version
    sticks for the life of the channel; a reset() re-probes, so a kubelet
    upgrade under us is picked up on reconnect. The two Lists are wire- and
    field-name-compatible for everything the locator touches
    (pod_resources/name/namespace/containers/devices/resource_name/
    device_ids), so callers never see the difference.
    """

    def __init__(self, socket_path: str = POD_RESOURCES_SOCKET) -> None:
        self._socket = socket_path
        self._lock = threading.Lock()  # one client is shared by multiple
        self._channel: Optional[grpc.Channel] = None  # locators + prefetch
        # immutable per-negotiation binding: (list_fn, request_cls,
        # allocatable_fn_or_None, version) — swapped atomically so a caller
        # can never pair a stale callable with the other version's request
        self._bound: Optional[tuple] = None  # threads

    @property
    def api_version(self) -> Optional[str]:
        bound = self._bound
        return bound[3] if bound else None

    @staticmethod
    def _bind_v1(channel) -> tuple:
        list_fn = channel.unary_unary(
            "/v1.PodResourcesLister/List",
            request_serializer=prv1.ListPodResourcesRequest.SerializeToString,
            response_deserializer=prv1.ListPodResourcesResponse.FromString,
        )
        allocatable = channel.unary_unary(
            "/v1.PodResourcesLister/GetAllocatableResources",
            request_serializer=(
                prv1.AllocatableResourcesRequest.SerializeToString
            ),
            response_deserializer=(
                prv1.AllocatableResourcesResponse.FromString
            ),
        )
        return (list_fn, prv1.ListPodResourcesRequest, allocatable, "v1")

    @staticmethod
    def _bind_v1alpha1(channel) -> tuple:
        list_fn = channel.unary_unary(
            "/v1alpha1.PodResourcesLister/List",
            request_serializer=pr.ListPodResourcesRequest.SerializeToString,
            response_deserializer=pr.ListPodResourcesResponse.FromString,
        )
        return (list_fn, pr.ListPodResourcesRequest, None, "v1alpha1")

    # Codes that mean "the kubelet (or the wire) is broken right now", not
    # "this RPC isn't served": negotiation must re-raise these and retry
    # later instead of concluding anything about the API version.
    _TRANSPORT_CODES = frozenset({
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.CANCELLED,
    })

    def _ensure(self, timeout_s: float) -> tuple:
        """Return the negotiated binding tuple, dialing + version-probing
        if needed (thread-safe). The probe is GetAllocatableResources — a
        tiny response, unlike a full-node List — which a v1alpha1-only
        kubelet rejects with UNIMPLEMENTED.

        k8s 1.21-1.22 wrinkle: those kubelets serve v1 List but answer the
        probe with a NON-UNIMPLEMENTED error when the
        KubeletPodResourcesGetAllocatable gate is off. Treating that as
        fatal would strand the locator on a kubelet whose List works fine,
        so on any non-transport probe failure the v1 List itself is probed
        to separate "v1 with allocatable disabled" (bind v1, allocatable
        marked unavailable) from "no v1 at all" (fall back to v1alpha1).
        """
        with self._lock:
            if self._bound is None:
                channel = grpc.insecure_channel(
                    unix_target(self._socket), options=_CHANNEL_OPTS
                )
                grpc.channel_ready_future(channel).result(timeout=timeout_s)
                self._channel = channel
                bound = self._bind_v1(channel)
                try:
                    bound[2](
                        prv1.AllocatableResourcesRequest(), timeout=timeout_s
                    )
                except grpc.RpcError as e:
                    if e.code() == grpc.StatusCode.UNIMPLEMENTED:
                        bound = self._bind_v1alpha1(channel)
                    elif e.code() in self._TRANSPORT_CODES:
                        raise
                    else:
                        try:
                            bound[0](
                                prv1.ListPodResourcesRequest(),
                                timeout=timeout_s,
                            )
                            # v1 List works; only allocatable is gated off
                            bound = (bound[0], bound[1], None, "v1")
                        except grpc.RpcError as e2:
                            if (
                                e2.code()
                                == grpc.StatusCode.UNIMPLEMENTED
                            ):
                                bound = self._bind_v1alpha1(channel)
                            else:
                                raise
                self._bound = bound
            return self._bound

    def reset(self) -> None:
        """Drop the channel so the next call re-dials (and re-probes the
        API version — a kubelet upgrade under us is picked up here). The
        old channel is closed after a grace period, NOT immediately: other
        threads (locator prefetch + inline locate share this client) may
        have RPCs in flight on it, and close() would cancel them."""
        with self._lock:
            old = self._channel
            self._channel = None
            self._bound = None
        if old is not None:
            timer = threading.Timer(5.0, old.close)
            timer.daemon = True
            timer.start()

    def list(self, timeout_s: float = 5.0):
        faults.fire("podresources.list")
        try:
            list_fn, req_cls, _, _ = self._ensure(timeout_s)
            return list_fn(req_cls(), timeout=timeout_s)
        except grpc.RpcError:
            self.reset()  # re-dial next call (reference: locator.go:47-53)
            raise

    def get_allocatable_resources(
        self, timeout_s: float = 5.0
    ) -> Optional[prv1.AllocatableResourcesResponse]:
        """Node allocatable devices (v1 only). Returns None when the
        kubelet only speaks v1alpha1 — callers treat that as 'unknown',
        not 'empty'."""
        try:
            _, _, allocatable_fn, version = self._ensure(timeout_s)
            if allocatable_fn is None:
                return None  # negotiated v1alpha1: genuinely unavailable
            return allocatable_fn(
                prv1.AllocatableResourcesRequest(), timeout=timeout_s
            )
        except grpc.RpcError:
            self.reset()
            raise

    def close(self) -> None:
        self.reset()


def add_pod_resources_servicer(
    server: grpc.Server,
    list_fn: Callable[[], pr.ListPodResourcesResponse],
) -> None:
    """Server side of pod-resources — served by kubelet in production, by
    the fake kubelet in tests (the reference shipped an unused server impl
    it never wired up as a fake; we use ours, SURVEY.md §4)."""

    def _list(request, context):  # noqa: ARG001
        return list_fn()

    handlers = {
        "List": grpc.unary_unary_rpc_method_handler(
            _list,
            request_deserializer=pr.ListPodResourcesRequest.FromString,
            response_serializer=pr.ListPodResourcesResponse.SerializeToString,
        )
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("v1alpha1.PodResourcesLister", handlers),)
    )


def add_pod_resources_v1_servicer(
    server: grpc.Server,
    list_fn: Callable[[], "prv1.ListPodResourcesResponse"],
    allocatable_fn: Optional[
        Callable[[], "prv1.AllocatableResourcesResponse"]
    ] = None,
) -> None:
    """v1 pod-resources server (kubelet >= 1.20 shape): List +
    GetAllocatableResources. Used by the fake kubelet so client version
    negotiation is testable against both shapes."""

    def _list(request, context):  # noqa: ARG001
        return list_fn()

    def _allocatable(request, context):  # noqa: ARG001
        # Real v1 kubelets always implement this RPC (the client uses it as
        # its version probe) — an unconfigured fake answers empty, never
        # UNIMPLEMENTED, which would misread as a v1alpha1-only kubelet.
        if allocatable_fn is None:
            return prv1.AllocatableResourcesResponse()
        return allocatable_fn()

    handlers = {
        "List": grpc.unary_unary_rpc_method_handler(
            _list,
            request_deserializer=prv1.ListPodResourcesRequest.FromString,
            response_serializer=(
                prv1.ListPodResourcesResponse.SerializeToString
            ),
        ),
        "GetAllocatableResources": grpc.unary_unary_rpc_method_handler(
            _allocatable,
            request_deserializer=(
                prv1.AllocatableResourcesRequest.FromString
            ),
            response_serializer=(
                prv1.AllocatableResourcesResponse.SerializeToString
            ),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler("v1.PodResourcesLister", handlers),)
    )
