# Build/test entry points (reference had image-build only, Makefile:1-11;
# a test target was notably absent there).
TAG ?= elastic-tpu-agent:latest

.PHONY: all native sanitize test test-all protos image bench clean

all: native test

native:
	$(MAKE) -C native

sanitize:
	$(MAKE) -C native sanitize

# fast tier: the correctness loop. Soaks, runner-mode sweeps, pipeline
# sweeps, and sanitized-native builds carry @pytest.mark.slow and run
# only under test-all (CI). Measured on the 1-CPU CI box: fast ~20 min,
# full ~34 min (the box is single-core; XLA compiles dominate).
test: native
	python -m pytest tests/ -q -m "not slow"

test-all: native
	python -m pytest tests/ -q

protos:
	sh elastic_tpu_agent/protos/regen.sh

image:
	docker build -t $(TAG) .

bench:
	python3 bench.py

clean:
	$(MAKE) -C native clean
