# Build/test entry points (reference had image-build only, Makefile:1-11;
# a test target was notably absent there).
TAG ?= elastic-tpu-agent:latest

# verify's tier-1 line uses pipefail, which /bin/sh (dash) lacks
SHELL := /bin/bash

.PHONY: all native sanitize test test-all verify doctor-smoke chaos-smoke bench-smoke crash-replay-smoke fleet-smoke event-smoke scale-smoke slice-smoke drain-smoke migrate-smoke timeline-smoke serving-smoke request-obs-smoke qos-smoke goodput-smoke latency-smoke chaos-matrix-smoke perf-gate protos image bench clean

all: native test

native:
	$(MAKE) -C native

sanitize:
	$(MAKE) -C native sanitize

# fast tier: the correctness loop. Soaks, runner-mode sweeps, pipeline
# sweeps, and sanitized-native builds carry @pytest.mark.slow and run
# only under test-all (CI). Measured on the 1-CPU CI box: fast ~20 min,
# full ~34 min (the box is single-core; XLA compiles dominate).
test: native
	python -m pytest tests/ -q -m "not slow"

test-all: native
	python -m pytest tests/ -q

# The CI gate: the exact tier-1 command from ROADMAP.md plus a
# metrics-registry smoke check (two AgentMetrics against fresh
# registries catches duplicate-metric-name regressions at build time,
# before a scrape ever hits the endpoint). T1_TIMEOUT: the ROADMAP
# budget by default; raise it on boxes slower than the reference
# (`make verify T1_TIMEOUT=1800`).
# node-doctor smoke: generate a diagnostics bundle against the stub
# operator in a scratch dir, then schema-validate it — catches a broken
# doctor/bundle path at build time, before support ever needs one.
doctor-smoke:
	@tmp=$$(mktemp -d) && \
	  python -m elastic_tpu_agent.cli node-doctor \
	    --operator stub:v5litepod-4 --node-name smoke \
	    --dev-root $$tmp/dev --db-file $$tmp/meta.db \
	    --alloc-spec-dir $$tmp/alloc --samples 2 --interval 0 \
	    > $$tmp/bundle.json && \
	  python -m elastic_tpu_agent.cli node-doctor --validate $$tmp/bundle.json && \
	  rm -rf $$tmp && echo "doctor smoke: OK"

# chaos smoke: the fault-injection suite — kills every supervised loop
# (die-thread failpoints), forces crash loops, and checks the /healthz
# 503-vs-degraded contract. Fast (~15s); catches a broken supervisor or
# fault registry at build time, before a node ever depends on the
# reflexes.
chaos-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_supervisor.py -q \
	  -p no:cacheprovider && echo "chaos smoke: OK"

# bench smoke: a tiny, deterministic concurrent-churn burst (bench.py
# --churn-smoke) on the stub cluster, run in BOTH pipeline shapes
# (striped+shared and the global-lock/dual-locator baseline), checked
# against structural sanity thresholds — every bind succeeds, exactly
# one record per pod, no O(n) storage scan on the bind path, the shared
# snapshot actually reduces kubelet List traffic. Timing thresholds are
# deliberately loose (5s p99 bound): the CI box's speed must not flake
# the gate.
bench-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --churn-smoke

# crash-replay smoke: the kill-at-every-failpoint suite — dies at each
# mid-bind crash window (die-thread failpoints), each mid-DRAIN window
# (drain.pre_cordon/post_signal/pre_reclaim) and each mid-REPARTITION
# window (repartition.pre_journal/post_journal/mid_restamp plus the
# between-sibling-spec-files restamp.spec_file tear), each mid-MIGRATION
# window (migration.pre_ack/post_record), restarts the
# manager over the surviving store + fake kubelet, and asserts
# convergence to the crash-free end state (empty bind-intent journal;
# resumed drain lifecycle; no pod left at a torn quota) — AND that the
# surviving lifecycle timeline still tells a consistent story (no
# phantom commits, every crashed intent resolved by a visible
# rollback/commit event; tests/test_timeline.py). Deterministic:
# in-process drive, no sleeps on the replay path.
crash-replay-smoke:
	JAX_PLATFORMS=cpu python -m pytest tests/test_reconciler.py \
	  tests/test_drain.py tests/test_timeline.py \
	  tests/test_repartition.py tests/test_migration.py -q \
	  -p no:cacheprovider && echo "crash replay smoke: OK"

# fleet smoke: the cluster-in-a-box simulator (bench.py --fleet-smoke):
# 4 in-process agents x 100 pods against one shared fake apiserver,
# churned fleet-wide and read back through the scraping aggregator.
# Structural assertions only — every bind lands, every node
# reconcile-converges after the churn, kubelet/apiserver request
# amplification stays within bound, admission->bind trace continuity
# holds — so a broken fleet observability layer (or a bind path that
# stopped scaling past one node) fails the build, not a dashboard.
fleet-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --fleet-smoke

# event smoke: the event-driven core gate (bench.py --event-smoke) —
# 2-node fleet, kill a bound pod's checkpoint record: the store's own
# delete notification must drive event-to-repair p50 under 50ms, a
# bus-suppressed (dropped) notification must still be caught by the
# stretched safety-net sweep, and the poll-only fallback must heal the
# same divergence with events disabled entirely.
event-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --event-smoke

# scale smoke: the thousand-pod scale-harness gate (bench.py
# --scale-smoke): 8 in-process agents x 64 pods driven through the full
# scenario (admission waves, delete churn, drain wave, slice reform,
# repartition ticks, 10k-series cardinality storm) in BOTH storage
# shapes — group-commit batching + coalesced sinks, and the historical
# per-write baseline. Structural assertions only: every bind lands,
# every node reconcile-converges, kubelet/apiserver/sink amplification
# within bound, RSS growth per driven series under the documented
# ceiling, batching measurably reduces storage commits per bind, and
# the kill-at-a-mid-bind-failpoint crash drill replays clean with
# batching ON and OFF.
scale-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --scale-smoke

# slice smoke: the slice-orchestrator chaos gate (bench.py
# --slice-smoke): a 4-agent multi-host slice forms against the shared
# fake apiserver (consistent TPU_WORKER_ID/HOSTNAMES env on every
# member), then one member agent is killed and its pod evicted — the
# survivors' reconcilers must re-form the slice at world size 3 with
# re-emitted topology env, a bumped epoch, a counted reform on every
# survivor and a TPUSliceReformed event per member. Structural and
# deterministic (no timing thresholds).
slice-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --slice-smoke

# drain smoke: the graceful-drain chaos gate (bench.py --drain-smoke):
# a 4-agent slice forms, then a GCE maintenance event is announced on
# one member's host — that agent must cordon WITHOUT failing health,
# stamp the deadline-bearing ELASTIC_TPU_DRAIN signal, and proactively
# annotate its member draining so the survivors re-form to world 3
# BEFORE the reclaim; the agent is then restarted mid-drain (journaled
# lifecycle must resume), the deadline reclaim must leave zero orphan
# links/specs per a converged reconciler pass, and the full event trail
# (TPUMaintenanceImminent/TPUNodeDraining/TPUSliceReformed/
# TPUNodeDrained) must reach the apiserver. Structural, deterministic.
drain-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --drain-smoke

# migrate smoke: the verified-migration gate (bench.py --migrate-smoke):
# a 4-node fleet runs stub workloads with the REAL lifecycle watcher; a
# maintenance drain on one node must produce an acked early reclaim
# with measured margin > 0 before the deadline, a published
# MigrationRecord the replacement pod (re-admitted on another node)
# restores from with the destination verifying the resume at the acked
# step, survivor slice members checkpoint-acking the reform at the
# post-reform world size, and an un-acked resident still honoring the
# FULL deadline. Structural, deterministic.
migrate-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --migrate-smoke

# timeline smoke: the lifecycle-journal gate (bench.py
# --timeline-smoke): a 4-agent fleet takes a churn burst sized past
# the timeline ring cap, forms a slice, then drains one member through
# maintenance (with a mid-drain agent restart) — every node's journal
# must stay seq-ordered and ring-capped with an accurate durable
# eviction counter, the aggregator's merged fleet view must sequence
# the story causally (draining before reform before reclaim, per-node
# order never violated), and `node-doctor timeline` must reconstruct
# the per-pod bind->reform->drain->reclaim history from the db alone,
# across the restart. Structural, deterministic.
timeline-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --timeline-smoke

# serving smoke: the serving data plane's CPU-only gate (bench.py
# --serving-smoke): the serving_proxy leg must run and its modeled
# gather-vs-paged HBM ratio must clear the documented paged_kernel
# threshold (with the XLA cost-analysis corroboration present), the
# repeated-shared-prefix scenario must show >= 3x prefilled-token
# reduction with the automatic prefix cache on and logit-equivalent
# (identical greedy) streams, and a 2-device tensor-parallel decode
# (--xla_force_host_platform_device_count) must match the
# single-device engine's streams and pool occupancy. Structural,
# deterministic.
serving-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --serving-smoke

# request-obs smoke: the request observatory end to end (bench.py
# --request-obs-smoke): unified head-of-line stall attributed while a
# disaggregated decode's TPOT rides through the same burst, stitched
# handoff = one partition per id, cached-token attribution, the
# /debug/requests endpoint contracts, and the fleet SLO rollup equal
# to the per-node ledgers.
request-obs-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --request-obs-smoke

# qos smoke: the utilization-loop gate (bench.py --qos-smoke,
# CPU-deterministic): two engines co-located on one stub chip under
# phase-imbalanced load must decode measurably more aggregate tokens
# with LIVE re-partitioning (the real annotation -> usage-report ->
# sampler -> controller -> restamped-quota loop) than the same run's
# static 50/50 baseline, with the quota trace proving units moved both
# ways and no spurious throttle; and the prefill/decode split must
# decode a token every tick through a long-prompt burst that
# head-of-line blocks the unified engine, with bit-identical streams.
qos-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --qos-smoke

# goodput smoke: the goodput-ledger gate (bench.py --goodput-smoke): a
# 4-node fleet runs the drain-with-migration story plus a QoS
# throttle->unthrottle story, then every node's ledger replays its
# journal — conservation must hold on every node AND over the wire
# (state intervals partition each pod's lifetime, gaps priced as
# unattributed), the drain's non-productive time must be attributed to
# the maintenance trigger, the clamp window to qos_throttle, the
# aggregator's fleet rollup must equal the per-node ledgers exactly,
# and the ledger's migration-attributed downtime must agree with the
# bench's own drain-to-resume stopwatch within one reconcile period.
# Structural, deterministic.
goodput-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --goodput-smoke

# chaos-matrix smoke: the serve-the-ugly-day gate (bench.py
# --chaos-matrix-smoke): seeded replayable traffic (diurnal load,
# flash crowds, prefix-hostile prompts, train/serve tenancy) replayed
# through a live 2-node fleet's real admission paths while a seeded
# chaos program overlaps apiserver brownouts, storage flush faults,
# kubelet socket flaps and maintenance drains. Schedule generation
# must be deterministic (generated twice, identical digests), every
# compound scenario must end with zero conservation problems and
# goodput/SLO above the floors, and a sabotaged known-bad run must
# TRIP the checker. Failing scenarios print a one-line repro
# (--trace-seed/--chaos-seed/--scenario).
chaos-matrix-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --chaos-matrix-smoke

# latency smoke: the critical-path observatory gate (bench.py
# --latency-smoke): a 2-node fleet churns, then injects a maintenance
# notice and a telemetry failure — the injected events must surface in
# the detection-lag histograms with sane (never-negative) bounds, the
# phase-attributed bind breakdown must account for measured totals
# within the 15% residual bound with resolvable trace exemplars, the
# continuous self-profiler must stay under its 1% measured-overhead
# contract, and every fully-wired agent's /metrics must lint clean.
latency-smoke:
	JAX_PLATFORMS=cpu python3 bench.py --latency-smoke

# perf gate: the perf-regression ledger (elastic_tpu_agent/
# bench_history.py) — parse the committed BENCH_r*.json trajectory
# into per-leg latency series, schema-validate every round, and fail
# when the newest round regresses beyond tolerance against the
# recent-median baseline. --self-test additionally seeds a synthetic
# regression and fails unless the gate catches it on every tracked
# series (the gate gating itself).
perf-gate:
	python3 -m elastic_tpu_agent.cli perf-gate --self-test

T1_TIMEOUT ?= 870
verify: doctor-smoke chaos-smoke bench-smoke crash-replay-smoke fleet-smoke event-smoke scale-smoke slice-smoke drain-smoke migrate-smoke timeline-smoke serving-smoke request-obs-smoke qos-smoke goodput-smoke latency-smoke chaos-matrix-smoke perf-gate
	python -c "from prometheus_client import CollectorRegistry; \
	  from elastic_tpu_agent.metrics import AgentMetrics; \
	  AgentMetrics(registry=CollectorRegistry()); \
	  AgentMetrics(registry=CollectorRegistry()); \
	  print('metrics registry smoke: OK')"
	set -o pipefail; rm -f /tmp/_t1.log; \
	  timeout -k 10 $(T1_TIMEOUT) env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	  rc=$${PIPESTATUS[0]}; \
	  echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	  exit $$rc

protos:
	sh elastic_tpu_agent/protos/regen.sh

image:
	docker build -t $(TAG) .

bench:
	python3 bench.py

clean:
	$(MAKE) -C native clean
