# Build/test entry points (reference had image-build only, Makefile:1-11;
# a test target was notably absent there).
TAG ?= elastic-tpu-agent:latest

.PHONY: all native sanitize test test-all protos image bench clean

all: native test

native:
	$(MAKE) -C native

sanitize:
	$(MAKE) -C native sanitize

# fast tier: the correctness loop (<~5 min); soak/sweep/sanitized-native
# tests carry @pytest.mark.slow and run under test-all (CI)
test: native
	python -m pytest tests/ -q -m "not slow"

test-all: native
	python -m pytest tests/ -q

protos:
	sh elastic_tpu_agent/protos/regen.sh

image:
	docker build -t $(TAG) .

bench:
	python3 bench.py

clean:
	$(MAKE) -C native clean
