# Build/test entry points (reference had image-build only, Makefile:1-11;
# a test target was notably absent there).
TAG ?= elastic-tpu-agent:latest

.PHONY: all native sanitize test protos image bench clean

all: native test

native:
	$(MAKE) -C native

sanitize:
	$(MAKE) -C native sanitize

test: native
	python -m pytest tests/ -q

protos:
	sh elastic_tpu_agent/protos/regen.sh

image:
	docker build -t $(TAG) .

bench:
	python3 bench.py

clean:
	$(MAKE) -C native clean
