"""Concurrent bind pipeline tests: striped per-owner locks, the shared
single-flight pod-resources snapshot, and O(1) store accounting.

Kubelet drives Allocate/PreStartContainer from a concurrent gRPC pool
(core + memory pairs land in parallel per container; a node restart
re-binds every pod at once). These tests pin the pipeline's contracts
under exactly that concurrency:

- a core+memory PreStart pair for the SAME container racing from two
  threads yields merged alloc specs and exactly one storage record;
- binds of UNRELATED pods do not serialize (a stalled bind of pod A
  must not block pod B);
- no full storage scan runs on the per-bind path, and the periodic
  scanners (GC, sampler join) hit the record cache instead of
  re-parsing every row each tick;
- concurrent cold locates coalesce onto a single-flight List instead of
  stampeding the kubelet, and one List serves both resources.
"""

import json
import os
import threading
import time

import pytest

from elastic_tpu_agent import rpc
from elastic_tpu_agent.common import (
    AnnotationAssumed,
    ResourceTPUCore,
    ResourceTPUMemory,
    container_annotation,
)
from elastic_tpu_agent.gen import deviceplugin_pb2 as dp
from elastic_tpu_agent.kube.locator import (
    KubeletDeviceLocator,
    PodResourcesSnapshotSource,
)
from elastic_tpu_agent.plugins import tpushare
from elastic_tpu_agent.plugins.base import PluginConfig
from elastic_tpu_agent.plugins.tpushare import (
    TPUSharePlugin,
    core_device_id,
    mem_device_id,
)
from elastic_tpu_agent.rpc import PodResourcesClient
from elastic_tpu_agent.storage import Storage
from elastic_tpu_agent.tpu import StubOperator
from elastic_tpu_agent.types import Device

from fake_kubelet import FakeKubelet, FakeSitter


class CountingClient(PodResourcesClient):
    def __init__(self, socket_path):
        super().__init__(socket_path)
        self.lists = 0

    def list(self, timeout_s: float = 5.0):
        self.lists += 1
        return super().list(timeout_s=timeout_s)


@pytest.fixture()
def rig(tmp_path):
    """Fake kubelet + stub operator + plugin bundle sharing ONE
    pod-resources snapshot source (the manager's wiring), with the
    servicers exposed for direct in-process calls."""
    dp_dir = str(tmp_path / "dp")
    pr_sock = str(tmp_path / "pr" / "kubelet.sock")
    dev_root = str(tmp_path / "dev")
    os.makedirs(dev_root)
    kubelet = FakeKubelet(dp_dir, pr_sock)
    kubelet.start()
    sitter = FakeSitter()
    storage = Storage(str(tmp_path / "meta.db"))
    client = CountingClient(pr_sock)
    source = PodResourcesSnapshotSource(client)
    config = PluginConfig(
        node_name="test-node",
        device_plugin_dir=dp_dir,
        pod_resources_socket=pr_sock,
        operator=StubOperator(dev_root, "v5litepod-4"),
        sitter=sitter,
        storage=storage,
        locator_factory=lambda res: KubeletDeviceLocator(res, source=source),
        extra={"alloc_spec_dir": str(tmp_path / "alloc")},
    )
    plugin = TPUSharePlugin(config)

    class R:
        pass

    r = R()
    r.kubelet, r.sitter, r.storage = kubelet, sitter, storage
    r.plugin, r.client, r.source = plugin, client, source
    r.alloc_dir = str(tmp_path / "alloc")
    yield r
    plugin.core.stop_streams()
    plugin.memory.stop_streams()
    kubelet.stop()
    storage.close()


def both_annotations(container="jax", chips="0"):
    return {
        AnnotationAssumed: "true",
        container_annotation(container): chips,
    }


def bind_pair_ids(i, chip=0):
    core = [core_device_id(chip, f"{i}x{j}") for j in range(10)]
    mem = [mem_device_id(chip, f"{i}x{j}") for j in range(16)]
    return core, mem


def prestart(servicer, ids):
    servicer.PreStartContainer(
        dp.PreStartContainerRequest(devicesIDs=ids), None
    )


# -- the sibling race ---------------------------------------------------------


def test_sibling_race_merges_specs_and_keeps_one_record(rig):
    """Core and memory PreStartContainer for the SAME container racing
    from two threads: the specs must come out merged (union devices/env)
    and storage must hold exactly one record carrying BOTH resources —
    the lost-update the per-owner lock exists to prevent."""
    rounds = 6
    for i in range(rounds):
        pod = f"race-{i}"
        rig.sitter.add_pod("default", pod, both_annotations())
        core_ids, mem_ids = bind_pair_ids(i)
        rig.kubelet.assign("default", pod, "jax", ResourceTPUCore, core_ids)
        rig.kubelet.assign("default", pod, "jax", ResourceTPUMemory, mem_ids)
        barrier = threading.Barrier(2)
        errors = []

        def race(servicer, ids):
            try:
                barrier.wait(timeout=5)
                prestart(servicer, ids)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        t1 = threading.Thread(
            target=race, args=(rig.plugin.core, core_ids)
        )
        t2 = threading.Thread(
            target=race, args=(rig.plugin.memory, mem_ids)
        )
        t1.start(); t2.start()
        t1.join(10); t2.join(10)
        assert not errors, f"round {i}: {errors}"

        # exactly one storage record, carrying both resources
        info = rig.storage.load("default", pod)
        assert info is not None
        by_resource = info.allocations["jax"]
        assert set(by_resource) == {ResourceTPUCore, ResourceTPUMemory}, (
            f"round {i}: sibling record lost: {sorted(by_resource)}"
        )

        # both spec files exist and carry the merged union
        core_hash = Device(core_ids, ResourceTPUCore).hash
        mem_hash = Device(mem_ids, ResourceTPUMemory).hash
        specs = []
        for h in (core_hash, mem_hash):
            with open(os.path.join(rig.alloc_dir, f"{h}.json")) as f:
                specs.append(json.load(f))
        for spec in specs:
            assert sorted(spec["resources"]) == sorted(
                [ResourceTPUCore, ResourceTPUMemory]
            ), f"round {i}: unmerged spec {spec['hash']}"
        assert specs[0]["chip_indexes"] == specs[1]["chip_indexes"]
        assert specs[0]["env"] == specs[1]["env"]
        # cleanup between rounds keeps chip unit space unambiguous
        rig.sitter.remove_pod("default", pod)
        rig.kubelet.unassign_pod("default", pod)


def test_unrelated_pods_do_not_serialize(rig):
    """A bind of pod A stalled INSIDE its critical section (storage save
    gated) must not block pod B's bind — the global-lock predecessor
    serialized exactly this. Pod names are chosen onto different stripes
    (crc32 striping is deterministic)."""
    # pick two pod names on different stripes
    locks = tpushare._BIND_LOCKS
    name_a = "par-a"
    name_b = next(
        n for n in (f"par-b{i}" for i in range(64))
        if locks.lock_for(f"default/{n}")
        is not locks.lock_for(f"default/{name_a}")
    )
    for i, name in ((0, name_a), (1, name_b)):
        rig.sitter.add_pod("default", name, both_annotations())
        core_ids, _ = bind_pair_ids(10 + i)
        rig.kubelet.assign(
            "default", name, "jax", ResourceTPUCore, core_ids
        )

    a_entered = threading.Event()
    gate = threading.Event()
    real_mutate = rig.storage.mutate

    # Gate pod A inside its bind critical section (the checkpoint step),
    # BEFORE any storage-internal lock — the single sqlite connection
    # legitimately serializes raw row writes, so gating under the
    # storage lock would block everyone by construction.
    def gated_mutate(namespace, name, fn):
        if name == name_a:
            a_entered.set()
            assert gate.wait(timeout=10), "test gate never released"
        return real_mutate(namespace, name, fn)

    rig.storage.mutate = gated_mutate
    errors = []

    def bind_a():
        try:
            core_ids, _ = bind_pair_ids(10)
            prestart(rig.plugin.core, core_ids)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=bind_a)
    t.start()
    try:
        assert a_entered.wait(timeout=10), "pod A never reached its save"
        # A holds its stripe, blocked in the critical section; B must
        # still bind.
        t0 = time.monotonic()
        core_ids_b, _ = bind_pair_ids(11)
        prestart(rig.plugin.core, core_ids_b)
        elapsed = time.monotonic() - t0
        assert rig.storage.load("default", name_b) is not None
        assert t.is_alive(), "pod A finished early; the test proved nothing"
        assert elapsed < 5.0, f"pod B serialized behind pod A ({elapsed:.1f}s)"
    finally:
        gate.set()
        t.join(timeout=10)
        rig.storage.mutate = real_mutate
    assert not errors
    assert rig.storage.load("default", name_a) is not None


def test_sibling_pair_still_serializes_via_shared_stripe(rig):
    """The same pod's core and memory binds must share a stripe — that
    is the merge-correctness half of the striping contract."""
    locks = tpushare._BIND_LOCKS
    assert locks.lock_for("ns/pod-x") is locks.lock_for("ns/pod-x")
    # and the global-mode escape hatch degenerates to one lock
    one = tpushare.set_bind_lock_stripes(1)
    try:
        assert one.lock_for("a/b") is one.lock_for("c/d")
    finally:
        tpushare.set_bind_lock_stripes(
            tpushare.DEFAULT_BIND_LOCK_STRIPES
        )


# -- O(1) accounting / record cache -------------------------------------------


def bind_whole(rig, i, pod):
    rig.sitter.add_pod("default", pod, both_annotations())
    core_ids, mem_ids = bind_pair_ids(i)
    rig.kubelet.assign("default", pod, "jax", ResourceTPUCore, core_ids)
    rig.kubelet.assign("default", pod, "jax", ResourceTPUMemory, mem_ids)
    prestart(rig.plugin.core, core_ids)
    prestart(rig.plugin.memory, mem_ids)


def test_no_full_scans_on_bind_path(rig):
    """The per-bind path must be O(1) in stored pods: no full storage
    scan per bind. The periodic scanners (GC, health fan-out, sampler
    join) pay ONE scan to warm the record cache and are cache-served
    afterwards — even across interleaved binds."""
    bind_whole(rig, 20, "scan-0")
    scans_after_first = rig.storage.scans
    for i in (21, 22, 23):
        bind_whole(rig, i, f"scan-{i - 20}")
    assert rig.storage.scans == scans_after_first, (
        "a bind triggered a full storage scan — O(n) crept back onto "
        "the hot path"
    )
    assert rig.storage.count() == 4

    # GC warms the cache once...
    rig.plugin.gc_once()
    scans_warm = rig.storage.scans
    assert scans_warm >= scans_after_first
    serves0 = rig.storage.cache_serves
    # ...then repeated sweeps, sampler joins and even interleaved binds
    # stay scan-free.
    rig.plugin.gc_once()
    bind_whole(rig, 24, "scan-4")
    rig.plugin.gc_once()

    from elastic_tpu_agent.sampler import UtilizationSampler

    sampler = UtilizationSampler(
        rig.plugin.core._operator, storage=rig.storage,
        alloc_spec_dir=rig.alloc_dir, period_s=0,
    )
    sampler.sample_once()
    sampler.sample_once()
    assert rig.storage.scans == scans_warm, (
        "periodic scanners re-scanned despite a warm record cache"
    )
    assert rig.storage.cache_serves > serves0
    assert rig.storage.count() == 5


def test_bind_stats_surface(rig):
    """bind_stats(): the /debug + doctor-bundle introspection for the
    concurrent pipeline (pool size, lock striping, totals)."""
    bind_whole(rig, 30, "stats-0")
    stats = rig.plugin.bind_stats()
    assert stats["grpc_pool_size"] == 8  # PluginConfig default
    assert stats["bind_locks"]["stripes"] == tpushare._BIND_LOCKS.stripes
    core = stats["resources"][ResourceTPUCore]
    assert core["binds_total"] >= 1
    assert core["inflight"] == 0
    # and the sampler snapshot carries it (manager wiring contract)
    from elastic_tpu_agent.sampler import UtilizationSampler

    sampler = UtilizationSampler(
        rig.plugin.core._operator, storage=rig.storage,
        alloc_spec_dir=rig.alloc_dir, period_s=0,
    )
    sampler.bind_stats_fn = rig.plugin.bind_stats
    sampler.sample_once()
    snap = sampler.allocations_snapshot()
    assert snap["bind"]["grpc_pool_size"] == 8
    assert "bind_locks" in snap["bind"]


# -- shared snapshot + single-flight ------------------------------------------


RESOURCE_IDS = {
    ResourceTPUCore: ["tpu-core-0-a", "tpu-core-0-b"],
    ResourceTPUMemory: ["tpu-mem-0-a", "tpu-mem-0-b"],
}


def test_one_list_serves_both_resources(tmp_path):
    """A cold core locate warms the MEMORY locator too: the shared
    snapshot halves cold-locate Lists for core+memory sibling pairs."""
    k = FakeKubelet(str(tmp_path / "dp"), str(tmp_path / "pr" / "k.sock"))
    k.start()
    try:
        for res, ids in RESOURCE_IDS.items():
            k.assign("ns", "p", "jax", res, ids)
        client = CountingClient(k.pod_resources_socket)
        source = PodResourcesSnapshotSource(client)
        core_loc = KubeletDeviceLocator(ResourceTPUCore, source=source)
        mem_loc = KubeletDeviceLocator(ResourceTPUMemory, source=source)
        owner = core_loc.locate(
            Device(RESOURCE_IDS[ResourceTPUCore], ResourceTPUCore)
        )
        assert owner.name == "p"
        assert client.lists == 1
        owner = mem_loc.locate(
            Device(RESOURCE_IDS[ResourceTPUMemory], ResourceTPUMemory)
        )
        assert owner.name == "p"
        assert client.lists == 1, (
            "memory locate paid its own List despite the shared snapshot"
        )
        stats = core_loc.stats()
        assert stats["shared_source"] is True
        assert stats["lists_total"] == 1
    finally:
        k.stop()


def test_stalled_list_does_not_serialize_misses(tmp_path, monkeypatch):
    """A wedged kubelet List must not queue every miss behind it one
    stalled deadline at a time: after STALL_WAIT_TIMEOUT_S a waiter
    abandons single-flight and pays its own List concurrently."""
    k = FakeKubelet(str(tmp_path / "dp"), str(tmp_path / "pr" / "k.sock"))
    k.start()
    try:
        ids = ["tpu-core-0-s0", "tpu-core-0-s1"]
        k.assign("ns", "p", "jax", ResourceTPUCore, ids)
        client = CountingClient(k.pod_resources_socket)
        source = PodResourcesSnapshotSource(client)
        monkeypatch.setattr(source, "STALL_WAIT_TIMEOUT_S", 0.2)
        loc = KubeletDeviceLocator(ResourceTPUCore, source=source)
        stall = threading.Event()
        orig_list = client.list
        first = {"armed": True}

        def wedged_first_list(timeout_s=5.0):
            if first["armed"]:
                first["armed"] = False
                stall.wait(10.0)  # the wedged List
                raise RuntimeError("kubelet deadline")
            return orig_list(timeout_s=timeout_s)

        client.list = wedged_first_list
        wedged_err = []

        def wedged_runner():
            try:
                source.refresh()
            except Exception as e:  # noqa: BLE001 - expected deadline
                wedged_err.append(e)

        t = threading.Thread(target=wedged_runner)
        t.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        owner = loc.locate(Device(ids, ResourceTPUCore))
        elapsed = time.monotonic() - t0
        assert owner.name == "p"
        assert elapsed < 2.0, (
            f"miss served only after the stalled List ({elapsed:.1f}s) — "
            "the stall escape is broken"
        )
        stall.set()
        t.join(timeout=10)
    finally:
        k.stop()


def test_concurrent_cold_misses_coalesce_single_flight(tmp_path):
    """N threads missing concurrently while a (stale) List is in flight
    must coalesce onto ONE fresh List, not stampede the kubelet with N.
    Budget: 1 stale List + at most 2 coalesced generations."""
    k = FakeKubelet(str(tmp_path / "dp"), str(tmp_path / "pr" / "k.sock"))
    k.start()
    try:
        client = CountingClient(k.pod_resources_socket)
        source = PodResourcesSnapshotSource(client)
        loc = KubeletDeviceLocator(ResourceTPUCore, source=source)
        gate = threading.Event()
        orig_list = client.list
        gated = {"armed": True}

        def slow_list(timeout_s=5.0):
            if gated["armed"]:
                gated["armed"] = False
                gate.wait(5.0)
            return orig_list(timeout_s=timeout_s)

        client.list = slow_list
        # a prefetch whose List is gated open — and predates the assigns
        loc.prefetch_async()
        time.sleep(0.05)
        ids = {
            i: [f"tpu-core-0-m{i}-{u}" for u in range(3)] for i in range(4)
        }
        for i, devs in ids.items():
            k.assign("ns", f"pod-{i}", "jax", ResourceTPUCore, devs)
        owners = {}
        errors = []

        def locate(i):
            try:
                owners[i] = loc.locate(Device(ids[i], ResourceTPUCore))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=locate, args=(i,)) for i in ids
        ]
        for t in threads:
            t.start()
        time.sleep(0.05)
        gate.set()  # release the stale List; misses now coalesce
        for t in threads:
            t.join(timeout=10)
        assert not errors
        assert {o.name for o in owners.values()} == {
            f"pod-{i}" for i in ids
        }
        assert client.lists <= 3, (
            f"{client.lists} Lists for 4 concurrent misses — the "
            "single-flight coalescing is broken"
        )
    finally:
        k.stop()
