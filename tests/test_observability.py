"""End-to-end allocation tracing: Allocate -> PreStartContainer -> GC
driven through the fake kubelet/apiserver/stub-operator stack, traces
retrieved over the REAL /debug/traces HTTP endpoint, and the trace id
propagated through the alloc-spec env file into a real runner step loop
whose flight-recorder JSONL carries the same id (the ISSUE 1 acceptance
flow, both sides of the correlation)."""

import json
import os
import urllib.error
import urllib.request

import pytest

from elastic_tpu_agent import tracing
from elastic_tpu_agent.common import (
    AnnotationAssumed,
    ResourceTPUCore,
    container_annotation,
)
from elastic_tpu_agent.metrics import AgentMetrics
from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, core_device_id
from elastic_tpu_agent.types import Device
from prometheus_client import CollectorRegistry

from fake_apiserver import make_pod
from test_e2e import Cluster, wait_until


@pytest.fixture()
def traced_cluster(tmp_path):
    """Fresh tracer + full Cluster + the unified HTTP endpoint. The
    metrics object is handed to the manager so the sampler exports into
    this registry and /debug/allocations is live."""
    prev = tracing.set_tracer(tracing.Tracer())
    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)  # ephemeral loopback port
    c = Cluster(tmp_path, metrics=metrics)
    c.start()
    c.metrics = metrics
    try:
        yield c
    finally:
        metrics.close()
        c.stop()
        tracing.set_tracer(prev)


def _traces(port, query=""):
    url = f"http://127.0.0.1:{port}/debug/traces{query}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())["traces"]


def test_allocation_trace_and_flight_recorder_correlate(
    traced_cluster, tmp_path, monkeypatch, capsys
):
    c = traced_cluster
    port = c.metrics.http_port

    # -- scheduler places the pod; kubelet drives Allocate + PreStart -----
    c.apiserver.upsert_pod(
        make_pod(
            "default", "traced", c.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): "1",
            },
            containers=[{"name": "jax"}],
        )
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", "traced") is not None
    )
    ids = [core_device_id(1, i) for i in range(100)]
    c.kubelet.kubelet_allocate_flow(
        CORE_ENDPOINT, "default", "traced", "jax", ResourceTPUCore, ids
    )
    dev_hash = Device(ids, ResourceTPUCore).hash

    # -- the agent side: one PreStart trace, >= 4 named spans, over HTTP --
    all_traces = _traces(port)
    assert any(t["name"] == "Allocate" for t in all_traces)
    pod_traces = _traces(port, "?pod=default/traced")
    prestarts = [t for t in pod_traces if t["name"] == "PreStartContainer"]
    assert len(prestarts) == 1
    trace = prestarts[0]
    span_names = {s["name"] for s in trace["spans"]}
    assert len(span_names) >= 4
    assert {
        "locator_locate", "pod_lookup", "materialize_nodes",
        "write_alloc_spec", "checkpoint",
    } <= span_names
    assert all(s["duration_ms"] >= 0 for s in trace["spans"])
    trace_id = trace["trace_id"]
    assert trace["attrs"]["pod"] == "default/traced"

    # the bind event carries the trace id for kubectl describe
    assert c.manager.events.flush()
    bound = [
        e for e in c.apiserver.core_events if e["reason"] == "TPUBound"
    ]
    assert bound and f"[trace {trace_id}]" in bound[0]["message"]

    # -- the spec env propagates the id to the hook-authored env file -----
    spec_path = os.path.join(str(c.tmp / "alloc"), f"{dev_hash}.json")
    with open(spec_path) as f:
        spec = json.load(f)
    assert spec["env"]["ELASTIC_TPU_TRACE_ID"] == trace_id

    # -- workload side: a real runner train loop, flight-recorder JSONL --
    env_file = tmp_path / "hook-env"
    env_file.write_text(
        f"ELASTIC_TPU_TRACE_ID={spec['env']['ELASTIC_TPU_TRACE_ID']}\n"
    )
    flight = tmp_path / "flight.jsonl"
    monkeypatch.setenv("ELASTIC_TPU_ENV_FILE", str(env_file))
    monkeypatch.setenv("ELASTIC_TPU_TRACE_ID", "pre-existing-must-lose")
    from elastic_tpu_agent.workloads import runner

    rc = runner.main([
        "--steps", "2", "--batch", "2", "--seq", "16",
        "--preset", "tiny", "--flight-recorder", str(flight),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["alloc_env"]["ELASTIC_TPU_TRACE_ID"] == trace_id
    assert report["flight_recorder"]["trace_id"] == trace_id
    records = [
        json.loads(line)
        for line in flight.read_text().splitlines() if line.strip()
    ]
    steps = [r for r in records if r["kind"] == "step"]
    assert len(steps) == 2
    assert all(r["trace_id"] == trace_id for r in steps)
    assert all(r["duration_ms"] > 0 for r in steps)
    assert all(r.get("tokens_per_s", 0) > 0 for r in steps)

    # -- GC closes the loop: reclaim is traced under the same pod ---------
    c.apiserver.delete_pod("default", "traced")
    c.kubelet.unassign_pod("default", "traced")
    assert wait_until(
        lambda: c.manager.storage.load("default", "traced") is None,
        timeout=15.0,
    )
    gc_traces = [
        t for t in _traces(port, "?pod=default/traced")
        if t["name"] == "gc_sweep"
    ]
    assert gc_traces, "the reclaiming GC sweep must be traced"
    assert gc_traces[0]["attrs"]["reclaimed"] >= 1
    reclaim_spans = [
        s for s in gc_traces[0]["spans"] if s["name"] == "reclaim_pod"
    ]
    assert reclaim_spans
    assert reclaim_spans[0]["attrs"]["pod"] == "default/traced"
    assert dev_hash in reclaim_spans[0]["attrs"]["hashes"]


def test_healthz_and_metrics_serve_alongside_traces(traced_cluster):
    port = traced_cluster.metrics.http_port
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10
    ) as resp:
        assert json.loads(resp.read())["status"] == "ok"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        body = resp.read()
    assert b"elastic_tpu_prestart_seconds" in body


def _get_json(port, path):
    url = f"http://127.0.0.1:{port}{path}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


def _bind_fractional_pod(c, pod_name, chip, units):
    c.apiserver.upsert_pod(
        make_pod(
            "default", pod_name, c.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): str(chip),
            },
            containers=[{"name": "jax"}],
        )
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", pod_name) is not None
    )
    ids = [core_device_id(chip, i) for i in range(units)]
    c.kubelet.kubelet_allocate_flow(
        CORE_ENDPOINT, "default", pod_name, "jax", ResourceTPUCore, ids
    )
    return Device(ids, ResourceTPUCore).hash


def test_debug_traces_rejects_bad_limit_with_400(traced_cluster):
    """?limit=abc must be a 400 with a JSON error, not an unhandled
    exception in the handler thread (which would surface as a dropped
    connection / 500)."""
    port = traced_cluster.metrics.http_port
    for bad in ("abc", "1.5", "1e3"):
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces?limit={bad}",
                timeout=10,
            )
        assert excinfo.value.code == 400
        body = json.loads(excinfo.value.read())
        assert "limit" in body["error"]
    # a good limit still works on the same (alive) server
    assert "traces" in _get_json(port, "/debug/traces?limit=5")


def test_debug_allocations_reports_granted_vs_used(traced_cluster):
    """The ISSUE 2 acceptance flow: a fractional pod's granted-vs-used
    core percent is served at /debug/allocations, a sustained overcommit
    increments the counter (visible at /metrics), and the per-pod gauges
    carry the same numbers."""
    c = traced_cluster
    port = c.metrics.http_port
    dev_hash = _bind_fractional_pod(c, "frac", chip=1, units=30)

    sampler = c.manager.sampler
    assert sampler is not None
    # chip 1 runs way above the pod's 30% grant, sustained
    c.manager.operator.set_utilization({1: 85.0}, hbm_used={1: 2 << 30})
    for _ in range(sampler.overcommit_sustain):
        sampler.sample_once()

    table = _get_json(port, "/debug/allocations")
    pods = {p["pod"]: p for p in table["pods"]}
    assert "default/frac" in pods
    pod = pods["default/frac"]
    assert pod["granted_core_percent"] == 30.0
    assert pod["used_core_percent"] == 85.0
    assert pod["overcommit"] is True
    assert pod["chips"] == [1]
    # the bind's trace id correlates the table row with /debug/traces
    traces = _traces(port, "?pod=default/frac")
    prestart = [t for t in traces if t["name"] == "PreStartContainer"][0]
    assert pod["last_trace_id"] == prestart["trace_id"]
    chips = {row["chip"]: row for row in table["chips"]}
    assert chips[1]["duty_cycle_percent"] == 85.0
    assert chips[1]["hbm_used_bytes"] == 2 << 30
    assert chips[1]["granted_core_percent"] == 30.0
    assert chips[1]["pods"] == ["default/frac"]
    assert chips[1]["healthy"] is True
    # locator introspection rides along
    assert ResourceTPUCore in table["locator"]

    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        body = resp.read().decode()
    assert "elastic_tpu_overcommit_detected_total 1.0" in body
    assert (
        'elastic_tpu_pod_core_granted_percent{pod="default/frac"} 30.0'
        in body
    )
    assert (
        'elastic_tpu_pod_core_used_percent{pod="default/frac"} 85.0'
        in body
    )

    # usage back under grant: the episode ends, the counter does NOT grow
    c.manager.operator.set_utilization({1: 10.0})
    sampler.sample_once()
    table = _get_json(port, "/debug/allocations")
    pod = {p["pod"]: p for p in table["pods"]}["default/frac"]
    assert pod["overcommit"] is False
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        assert b"elastic_tpu_overcommit_detected_total 1.0" in resp.read()

    # reclaim: the pod's series and table row go away
    c.apiserver.delete_pod("default", "frac")
    c.kubelet.unassign_pod("default", "frac")
    assert wait_until(
        lambda: c.manager.storage.load("default", "frac") is None,
        timeout=15.0,
    )
    sampler.sample_once()
    table = _get_json(port, "/debug/allocations")
    assert all(p["pod"] != "default/frac" for p in table["pods"])
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        assert b'pod="default/frac"' not in resp.read()
    assert dev_hash  # silence unused warning, hash asserted via traces


def test_debug_allocations_503_without_sampler():
    """An endpoint with no sampler attached (agent starting, sampling
    disabled) answers 503, not 500."""
    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            urllib.request.urlopen(
                f"http://127.0.0.1:{metrics.http_port}/debug/allocations",
                timeout=10,
            )
        assert excinfo.value.code == 503
        assert "sampler" in json.loads(excinfo.value.read())["error"]
    finally:
        metrics.close()


def test_bind_failure_trace_records_error(traced_cluster):
    """A PreStart against a pod the scheduler never assumed: the failed
    trace is kept, carries the error, and the TPUBindFailed event links
    to it."""
    c = traced_cluster
    port = c.metrics.http_port
    c.apiserver.upsert_pod(
        make_pod("default", "unassumed", c.node, annotations={},
                 containers=[{"name": "jax"}])
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", "unassumed") is not None
    )
    ids = [core_device_id(0, i) for i in range(10)]
    client = c.kubelet.plugin_client(CORE_ENDPOINT)
    client.allocate(ids)
    c.kubelet.assign("default", "unassumed", "jax", ResourceTPUCore, ids)
    with pytest.raises(Exception):
        client.pre_start_container(ids)
    failed = [
        t for t in _traces(port, "?pod=default/unassumed")
        if t["name"] == "PreStartContainer"
    ]
    assert failed and "not assumed" in failed[0]["error"]
    assert c.manager.events.flush()
    bind_failed = [
        e for e in c.apiserver.core_events
        if e["reason"] == "TPUBindFailed"
    ]
    assert bind_failed
    assert f"[trace {failed[0]['trace_id']}]" in bind_failed[0]["message"]


# -- /debug index + unknown-path contract (ISSUE 15) --------------------------


def _open_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return json.loads(resp.read())


def test_debug_index_lists_every_registered_route():
    """/debug answers the route index — and the index is the SAME dict
    the handler dispatches on, so a new endpoint that forgets to
    register itself fails this pin, not a 3am triage session."""
    from elastic_tpu_agent.metrics import DEBUG_ROUTES

    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        payload = _open_json(metrics.http_port, "/debug")
        assert payload["routes"] == DEBUG_ROUTES
        # every advertised route actually dispatches (503 while its
        # subsystem is unattached is fine; 404 means a stale index)
        for route in DEBUG_ROUTES:
            try:
                _open_json(metrics.http_port, route)
            except urllib.error.HTTPError as e:
                assert e.code != 404, f"{route} advertised but unknown"
    finally:
        metrics.close()


def test_unknown_debug_path_is_a_json_404_naming_the_routes():
    from elastic_tpu_agent.metrics import DEBUG_ROUTES

    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _open_json(metrics.http_port, "/debug/goodpoot")
        assert excinfo.value.code == 404
        body = json.loads(excinfo.value.read())
        assert "/debug/goodpoot" in body["error"]
        assert body["debug_routes"] == sorted(DEBUG_ROUTES)
    finally:
        metrics.close()


def test_debug_goodput_endpoint_503_then_serves_the_ledger(tmp_path):
    from elastic_tpu_agent import timeline as tl
    from elastic_tpu_agent.common import ManualClock
    from elastic_tpu_agent.goodput import GoodputLedger
    from elastic_tpu_agent.storage import Storage

    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _open_json(metrics.http_port, "/debug/goodput")
        assert excinfo.value.code == 503
        with Storage(str(tmp_path / "meta.db")) as store:
            clk = ManualClock()
            t = tl.Timeline(store, node_name="n0", cap=64, clock=clk)
            t.emit(tl.KIND_BIND_COMMIT, keys={"pod": "d/a"})
            t.emit(tl.KIND_BIND_COMMIT, keys={"pod": "d/b"})
            clk.advance(4.0)
            t.emit(tl.KIND_THROTTLE, keys={"pod": "d/b"},
                   action="throttle")
            clk.advance(1.0)
            ledger = GoodputLedger(
                store, node_name="n0", metrics=metrics, clock=clk,
            )
            ledger.tick()
            metrics.attach_goodput(ledger)
            payload = _open_json(metrics.http_port, "/debug/goodput")
            assert set(payload["pods"]) == {"d/a", "d/b"}
            assert payload["conservation_problems"] == []
            assert payload["downtime_by_cause"] == {"qos_throttle": 1.0}
            only_b = _open_json(metrics.http_port, "/debug/goodput?pod=b")
            assert set(only_b["pods"]) == {"d/b"}
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                _open_json(
                    metrics.http_port, "/debug/goodput?since=yesterday"
                )
            assert excinfo.value.code == 400
            # the tick exported the closed-vocabulary downtime gauge
            scrape = urllib.request.urlopen(
                f"http://127.0.0.1:{metrics.http_port}/metrics", timeout=10
            ).read().decode()
            assert (
                'elastic_tpu_downtime_seconds_total{cause="qos_throttle"}'
                " 1.0" in scrape
            )
            assert 'elastic_tpu_goodput_ratio{pod="d/a"} 1.0' in scrape
    finally:
        metrics.close()


# -- Prometheus exposition-format conformance (promtool-style, in-repo) -------


def test_fully_wired_scrape_is_exposition_conformant(tmp_path):
    """Scrape an AgentMetrics with series driven into every labeled
    family (including label values that NEED escaping) and lint the
    payload: no duplicate series, HELP/TYPE on every family, label
    escaping correct."""
    from elastic_tpu_agent.metrics import lint_exposition

    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        metrics.gc_reclaimed.inc()
        metrics.allocate_latency.observe(0.01)
        metrics.chip_duty_cycle.labels(chip="0").set(50.0)
        metrics.pod_core_granted.set(50.0, pod='default/we"ird\\pod\n')
        metrics.pod_core_used.set(25.0, pod='default/we"ird\\pod\n')
        metrics.goodput_ratio.set(0.75, pod="default/train")
        metrics.workload_tokens_per_s.set(123.4, pod="default/train")
        for cause in ("maintenance_drain", "qos_throttle"):
            metrics.downtime_seconds.labels(cause=cause).set(1.5)
        metrics.drains_total.labels(
            trigger="maintenance", outcome="drained_acked"
        ).inc()
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.http_port}/metrics", timeout=10
        ).read().decode()
        problems = lint_exposition(scrape)
        assert problems == [], problems
    finally:
        metrics.close()


def test_lint_exposition_catches_seeded_breakage():
    from elastic_tpu_agent.metrics import lint_exposition

    # a known-good family first: the lint is not just rejecting all
    good = (
        "# HELP x_total things\n"
        "# TYPE x_total counter\n"
        'x_total{pod="a"} 1\n'
    )
    assert lint_exposition(good) == []
    assert any(
        "duplicate series" in p
        for p in lint_exposition(good + 'x_total{pod="a"} 2\n')
    )
    assert any(
        "no HELP/TYPE" in p
        for p in lint_exposition("orphan_metric 1\n")
    )
    assert any(
        "has no HELP" in p
        for p in lint_exposition(
            "# TYPE y gauge\ny 1\n"
        )
    )
    assert any(
        "illegal escape" in p
        for p in lint_exposition(
            "# HELP z t\n# TYPE z gauge\n" 'z{pod="a\\d"} 1\n'
        )
    )
    assert any(
        "not a number" in p
        for p in lint_exposition(
            "# HELP w t\n# TYPE w gauge\nw banana\n"
        )
    )
    assert any(
        "duplicate TYPE" in p
        for p in lint_exposition(
            "# TYPE v gauge\n# TYPE v gauge\n# HELP v t\nv 1\n"
        )
    )


# -- /debug/latency + /debug/profile + scrape self-metrics (ISSUE 16) ---------


def test_debug_latency_endpoint_503_then_serves_phase_breakdown():
    """503 before attach; after attach the payload carries the bind
    phase breakdown whose sums + residual equal the measured totals,
    with a resolvable exemplar for every populated phase, the
    detection-lag block, and the effective slow-span threshold."""
    import time as _time

    from elastic_tpu_agent.common import ManualClock
    from elastic_tpu_agent.latency import (
        PHASE_UNATTRIBUTED,
        BindLatencyObservatory,
        DetectionLagTracker,
    )

    prev = tracing.set_tracer(tracing.Tracer())
    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    tr = tracing.get_tracer()
    obs = BindLatencyObservatory(metrics=metrics, node_name="n0")
    tr.add_listener(obs.observe_trace)
    try:
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _open_json(metrics.http_port, "/debug/latency")
        assert excinfo.value.code == 503

        clk = ManualClock()
        lag = DetectionLagTracker(metrics=metrics, clock=clk)
        lag.mark("maintenance", key="n0")
        clk.advance(0.7)
        lag.repaired("drain", "maintenance", key="n0")
        for _ in range(3):
            with tr.trace("PreStartContainer", node="n0", pod="d/p"):
                with tr.span("bind_lock_wait"):
                    _time.sleep(0.002)
                with tr.span("locator_locate"):
                    _time.sleep(0.003)
        metrics.attach_latency(obs, lag)

        payload = _open_json(metrics.http_port, "/debug/latency")
        bind = payload["bind"]
        assert bind["observed_total"] == 3
        # phase sums + residual == measured total, per slowest entry
        for entry in bind["slowest"]:
            attributed = sum(entry["phases_ms"].values())
            assert (
                abs(attributed + entry["residual_ms"] - entry["total_ms"])
                < 0.01
            )
        # every populated phase resolves to an exemplar trace id that
        # /debug/traces can actually serve
        for phase, block in bind["phases"].items():
            if not block["count"]:
                continue
            assert block["exemplars"], phase
            ex = next(iter(block["exemplars"].values()))
            hits = _open_json(
                metrics.http_port, f"/debug/traces?trace={ex['trace_id']}"
            )["traces"]
            assert hits and hits[0]["trace_id"] == ex["trace_id"]
        assert bind["phases"][PHASE_UNATTRIBUTED]["share_of_total"] is not None
        assert payload["detection_lag"]["classes"]["maintenance"]["count"] == 1
        assert payload["slow_span_ms"] == pytest.approx(
            tr.slow_span_s * 1000
        )
        # ?top= bounds the slowest table; bad values are a 400
        small = _open_json(metrics.http_port, "/debug/latency?top=1")
        assert len(small["bind"]["slowest"]) == 1
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _open_json(metrics.http_port, "/debug/latency?top=banana")
        assert excinfo.value.code == 400
    finally:
        tr.remove_listener(obs.observe_trace)
        metrics.close()
        tracing.set_tracer(prev)


def test_debug_profile_endpoint_503_then_serves_stacks():
    from elastic_tpu_agent.profiler import SamplingProfiler

    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        with pytest.raises(urllib.error.HTTPError) as excinfo:
            _open_json(metrics.http_port, "/debug/profile")
        assert excinfo.value.code == 503

        prof = SamplingProfiler(hz=10.0)
        prof.sample_once()  # the HTTP server thread is always sampleable
        metrics.attach_profiler(prof)
        payload = _open_json(metrics.http_port, "/debug/profile")
        assert payload["enabled"] is True
        assert payload["samples_total"] == 1
        assert payload["overhead_ratio"] >= 0.0
        assert isinstance(payload["top"], list)
        # the attach wires the overhead + sample gauges into the scrape
        scrape = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.http_port}/metrics", timeout=10
        ).read().decode()
        assert "elastic_tpu_profiler_overhead_ratio" in scrape
        assert "elastic_tpu_profiler_samples_total 1.0" in scrape
    finally:
        metrics.close()


def test_scrape_self_metrics_count_and_time_every_request():
    """Every HTTP request — scrape, debug route, scanner noise — lands
    in elastic_tpu_scrape_requests_total under a bounded path label
    ('other' for unknown paths) and in the scrape-duration histogram."""
    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)
    try:
        def scrape_text():
            return urllib.request.urlopen(
                f"http://127.0.0.1:{metrics.http_port}/metrics", timeout=10
            ).read().decode()

        scrape_text()
        _open_json(metrics.http_port, "/debug")
        for path in ("/debug/goodpoot", "/totally/unknown"):
            with pytest.raises(urllib.error.HTTPError):
                _open_json(metrics.http_port, path)
        text = scrape_text()
        assert 'elastic_tpu_scrape_requests_total{path="/metrics"}' in text
        assert 'elastic_tpu_scrape_requests_total{path="/debug"} 1.0' in text
        # unknown paths collapse into 'other' — a scanner cannot mint
        # unbounded label values
        assert 'elastic_tpu_scrape_requests_total{path="other"} 2.0' in text
        assert 'path="/debug/goodpoot"' not in text
        assert "elastic_tpu_scrape_duration_seconds_count" in text
        # and the self-metrics themselves stay exposition-conformant
        from elastic_tpu_agent.metrics import lint_exposition

        assert lint_exposition(text) == []
    finally:
        metrics.close()
