"""End-to-end allocation tracing: Allocate -> PreStartContainer -> GC
driven through the fake kubelet/apiserver/stub-operator stack, traces
retrieved over the REAL /debug/traces HTTP endpoint, and the trace id
propagated through the alloc-spec env file into a real runner step loop
whose flight-recorder JSONL carries the same id (the ISSUE 1 acceptance
flow, both sides of the correlation)."""

import json
import os
import urllib.request

import pytest

from elastic_tpu_agent import tracing
from elastic_tpu_agent.common import (
    AnnotationAssumed,
    ResourceTPUCore,
    container_annotation,
)
from elastic_tpu_agent.metrics import AgentMetrics
from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, core_device_id
from elastic_tpu_agent.types import Device
from prometheus_client import CollectorRegistry

from fake_apiserver import make_pod
from test_e2e import Cluster, wait_until


@pytest.fixture()
def traced_cluster(tmp_path):
    """Fresh tracer + full Cluster + the unified HTTP endpoint."""
    prev = tracing.set_tracer(tracing.Tracer())
    c = Cluster(tmp_path)
    c.start()
    metrics = AgentMetrics(registry=CollectorRegistry())
    metrics.serve(0)  # ephemeral loopback port
    c.metrics = metrics
    try:
        yield c
    finally:
        metrics.close()
        c.stop()
        tracing.set_tracer(prev)


def _traces(port, query=""):
    url = f"http://127.0.0.1:{port}/debug/traces{query}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())["traces"]


def test_allocation_trace_and_flight_recorder_correlate(
    traced_cluster, tmp_path, monkeypatch, capsys
):
    c = traced_cluster
    port = c.metrics.http_port

    # -- scheduler places the pod; kubelet drives Allocate + PreStart -----
    c.apiserver.upsert_pod(
        make_pod(
            "default", "traced", c.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): "1",
            },
            containers=[{"name": "jax"}],
        )
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", "traced") is not None
    )
    ids = [core_device_id(1, i) for i in range(100)]
    c.kubelet.kubelet_allocate_flow(
        CORE_ENDPOINT, "default", "traced", "jax", ResourceTPUCore, ids
    )
    dev_hash = Device(ids, ResourceTPUCore).hash

    # -- the agent side: one PreStart trace, >= 4 named spans, over HTTP --
    all_traces = _traces(port)
    assert any(t["name"] == "Allocate" for t in all_traces)
    pod_traces = _traces(port, "?pod=default/traced")
    prestarts = [t for t in pod_traces if t["name"] == "PreStartContainer"]
    assert len(prestarts) == 1
    trace = prestarts[0]
    span_names = {s["name"] for s in trace["spans"]}
    assert len(span_names) >= 4
    assert {
        "locator_locate", "pod_lookup", "materialize_nodes",
        "write_alloc_spec", "checkpoint",
    } <= span_names
    assert all(s["duration_ms"] >= 0 for s in trace["spans"])
    trace_id = trace["trace_id"]
    assert trace["attrs"]["pod"] == "default/traced"

    # the bind event carries the trace id for kubectl describe
    assert c.manager.events.flush()
    bound = [
        e for e in c.apiserver.core_events if e["reason"] == "TPUBound"
    ]
    assert bound and f"[trace {trace_id}]" in bound[0]["message"]

    # -- the spec env propagates the id to the hook-authored env file -----
    spec_path = os.path.join(str(c.tmp / "alloc"), f"{dev_hash}.json")
    with open(spec_path) as f:
        spec = json.load(f)
    assert spec["env"]["ELASTIC_TPU_TRACE_ID"] == trace_id

    # -- workload side: a real runner train loop, flight-recorder JSONL --
    env_file = tmp_path / "hook-env"
    env_file.write_text(
        f"ELASTIC_TPU_TRACE_ID={spec['env']['ELASTIC_TPU_TRACE_ID']}\n"
    )
    flight = tmp_path / "flight.jsonl"
    monkeypatch.setenv("ELASTIC_TPU_ENV_FILE", str(env_file))
    monkeypatch.setenv("ELASTIC_TPU_TRACE_ID", "pre-existing-must-lose")
    from elastic_tpu_agent.workloads import runner

    rc = runner.main([
        "--steps", "2", "--batch", "2", "--seq", "16",
        "--preset", "tiny", "--flight-recorder", str(flight),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["alloc_env"]["ELASTIC_TPU_TRACE_ID"] == trace_id
    assert report["flight_recorder"]["trace_id"] == trace_id
    records = [
        json.loads(line)
        for line in flight.read_text().splitlines() if line.strip()
    ]
    steps = [r for r in records if r["kind"] == "step"]
    assert len(steps) == 2
    assert all(r["trace_id"] == trace_id for r in steps)
    assert all(r["duration_ms"] > 0 for r in steps)
    assert all(r.get("tokens_per_s", 0) > 0 for r in steps)

    # -- GC closes the loop: reclaim is traced under the same pod ---------
    c.apiserver.delete_pod("default", "traced")
    c.kubelet.unassign_pod("default", "traced")
    assert wait_until(
        lambda: c.manager.storage.load("default", "traced") is None,
        timeout=15.0,
    )
    gc_traces = [
        t for t in _traces(port, "?pod=default/traced")
        if t["name"] == "gc_sweep"
    ]
    assert gc_traces, "the reclaiming GC sweep must be traced"
    assert gc_traces[0]["attrs"]["reclaimed"] >= 1
    reclaim_spans = [
        s for s in gc_traces[0]["spans"] if s["name"] == "reclaim_pod"
    ]
    assert reclaim_spans
    assert reclaim_spans[0]["attrs"]["pod"] == "default/traced"
    assert dev_hash in reclaim_spans[0]["attrs"]["hashes"]


def test_healthz_and_metrics_serve_alongside_traces(traced_cluster):
    port = traced_cluster.metrics.http_port
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/healthz", timeout=10
    ) as resp:
        assert json.loads(resp.read())["status"] == "ok"
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as resp:
        body = resp.read()
    assert b"elastic_tpu_prestart_seconds" in body


def test_bind_failure_trace_records_error(traced_cluster):
    """A PreStart against a pod the scheduler never assumed: the failed
    trace is kept, carries the error, and the TPUBindFailed event links
    to it."""
    c = traced_cluster
    port = c.metrics.http_port
    c.apiserver.upsert_pod(
        make_pod("default", "unassumed", c.node, annotations={},
                 containers=[{"name": "jax"}])
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", "unassumed") is not None
    )
    ids = [core_device_id(0, i) for i in range(10)]
    client = c.kubelet.plugin_client(CORE_ENDPOINT)
    client.allocate(ids)
    c.kubelet.assign("default", "unassumed", "jax", ResourceTPUCore, ids)
    with pytest.raises(Exception):
        client.pre_start_container(ids)
    failed = [
        t for t in _traces(port, "?pod=default/unassumed")
        if t["name"] == "PreStartContainer"
    ]
    assert failed and "not assumed" in failed[0]["error"]
    assert c.manager.events.flush()
    bind_failed = [
        e for e in c.apiserver.core_events
        if e["reason"] == "TPUBindFailed"
    ]
    assert bind_failed
    assert f"[trace {failed[0]['trace_id']}]" in bind_failed[0]["message"]
