"""LoRA adapters (workloads/lora.py): zero-init identity, frozen-base
training that actually learns, adapter-only optimizer state, and
merge-then-serve."""

import jax
import jax.numpy as jnp
import numpy as np

from elastic_tpu_agent.workloads.generate import generate
from elastic_tpu_agent.workloads.lora import (
    apply_lora,
    init_lora_params,
    lora_param_count,
    make_lora_train_step,
    merge_lora,
)
from elastic_tpu_agent.workloads.transformer import (
    ModelConfig,
    forward,
    init_params,
)

BASE = dict(
    vocab=97, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64,
    dtype=jnp.float32, attn="reference",
)


def test_zero_init_is_identity():
    """B = 0 at init: the adapted model IS the base model."""
    cfg = ModelConfig(**BASE)
    params = init_params(cfg, jax.random.key(0))
    lora = init_lora_params(params, jax.random.key(1), rank=4)
    eff = apply_lora(params, lora)
    toks = jax.random.randint(jax.random.key(2), (2, 10), 0, cfg.vocab)
    np.testing.assert_allclose(
        np.asarray(forward(eff, toks, cfg)),
        np.asarray(forward(params, toks, cfg)),
        atol=1e-6, rtol=1e-6,
    )


def test_adapter_count_is_small_and_targets_respected():
    cfg = ModelConfig(**BASE, n_kv_heads=2)  # GQA: wq+wkv, no wqkv
    params = init_params(cfg, jax.random.key(0))
    lora = init_lora_params(params, jax.random.key(1), rank=4)
    base_count = sum(
        p.size for p in jax.tree_util.tree_leaves(params)
    )
    assert lora_param_count(lora) * 5 < base_count
    for entry in lora["layers"]:
        assert set(entry) == {"wq", "wkv", "wo"}
        for ab in entry.values():
            assert ab["a"].shape[1] == 4 and ab["b"].shape[0] == 4


def _pretrain(cfg, params, stream, steps=150, lr=3e-3):
    import optax

    optimizer = optax.adam(lr)
    opt = optimizer.init(params)

    def loss_fn(p, toks):
        logits = forward(p, toks[:, :-1], cfg).astype(jnp.float32)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(
                logits, toks[:, 1:]
            )
        )

    @jax.jit
    def train(p, o, toks):
        loss, g = jax.value_and_grad(loss_fn)(p, toks)
        upd, o = optimizer.update(g, o)
        return optax.apply_updates(p, upd), o, loss

    batch = jnp.stack([
        jax.lax.dynamic_slice(stream, (i * 4,), (33,)) for i in range(8)
    ])
    for _ in range(steps):
        params, opt, loss = train(params, opt, batch)
    return params, batch, float(loss)


def test_lora_adapts_pretrained_base_which_stays_frozen():
    """The real use case: pretrain the base on pattern A, then teach it
    pattern B through adapters ONLY. The base pytree stays bitwise
    frozen, the adapted model generates B, and the MERGED tree serves
    through the standard decode path."""
    cfg = ModelConfig(**BASE)
    pat_a = jnp.array([5, 17, 42, 9], jnp.int32)
    # B permutes A's tokens: re-mapping transitions is squarely inside
    # the adapted weights' reach, while tokens the base never trained
    # would demand new embedding/lm_head geometry LoRA (correctly)
    # cannot provide — adapters target attention/MLP, not the vocab
    pat_b = jnp.array([42, 5, 9, 17], jnp.int32)
    stream_a = jnp.tile(pat_a, 64)
    stream_b = jnp.tile(pat_b, 64)

    params = init_params(cfg, jax.random.key(0))
    params, _, pre_loss = _pretrain(cfg, params, stream_a)
    assert pre_loss < 0.1, pre_loss
    frozen = jax.tree_util.tree_map(lambda x: np.asarray(x), params)

    step, init = make_lora_train_step(
        cfg, rank=8, learning_rate=3e-3,
        targets=("wqkv", "wo", "w1", "w2"),
    )
    lora, opt = init(params, jax.random.key(1))
    batch_b = jnp.stack([
        jax.lax.dynamic_slice(stream_b, (i * 4,), (33,))
        for i in range(8)
    ])
    for _ in range(200):
        lora, opt, loss = step(params, lora, opt, batch_b)
    assert float(loss) < 0.3, float(loss)

    # base params never moved
    for got, want in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(frozen),
    ):
        np.testing.assert_array_equal(np.asarray(got), want)

    # merged adapters serve pattern B through the standard decode path
    merged = merge_lora(params, lora)
    out = generate(merged, stream_b[None, :4], cfg, max_new_tokens=8)
    np.testing.assert_array_equal(
        np.asarray(out[0]), np.asarray(stream_b[:12])
    )
    # the untouched base still serves pattern A
    out_a = generate(params, stream_a[None, :4], cfg, max_new_tokens=8)
    np.testing.assert_array_equal(
        np.asarray(out_a[0]), np.asarray(stream_a[:12])
    )


def test_optimizer_state_covers_adapters_only():
    cfg = ModelConfig(**BASE)
    params = init_params(cfg, jax.random.key(0))
    step, init = make_lora_train_step(cfg, rank=2)
    lora, opt = init(params)
    opt_bytes = sum(
        p.size * p.dtype.itemsize
        for p in jax.tree_util.tree_leaves(opt)
        if hasattr(p, "dtype")
    )
    base_f32_bytes = sum(
        p.size * 4 for p in jax.tree_util.tree_leaves(params)
    )
    # adam on adapters only: far below even ONE f32 copy of the base
    assert opt_bytes * 3 < base_f32_bytes
