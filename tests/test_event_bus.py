"""Event-bus semantics + the no-gap poll fallback (events.py design
contract, pinned clause by clause).

The bus is the agent's poll-to-push seam: sources publish, loops run
targeted passes, and the jittered periodic sweep is demoted to a
stretched safety net. That only holds if the bus itself can never hurt
the hot path — so these tests pin the load-bearing invariants:

- publishers never block and never see a subscriber failure (bounded
  queues drop-oldest with counted drops; callback exceptions are
  isolated),
- ordering is deterministic under ManualClock (global monotone seq,
  injected-clock timestamps),
- degraded mode is loud (BUS_WAKE broadcast on aggregate transitions
  only) and the watch-stream-dies-during-brownout path collapses loops
  back to their base sweep period with NO repair gap,
- poll-only mode (bus disabled) converges to the same repaired end
  state as event mode — the bus is an accelerator, never a
  correctness dependency.
"""

import threading
import time

import pytest

from elastic_tpu_agent import events
from elastic_tpu_agent.common import (
    AnnotationAssumed,
    ManualClock,
    ResourceTPUCore,
    container_annotation,
)
from elastic_tpu_agent.plugins.tpushare import core_device_id
from elastic_tpu_agent.types import Device

from test_e2e import Cluster, wait_until
from fake_apiserver import make_pod


# -- bounded queues: overflow drops oldest, counted, never blocks -------------


def test_overflow_drops_oldest_counted_never_blocks():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("slow", (events.POD_DELTA,), cap=4)
    for i in range(10):
        # publish returns the fan-out count and NEVER raises/blocks,
        # full mailbox or not
        assert bus.publish(events.POD_DELTA, "added", f"ns/p{i}") == 1
    assert sub.drops == 6
    drained = sub.drain()
    # the mailbox holds the NEWEST cap events (drop-oldest semantics:
    # a slow consumer keeps the freshest picture plus a drop count)
    assert [e.key for e in drained] == ["ns/p6", "ns/p7", "ns/p8", "ns/p9"]
    assert sub.pending() == 0
    stats = bus.stats()
    assert stats["drops_total"] == 6
    assert stats["published_by_topic"][events.POD_DELTA] == 10


def test_queue_cap_floor_is_one():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("tiny", (events.STORE_BIND,), cap=0)
    bus.publish(events.STORE_BIND, "save", "a")
    bus.publish(events.STORE_BIND, "save", "b")
    assert sub.cap == 1
    assert [e.key for e in sub.drain()] == ["b"]
    assert sub.drops == 1


# -- callback mode: subscriber exceptions never reach the publisher -----------


def test_callback_exception_isolated_from_publisher():
    bus = events.EventBus(clock=ManualClock())
    seen = []

    def boom(event):
        raise RuntimeError("subscriber bug")

    bad = bus.subscribe("bad", (events.POD_DELTA,), callback=boom)
    good = bus.subscribe("good", (events.POD_DELTA,),
                         callback=lambda e: seen.append(e.key))
    # the crashing callback is counted, the publisher is untouched and
    # the OTHER subscriber still gets every event
    assert bus.publish(events.POD_DELTA, "added", "ns/x") == 2
    assert bus.publish(events.POD_DELTA, "deleted", "ns/x") == 2
    assert bad.callback_errors == 2
    assert good.callback_errors == 0
    assert seen == ["ns/x", "ns/x"]
    assert bad.stats()["mode"] == "callback"


# -- ManualClock determinism: monotone seq, injected timestamps ---------------


def test_manualclock_deterministic_ordering():
    clock = ManualClock(start=100.0)
    bus = events.EventBus(clock=clock)
    sub = bus.subscribe("all", events.ALL_TOPICS)
    bus.publish(events.POD_DELTA, "added", "a")
    clock.advance(1.5)
    bus.publish(events.STORE_BIND, "save", "b")
    clock.advance(0.5)
    bus.publish(events.ASSIGNMENT_DELTA, "removed", "c")
    drained = sub.drain()
    assert [e.seq for e in drained] == [1, 2, 3]
    assert [e.ts for e in drained] == [100.0, 101.5, 102.0]
    assert [(e.topic, e.kind, e.key) for e in drained] == [
        (events.POD_DELTA, "added", "a"),
        (events.STORE_BIND, "save", "b"),
        (events.ASSIGNMENT_DELTA, "removed", "c"),
    ]


def test_unknown_topic_rejected():
    bus = events.EventBus(clock=ManualClock())
    with pytest.raises(ValueError):
        bus.subscribe("typo", ("pod.deltas",))


def test_topic_filter_and_unsubscribe():
    bus = events.EventBus(clock=ManualClock())
    binds = bus.subscribe("binds", (events.STORE_BIND,))
    pods = bus.subscribe("pods", (events.POD_DELTA,))
    bus.publish(events.STORE_BIND, "save", "x")
    assert binds.pending() == 1 and pods.pending() == 0
    binds.close()
    assert bus.publish(events.STORE_BIND, "save", "y") == 0
    assert binds.pending() == 1  # nothing delivered after close
    assert len(bus.stats()["subscribers"]) == 1


# -- degraded mode: BUS_WAKE broadcast on AGGREGATE transitions only ----------


def test_bus_wake_broadcast_on_aggregate_degraded_transitions():
    bus = events.EventBus(clock=ManualClock())
    # disjoint topic filters: BUS_WAKE must reach BOTH regardless
    a = bus.subscribe("a", (events.POD_DELTA,))
    b = bus.subscribe("b", (events.STORE_BIND,))
    assert bus.healthy()

    bus.set_degraded("sitter-watch", True)
    assert not bus.healthy()
    assert bus.degraded_sources() == ["sitter-watch"]
    for sub in (a, b):
        (wake,) = sub.drain()
        assert (wake.topic, wake.kind, wake.key) == (
            events.BUS_WAKE, "degraded", "sitter-watch")

    # a SECOND source degrading is not a healthy->degraded transition:
    # no extra broadcast (loops already collapsed their periods)
    bus.set_degraded("kubelet-list", True)
    assert a.pending() == 0 and b.pending() == 0

    # partial recovery: still degraded in aggregate, still no broadcast
    bus.set_degraded("sitter-watch", False)
    assert not bus.healthy()
    assert a.pending() == 0 and b.pending() == 0

    # LAST source healing is the recovered transition: broadcast again
    bus.set_degraded("kubelet-list", False)
    assert bus.healthy()
    for sub in (a, b):
        (wake,) = sub.drain()
        assert (wake.kind, wake.key) == ("recovered", "kubelet-list")


def test_set_degraded_idempotent():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.POD_DELTA,))
    bus.set_degraded("src", True)
    bus.set_degraded("src", True)  # repeat: no transition, no wake
    assert len(sub.drain()) == 1
    bus.set_degraded("src", False)
    bus.set_degraded("src", False)
    assert len(sub.drain()) == 1


# -- chaos seam: suppress() swallows counted publishes ------------------------


def test_suppress_seam_swallows_counted_publishes():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.STORE_BIND, events.POD_DELTA))
    bus.suppress(events.STORE_BIND, count=2)
    assert bus.publish(events.STORE_BIND, "delete", "a") == 0
    assert bus.publish(events.STORE_BIND, "delete", "b") == 0
    # other topics unaffected while the suppression is armed
    assert bus.publish(events.POD_DELTA, "added", "c") == 1
    # armed count exhausted: third bind publish flows again
    assert bus.publish(events.STORE_BIND, "save", "d") == 1
    assert bus.suppressed_total == 2
    assert [e.key for e in sub.drain()] == ["c", "d"]
    assert bus.stats()["suppressed_total"] == 2


# -- wait_trigger: stop / event / poll --------------------------------------


def test_wait_trigger_returns_poll_on_timeout():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.POD_DELTA,))
    t0 = time.monotonic()
    assert sub.wait_trigger(threading.Event(), 0.05) == "poll"
    assert time.monotonic() - t0 < 2.0


def test_wait_trigger_fires_immediately_on_pending_events():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.POD_DELTA,))
    bus.publish(events.POD_DELTA, "added", "x")
    t0 = time.monotonic()
    # a LONG timeout must not matter: undrained events fire at once
    assert sub.wait_trigger(threading.Event(), 30.0) == "event"
    assert time.monotonic() - t0 < 1.0
    sub.drain()


def test_wait_trigger_honors_stop():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.POD_DELTA,))
    stop = threading.Event()
    stop.set()
    assert sub.wait_trigger(stop, 30.0) == "stop"


def test_wait_trigger_wakes_on_concurrent_publish():
    bus = events.EventBus(clock=ManualClock())
    sub = bus.subscribe("s", (events.STORE_BIND,))
    result = []
    t = threading.Thread(
        target=lambda: result.append(
            sub.wait_trigger(threading.Event(), 10.0))
    )
    t.start()
    time.sleep(0.05)
    bus.publish(events.STORE_BIND, "save", "x")
    t.join(timeout=5.0)
    assert result == ["event"]


# -- integration: poll-only fallback equivalence ------------------------------

POD = "event-pod"
CHIPS = [core_device_id(1, 0), core_device_id(1, 1)]


def _bind_pod(c, pod_name=POD, chips="2"):
    c.apiserver.upsert_pod(
        make_pod(
            "default", pod_name, c.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): chips,
            },
            containers=[{"name": "jax"}],
        )
    )
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", pod_name) is not None
    )
    c.kubelet.assign("default", pod_name, "jax", ResourceTPUCore, CHIPS)
    c.manager.plugin.core._bind(Device(CHIPS, ResourceTPUCore))
    assert c.manager.storage.load("default", pod_name) is not None


@pytest.mark.parametrize("enable_bus", [True, False])
def test_lost_record_repaired_in_event_and_poll_only_modes(
    tmp_path, enable_bus
):
    """The bus is an accelerator, never a correctness dependency: a
    deleted store record (kubelet assignment surviving) is replayed to
    the same repaired state whether the bus is on or off (poll-only
    fallback mode, the chaos matrix's second leg)."""
    c = Cluster(
        tmp_path,
        enable_event_bus=enable_bus,
        reconcile_period_s=0.4,
        event_safety_net_factor=1.0,
    )
    c.start()
    try:
        assert (c.manager.bus is not None) == enable_bus
        _bind_pod(c)
        c.manager.storage.delete("default", POD)
        assert wait_until(
            lambda: c.manager.storage.load("default", POD) is not None,
            timeout=15.0,
        ), f"lost record never replayed (enable_bus={enable_bus})"
        repaired = c.manager.storage.load("default", POD)
        device = repaired.device_of("jax", ResourceTPUCore)
        assert device is not None
        assert sorted(device.ids) == sorted(CHIPS)
    finally:
        c.stop()


# -- pinned regression: watch dies during brownout -> no repair gap -----------


def test_brownout_watch_death_falls_back_to_sweep_no_gap_seed_20260807(
    tmp_path,
):
    """Watch stream dies during an apiserver brownout: the sitter flips
    the bus degraded (BUS_WAKE broadcast), every loop collapses back to
    its base sweep period, and a lost store record is STILL repaired
    promptly — far inside the stretched safety-net period the loops
    were using while healthy. Seeded brownout: same seed, same failure
    sequence."""
    c = Cluster(
        tmp_path,
        reconcile_period_s=0.4,
        # stretched sweep would be 20s: a repair landing in a few
        # seconds proves the loop fell back to its 0.4s base period
        event_safety_net_factor=50.0,
    )
    # short watch windows so the brownout kills the stream quickly
    c.manager.sitter._relist_s = 1.0
    c.start()
    try:
        assert c.manager.bus is not None
        _bind_pod(c)
        assert wait_until(c.manager.bus.healthy, timeout=10.0)

        c.apiserver.set_brownout(error_rate=1.0, seed=20260807)
        assert wait_until(
            lambda: not c.manager.bus.healthy(), timeout=20.0
        ), "sitter never reported its dead watch stream"
        assert "sitter-watch" in c.manager.bus.degraded_sources()

        # mid-brownout repair: pod deltas are NOT flowing, so only the
        # (collapsed) periodic sweep can catch this
        t0 = time.monotonic()
        c.manager.storage.delete("default", POD)
        assert wait_until(
            lambda: c.manager.storage.load("default", POD) is not None,
            timeout=10.0,
        ), "no repair while degraded: the poll fallback has a gap"
        took = time.monotonic() - t0
        # stretched period is 20s; base-period two-pass repair is ~1s
        assert took < 8.0, (
            f"repair took {took:.1f}s mid-brownout -- loop still "
            "sleeping its stretched safety-net period"
        )

        c.apiserver.clear_brownout()
        assert wait_until(c.manager.bus.healthy, timeout=20.0), (
            "bus never recovered after the brownout cleared"
        )
    finally:
        c.apiserver.clear_brownout()
        c.stop()
