"""POSITIVE multi-process slice formation (BASELINE config 5): two
real runner subprocesses, driven by the same agent-style env contract
(TPU_WORKER_ID / TPU_WORKER_HOSTNAMES), form an actual
jax.distributed slice over CPU, build a global dp=2 mesh, and run
training steps — both processes must agree on the loss, because dp
averages gradients over the WHOLE global batch.

Complements the negative test (tests/test_fullchain.py's
unreachable-coordinator path) and the env-consistency multihost
tests: here the slice genuinely forms and steps."""

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_worker(worker_id: int, port: int, extra=()):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # one local CPU device per process -> global mesh has 2
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO,
        # the agent-injected slice contract
        "TPU_WORKER_ID": str(worker_id),
        "TPU_WORKER_HOSTNAMES": "localhost,localhost",
        "ELASTIC_TPU_COORD_PORT": str(port),
        # a real agent env file would OVERRIDE the slice contract
        # above (load_alloc_env is authoritative by design) — point
        # the runner at a nonexistent file like every other
        # runner-subprocess test does
        "ELASTIC_TPU_ENV_FILE": "/nonexistent-alloc-env",
    }
    return subprocess.Popen(
        [
            sys.executable, "-m", "elastic_tpu_agent.workloads.runner",
            "--preset", "tiny", "--steps", "3", "--batch", "4",
            "--seq", "32", "--dp", "2", "--tp", "1", *extra,
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )


def _reap(*procs):
    """A failed peer must not orphan the other worker at the
    distributed barrier: kill and wait both unconditionally."""
    for p in procs:
        if p.poll() is None:
            p.kill()
        try:
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass


def _result_line(proc):
    out, err = proc.communicate(timeout=420)
    assert proc.returncode == 0, (
        f"worker failed rc={proc.returncode}:\n{err.decode()[-1500:]}"
    )
    for line in reversed(out.decode().splitlines()):
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON result:\n{out.decode()[-500:]}")


@pytest.mark.slow
def test_two_process_slice_trains_and_agrees_on_loss():
    port = _free_port()
    w0 = _spawn_worker(0, port)
    w1 = _spawn_worker(1, port)
    try:
        r0 = _result_line(w0)
        r1 = _result_line(w1)
    finally:
        _reap(w0, w1)
    # the slice actually formed: each process saw the GLOBAL device set
    assert r0["devices"] == 2 and r1["devices"] == 2, (r0, r1)
    assert r0["mesh"] == {"dp": 2, "sp": 1, "tp": 1, "ep": 1}
    # dp training is one global computation: the replicated loss must
    # be identical on both processes
    assert r0["final_loss"] == pytest.approx(
        r1["final_loss"], rel=1e-6
    ), (r0["final_loss"], r1["final_loss"])
    assert r0["steps"] == 3 and not r0["preempted"]


@pytest.mark.slow
def test_two_process_slice_with_zero1_masters():
    """The dp=2 slice composes with ZeRO-1 + master-weights: optimizer
    shards live on different PROCESSES and the all-gathered params
    still agree (loss equality)."""
    port = _free_port()
    w0 = _spawn_worker(0, port, ("--zero1", "--master-weights"))
    w1 = _spawn_worker(1, port, ("--zero1", "--master-weights"))
    try:
        r0 = _result_line(w0)
        r1 = _result_line(w1)
    finally:
        _reap(w0, w1)
    assert r0["devices"] == 2
    assert r0["final_loss"] == pytest.approx(r1["final_loss"], rel=1e-6)
