"""ElasticTPU chip-inventory lifecycle (VERDICT r3 #7): boot publish,
upsert idempotence, restore's stale sweep, and the health→phase loop that
keeps an external scheduler from placing onto a dead chip."""

import pytest

from elastic_tpu_agent.crd import (
    ElasticTPU,
    ElasticTPUClient,
    PhaseAvailable,
    PhaseFailed,
)
from elastic_tpu_agent.common import ResourceTPUCore, TPUPercentEachChip

from test_e2e import Cluster, wait_until


@pytest.fixture()
def cluster(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    yield c
    c.stop()


def _client(cluster) -> ElasticTPUClient:
    return ElasticTPUClient(cluster.opts.kube_client)


def _inventory(cluster):
    return sorted(
        (o for o in _client(cluster).list(cluster.node)
         if "-chip" in o.name),
        key=lambda o: o.name,
    )


def test_boot_publishes_available_inventory(cluster):
    """After start, every discovered chip has an Available-phase object
    with its capacity (reference modeled these phases but never wrote
    them, vendored types.go:49-78)."""
    assert cluster.manager.crd_recorder.flush(timeout=10.0)
    objs = _inventory(cluster)
    assert [o.name for o in objs] == [
        f"{cluster.node}-chip{i}" for i in range(4)
    ]
    for i, o in enumerate(objs):
        assert o.phase == PhaseAvailable
        assert o.chip_indexes == [i]
        assert o.capacity[ResourceTPUCore] == str(TPUPercentEachChip)
        assert int(o.capacity["elasticgpu.io/tpu-memory"]) > 0


def test_publish_inventory_is_upsert_idempotent(cluster):
    recorder = cluster.manager.crd_recorder
    assert recorder.flush(timeout=10.0)
    before = _inventory(cluster)
    recorder.publish_inventory(cluster.manager.operator.devices())
    recorder.publish_inventory(cluster.manager.operator.devices())
    assert recorder.flush(timeout=10.0)
    after = _inventory(cluster)
    assert [o.name for o in after] == [o.name for o in before]
    assert all(o.phase == PhaseAvailable for o in after)


def test_restore_sweeps_stale_inventory_keeps_live(cluster):
    """A chip object left over from a host reshape (chip no longer
    present) is swept by restore's reconcile; present chips' objects
    survive."""
    recorder = cluster.manager.crd_recorder
    assert recorder.flush(timeout=10.0)
    ghost = ElasticTPU(
        name=f"{cluster.node}-chip9",
        node_name=cluster.node,
        capacity={ResourceTPUCore: "100"},
        chip_indexes=[9],
        phase=PhaseAvailable,
    )
    _client(cluster).create(ghost)
    cluster.manager.restore()
    assert recorder.flush(timeout=10.0)
    names = [o.name for o in _inventory(cluster)]
    assert f"{cluster.node}-chip9" not in names
    assert names == [f"{cluster.node}-chip{i}" for i in range(4)]


def test_unhealthy_chip_flips_inventory_to_failed_and_back(cluster):
    """health_once drives the inventory phase: dead chip → Failed (with
    reason), recovery → Available."""
    recorder = cluster.manager.crd_recorder
    assert recorder.flush(timeout=10.0)
    op = cluster.manager.operator
    plugin = cluster.manager.plugin

    op.set_unhealthy({2})
    assert plugin.health_once()
    assert recorder.flush(timeout=10.0)
    objs = {o.name: o for o in _inventory(cluster)}
    assert objs[f"{cluster.node}-chip2"].phase == PhaseFailed
    # the other chips stay Available
    assert objs[f"{cluster.node}-chip0"].phase == PhaseAvailable

    op.set_unhealthy(set())
    assert plugin.health_once()
    assert recorder.flush(timeout=10.0)
    objs = {o.name: o for o in _inventory(cluster)}
    assert objs[f"{cluster.node}-chip2"].phase == PhaseAvailable


def test_allocatable_drift_detected_and_evented(cluster):
    """VERDICT r3 #8: kubelet's GetAllocatableResources view is
    cross-checked against the advertisement; a chip kubelet doesn't count
    surfaces as a warning node event."""
    from elastic_tpu_agent.plugins.tpushare import (
        core_device_id,
        mem_device_id,
    )

    # kubelet counts chips 0-2 for core (chip 3 missing) and an absent
    # chip 7 for memory
    cluster.kubelet.allocatable[ResourceTPUCore] = [
        core_device_id(c, u) for c in range(3) for u in range(100)
    ]
    cluster.kubelet.allocatable["elasticgpu.io/tpu-memory"] = [
        mem_device_id(c, u) for c in [0, 1, 2, 3, 7] for u in range(4)
    ]
    drift = cluster.manager.check_allocatable_drift()
    assert drift[ResourceTPUCore] == {"missing": [3], "extra": []}
    assert drift["elasticgpu.io/tpu-memory"] == {
        "missing": [], "extra": [7]
    }
    # warning event landed on the node
    assert cluster.manager.events is not None
    cluster.manager.events.flush()
    events = [
        e for e in cluster.apiserver.core_events
        if e.get("reason") == "TPUAllocatableDrift"
    ]
    assert events, "drift did not surface as a node event"
    assert "chip(s) 3" in events[0]["message"]


def test_allocatable_in_sync_reports_empty(cluster):
    from elastic_tpu_agent.plugins.tpushare import (
        core_device_id,
        mem_device_id,
    )

    cluster.kubelet.allocatable[ResourceTPUCore] = [
        core_device_id(c, u) for c in range(4) for u in range(100)
    ]
    cluster.kubelet.allocatable["elasticgpu.io/tpu-memory"] = [
        mem_device_id(c, u) for c in range(4) for u in range(4)
    ]
    assert cluster.manager.check_allocatable_drift() == {}


def test_allocatable_unknown_on_old_kubelet(cluster):
    """A fresh boot (kubelet has nothing for our resources yet) must NOT
    cry drift; a v1alpha1-only kubelet reports None (unknown)."""
    assert cluster.manager.check_allocatable_drift() == {}  # nothing seen
    cluster.manager.pr_client.reset()
    cluster.kubelet.allocatable_disabled = True
    cluster.manager.pr_client.reset()
    assert cluster.manager.check_allocatable_drift() is None


def test_health_flip_carries_reason_into_status(cluster):
    recorder = cluster.manager.crd_recorder
    assert recorder.flush(timeout=10.0)
    op = cluster.manager.operator
    op.set_unhealthy({1})
    # stub operator has no health_reasons(); the generic reason applies
    assert cluster.manager.plugin.health_once()
    assert recorder.flush(timeout=10.0)
    obj = _client(cluster).get(f"{cluster.node}-chip1")
    assert obj.phase == PhaseFailed
    assert obj.message
