"""The black-box lifecycle timeline (timeline.py).

Fast tier: journal primitives (ring cap, durable eviction counter, seq
monotonicity across restart), causal per-entity reconstruction, the
/debug/timeline endpoint, the node-doctor timeline subcommand, the
doctor-bundle block, the bind-story events from a real end-to-end bind,
and the drain-phase histogram — all clock-injected or event-driven, no
sleep-based polling.

Slow tier (runs under `make crash-replay-smoke`): kill-at-every-bind-
failpoint replays must leave a journal that still tells a consistent
story — no phantom commits, every crashed intent resolved by a visible
rollback/commit event.
"""

import json
import urllib.error
import urllib.request

import pytest

from elastic_tpu_agent import cli, faults
from elastic_tpu_agent import timeline as tl
from elastic_tpu_agent.common import (
    AnnotationAssumed,
    ManualClock,
    ResourceTPUCore,
    container_annotation,
)
from elastic_tpu_agent.manager import TPUManager
from elastic_tpu_agent.plugins.tpushare import core_device_id
from elastic_tpu_agent.storage import Storage
from elastic_tpu_agent.types import Device

from test_e2e import Cluster, wait_until

from fake_apiserver import make_pod


# -- journal primitives -------------------------------------------------------


@pytest.fixture()
def store(tmp_path):
    s = Storage(str(tmp_path / "meta.db"))
    yield s
    s.close()


def test_ring_cap_and_durable_eviction_counter(store):
    clk = ManualClock()
    t = tl.Timeline(store, node_name="n0", cap=4, clock=clk)
    for i in range(10):
        clk.advance(1.0)
        assert t.emit("bind_intent", keys={"pod": f"d/p{i}"}) is not None
    assert store.timeline_count() == 4
    assert store.timeline_evicted_total() == 6
    rows = store.timeline_rows()
    # newest survive; seqs stay the ORIGINAL monotonic ids
    assert [r["seq"] for r in rows] == [7, 8, 9, 10]
    assert [r["keys"]["pod"] for r in rows] == [
        "d/p6", "d/p7", "d/p8", "d/p9",
    ]
    # events carry the injected clock's wall time, not the real one
    assert rows[0]["ts"] == pytest.approx(1_000_000_007.0)
    # the writing agent's cap is persisted for offline readers: a
    # node-doctor run must report the REAL ring bound, not its default
    assert store.timeline_cap_stored() == 4


def test_seq_monotonic_across_restart_and_trim(tmp_path):
    path = str(tmp_path / "m.db")
    with Storage(path) as s:
        t = tl.Timeline(s, cap=3)
        for i in range(5):
            t.emit("k", keys={"pod": f"d/p{i}"})
    with Storage(path) as s2:
        t2 = tl.Timeline(s2, cap=3)
        seq = t2.emit("agent_started")
        # 5 emitted before, so the restarted agent continues at 6 —
        # AUTOINCREMENT never reuses trimmed ids.
        assert seq == 6
        assert s2.timeline_evicted_total() == 3  # counter survived too


def test_emit_never_raises_once_storage_closed(store):
    t = tl.Timeline(store, cap=8)
    assert t.emit("k") is not None
    store.close()
    assert t.emit("k") is None  # swallowed, counted
    assert t.dropped_total == 1


def test_emit_autofills_node_and_active_trace(store):
    from elastic_tpu_agent.tracing import get_tracer

    t = tl.Timeline(store, node_name="node-x", cap=8)
    with get_tracer().trace("bind") as tr:
        t.emit("bind_commit", keys={"pod": "d/p"})
    row = store.timeline_rows()[-1]
    assert row["keys"]["node"] == "node-x"
    assert row["keys"]["trace"] == tr.trace_id


# -- selection & causal reconstruction ----------------------------------------


def _mk_events():
    # node A binds pod P under trace T inside slice S; node B reforms S;
    # an unrelated pod Q binds on node A.
    return [
        {"seq": 1, "ts": 1.0, "kind": "bind_intent",
         "keys": {"pod": "d/p", "trace": "T", "slice": "S", "node": "A",
                  "chips": [0, 1]}, "attrs": {"intent_id": 1}},
        {"seq": 2, "ts": 2.0, "kind": "bind_commit",
         "keys": {"pod": "d/p", "trace": "T", "slice": "S", "node": "A",
                  "chips": [0, 1]}, "attrs": {"intent_id": 1}},
        {"seq": 3, "ts": 3.0, "kind": "bind_commit",
         "keys": {"pod": "d/q", "trace": "U", "node": "A", "chips": [2]},
         "attrs": {"intent_id": 2}},
        {"seq": 1, "ts": 4.0, "kind": "slice_reformed",
         "keys": {"pod": "d/m1", "slice": "S", "node": "B"},
         "attrs": {"epoch": 1}},
        {"seq": 4, "ts": 5.0, "kind": "reconcile_repair",
         "keys": {"trace": "T", "node": "A"},
         "attrs": {"class": "restored_link"}},
    ]


def test_pod_history_expands_along_trace_and_slice_links():
    events = tl.select_events(_mk_events(), pod="d/p")
    kinds = [e["kind"] for e in events]
    # direct pod matches + the slice's reform on ANOTHER node + the
    # repair that shares the bind's trace — but never unrelated d/q
    assert kinds == [
        "bind_intent", "bind_commit", "slice_reformed",
        "reconcile_repair",
    ]
    assert events[2].get("related") is True
    assert events[3].get("related") is True
    assert all(e["keys"].get("pod") != "d/q" for e in events)


def test_select_filters_chip_kind_node_and_limit():
    events = _mk_events()
    assert [e["seq"] for e in tl.select_events(
        events, chip=2, causal=False
    )] == [3]
    assert [e["kind"] for e in tl.select_events(
        events, kinds=["bind_commit"]
    )] == ["bind_commit", "bind_commit"]
    assert [e["keys"]["node"] for e in tl.select_events(
        events, node="B", causal=False
    )] == ["B"]
    assert len(tl.select_events(events, limit=2)) == 2
    # bare pod name matches like /debug/traces does
    assert tl.select_events(events, pod="p", causal=False)[0][
        "keys"]["pod"] == "d/p"


def test_merge_preserves_per_node_order_despite_clock_skew():
    # node B's clock runs ahead; its events must still come out in ITS
    # seq order, interleaved with A by wall time where possible.
    per_node = {
        "A": [{"seq": 1, "ts": 1.0, "kind": "a1", "keys": {}},
              {"seq": 2, "ts": 6.0, "kind": "a2", "keys": {}}],
        "B": [{"seq": 1, "ts": 5.0, "kind": "b1", "keys": {}},
              {"seq": 2, "ts": 2.0, "kind": "b2", "keys": {}}],
    }
    merged = tl.merge_node_events(per_node)
    assert [e["kind"] for e in merged] == ["a1", "b1", "b2", "a2"]


def test_verify_bind_story_flags_phantom_commit_and_dangling_intent():
    ok = [
        {"seq": 1, "kind": "bind_intent", "keys": {"node": "A"},
         "attrs": {"intent_id": 7}},
        {"seq": 2, "kind": "bind_commit", "keys": {"node": "A"},
         "attrs": {"intent_id": 7}},
    ]
    assert tl.verify_bind_story(ok) == []
    phantom = [{"seq": 1, "kind": "bind_commit", "keys": {"node": "A"},
                "attrs": {"intent_id": 9}}]
    assert any("phantom" in p for p in tl.verify_bind_story(phantom))
    # an EVICTED journal (min seq > 1) cannot claim phantoms — the
    # intent event may simply have aged out of the ring
    evicted = [{"seq": 40, "kind": "bind_commit", "keys": {"node": "A"},
                "attrs": {"intent_id": 9}}]
    assert tl.verify_bind_story(evicted) == []
    dangling = [{"seq": 4, "kind": "bind_intent",
                 "keys": {"node": "A", "pod": "d/p"},
                 "attrs": {"intent_id": 4}}]
    assert any("dangling" in p for p in tl.verify_bind_story(dangling))
    # a reconciler repair naming the intent's fate resolves it
    resolved = dangling + [
        {"seq": 5, "kind": "reconcile_repair", "keys": {"node": "A"},
         "attrs": {"class": "intent_rolled_back", "intent_id": 4}},
    ]
    assert tl.verify_bind_story(resolved) == []


# -- /debug/timeline endpoint -------------------------------------------------


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read())


def test_debug_timeline_endpoint(store):
    from prometheus_client import CollectorRegistry

    from elastic_tpu_agent.metrics import AgentMetrics

    m = AgentMetrics(registry=CollectorRegistry())
    httpd = m.serve(0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"{base}/debug/timeline")
        assert ei.value.code == 503  # not attached yet
        t = tl.Timeline(store, node_name="n0", metrics=m, cap=8)
        m.attach_timeline(t)
        t.emit("bind_commit", keys={"pod": "d/p", "chips": [1]})
        t.emit("cordon", keys={"chips": [0, 1]}, cordoned=True)
        payload = _get_json(f"{base}/debug/timeline")
        assert payload["cap"] == 8
        assert [e["kind"] for e in payload["events"]] == [
            "bind_commit", "cordon",
        ]
        filtered = _get_json(f"{base}/debug/timeline?pod=d/p")
        # the cordon is node-scoped lifecycle context: part of every
        # co-located pod's history, flagged related
        assert [e["kind"] for e in filtered["events"]] == [
            "bind_commit", "cordon",
        ]
        assert filtered["events"][1].get("related") is True
        by_chip = _get_json(f"{base}/debug/timeline?chip=0")
        assert [e["kind"] for e in by_chip["events"]] == ["cordon"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"{base}/debug/timeline?chip=zero")
        assert ei.value.code == 400
        # the eviction gauge serves the durable counter
        scrape = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "elastic_tpu_timeline_evicted_rows 0.0" in scrape
        assert "elastic_tpu_timeline_events_total 2.0" in scrape
        # /healthz carries the boot id
        health = _get_json(f"{base}/healthz")
        assert health["boot_id"] == t.boot_id
    finally:
        m.close()


# -- node-doctor timeline (dead-agent reconstruction) -------------------------


def test_node_doctor_timeline_reads_a_dead_agents_db(tmp_path, capsys):
    db = str(tmp_path / "meta.db")
    with Storage(db) as s:
        t = tl.Timeline(s, node_name="n0", cap=64)
        t.emit("agent_started", version="9.9.9", boot_id="cafe")
        t.emit("bind_intent",
               keys={"pod": "d/p", "trace": "T", "slice": "S"},
               intent_id=1)
        t.emit("bind_commit",
               keys={"pod": "d/p", "trace": "T", "slice": "S"},
               intent_id=1)
        t.emit("slice_reformed", keys={"pod": "d/m", "slice": "S"},
               epoch=1)
        t.emit("bind_commit", keys={"pod": "d/other"}, intent_id=2)
    # storage is CLOSED: the subcommand reconstructs from the db alone
    rc = cli.main([
        "node-doctor", "timeline", "--db-file", db, "--pod", "d/p",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["entity"] == {"pod": "d/p"}
    kinds = [e["kind"] for e in out["events"]]
    # the boot boundary and the slice's reform are part of the pod's
    # history; the unrelated pod is not
    assert kinds == [
        "agent_started", "bind_intent", "bind_commit", "slice_reformed",
    ]
    assert all(
        e["keys"].get("pod") != "d/other" for e in out["events"]
    )
    assert out["journal"]["evicted_total"] == 0

    rc = cli.main([
        "node-doctor", "timeline", "--db-file", db, "--slice", "S",
        "--kind", "slice_reformed",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert [e["kind"] for e in out["events"]] == ["slice_reformed"]

    assert cli.main([
        "node-doctor", "timeline",
        "--db-file", str(tmp_path / "absent.db"),
    ]) == 1


# -- doctor bundle block ------------------------------------------------------


def test_doctor_bundle_carries_timeline_block(tmp_path):
    from elastic_tpu_agent.manager import ManagerOptions, build_operator
    from elastic_tpu_agent.sampler import (
        build_diagnostics_bundle,
        validate_bundle,
    )

    db = str(tmp_path / "meta.db")
    with Storage(db) as s:
        t = tl.Timeline(s, node_name="n0", cap=64)
        t.emit("agent_started", version="1.2.3", boot_id=t.boot_id)
        t.emit("bind_commit", keys={"pod": "d/p"})
        operator = build_operator(ManagerOptions(
            operator_kind="stub:v5litepod-4",
            dev_root=str(tmp_path / "dev"),
        ))
        bundle = build_diagnostics_bundle(
            operator, node_name="n0", storage=s
        )
        assert validate_bundle(bundle) == [], validate_bundle(bundle)
        block = bundle["timeline"]
        assert block["agent_version"] == "1.2.3"
        assert block["boot_id"] == t.boot_id
        assert [e["kind"] for e in block["events"]] == [
            "agent_started", "bind_commit",
        ]


def test_validate_bundle_rejects_broken_timeline_block():
    from elastic_tpu_agent.sampler import validate_bundle

    base = {
        "kind": "elastic-tpu-node-doctor", "version": 1,
        "generated_ts": 0.0, "node": "", "devices": [],
        "healthy_indexes": [], "health_reasons": {},
        "error_counters": {},
        "allocations": {"chips": [], "pods": [], "sampler": {}},
        "sampler_windows": {"chips": {}, "pods": {}},
        "traces": [], "agent": {},
    }
    bad = dict(base)
    bad["timeline"] = {"events": [
        {"seq": 5, "ts": 1.0, "kind": "k", "keys": {}, "attrs": {}},
        {"seq": 3, "ts": 2.0, "kind": "k", "keys": {}, "attrs": {}},
    ], "total_events": 2, "evicted_total": 0,
        "agent_version": "", "boot_id": ""}
    assert any(
        "monotonically" in p for p in validate_bundle(bad)
    )
    bad2 = dict(base)
    bad2["timeline"] = {"events": []}
    assert any("missing" in p for p in validate_bundle(bad2))


# -- end-to-end: a real bind journals its story -------------------------------


CORE_IDS = [core_device_id(1, i) for i in range(100)]


def _admit(c, name, chips="1"):
    c.apiserver.upsert_pod(make_pod(
        "default", name, c.node,
        annotations={
            AnnotationAssumed: "true",
            container_annotation("jax"): chips,
        },
        containers=[{"name": "jax"}],
    ))
    assert wait_until(
        lambda: c.manager.sitter.get_pod("default", name) is not None
    )


def _bind(c, name, ids):
    from elastic_tpu_agent.gen import deviceplugin_pb2 as dp

    c.kubelet.assign("default", name, "jax", ResourceTPUCore, ids)
    # Through the real PreStart handler so the bind runs inside its
    # trace — the journal events must inherit the trace id.
    c.manager.plugin.core.PreStartContainer(
        dp.PreStartContainerRequest(devicesIDs=ids), None
    )


def test_bind_journals_intent_and_commit_with_join_keys(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    try:
        _admit(c, "timeline-pod")
        _bind(c, "timeline-pod", CORE_IDS)
        rows = c.manager.storage.timeline_rows()
        kinds = [r["kind"] for r in rows]
        assert kinds[0] == "agent_started"
        assert "bind_intent" in kinds and "bind_commit" in kinds
        commit = next(r for r in rows if r["kind"] == "bind_commit")
        intent = next(r for r in rows if r["kind"] == "bind_intent")
        assert commit["keys"]["pod"] == "default/timeline-pod"
        assert commit["keys"]["chips"] == [1]
        assert commit["keys"]["node"] == c.node
        assert commit["keys"]["trace"]  # the bind trace rode along
        assert commit["attrs"]["intent_id"] == (
            intent["attrs"]["intent_id"]
        )
        assert tl.verify_bind_story(rows) == []
        # the pod's reconstructed history is non-empty and causally
        # closed over its own trace
        history = c.manager.timeline.events(pod="default/timeline-pod")
        assert [e["kind"] for e in history].count("bind_commit") == 1
    finally:
        c.stop()


def test_handled_bind_failure_journals_rollback(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    try:
        _admit(c, "rollback-pod")
        c.kubelet.assign(
            "default", "rollback-pod", "jax", ResourceTPUCore, CORE_IDS
        )
        with faults.armed("bind.post_spec", "raise"):
            with pytest.raises(Exception):
                c.manager.plugin.core._bind(
                    Device(CORE_IDS, ResourceTPUCore)
                )
        rows = c.manager.storage.timeline_rows()
        rollback = [r for r in rows if r["kind"] == "bind_rollback"]
        assert rollback, [r["kind"] for r in rows]
        assert rollback[-1]["attrs"]["reason"] == "handled_failure"
        assert tl.verify_bind_story(rows) == []
    finally:
        c.stop()


# -- drain: transitions journaled, phase histogram observed -------------------


def test_drain_journals_transitions_and_phase_histogram(tmp_path):
    from prometheus_client import CollectorRegistry

    from elastic_tpu_agent.metrics import AgentMetrics

    m = AgentMetrics(registry=CollectorRegistry())
    c = Cluster(tmp_path, metrics=m)
    c.manager.drain.period_s = 0.05
    c.start()
    try:
        c.manager.drain.request_drain("timeline-test")
        assert wait_until(
            lambda: c.manager.drain.state == "drained", timeout=20
        ), c.manager.drain.status()
        rows = c.manager.storage.timeline_rows()
        states = [
            r["attrs"]["state"] for r in rows
            if r["kind"] == "drain_transition"
        ]
        assert states[:3] == ["cordoned", "draining", "drained"]
        cordons = [r for r in rows if r["kind"] == "cordon"]
        assert cordons and cordons[0]["attrs"]["cordoned"] is True
        # phase histogram: cordon->signaled (vacuous, no residents) and
        # signaled->drained each observed exactly once
        reg = m._registry
        assert reg.get_sample_value(
            "elastic_tpu_drain_phase_seconds_count",
            {"phase": "cordon_to_signaled"},
        ) == 1.0
        assert reg.get_sample_value(
            "elastic_tpu_drain_phase_seconds_count",
            {"phase": "signaled_to_drained"},
        ) == 1.0
        # cancel re-admits: the journal shows the return to active
        c.manager.drain.cancel_request()
        assert wait_until(
            lambda: c.manager.drain.state == "active", timeout=20
        )
        rows = c.manager.storage.timeline_rows()
        states = [
            r["attrs"]["state"] for r in rows
            if r["kind"] == "drain_transition"
        ]
        assert states[-1] == "active"
    finally:
        c.stop()


def test_drain_phase_anchor_survives_restart(tmp_path):
    """The phase anchors ride the drain journal: a ManualClock-driven
    orchestrator restarted mid-drain must not observe a phase twice or
    restart its measurement."""
    from elastic_tpu_agent.drain import PHASE_SIGNAL, DrainOrchestrator

    class _FakePlugin:
        cordoned = False
        # _signal_residents needs a per-resource spec plugin to exist;
        # with zero residents it is never invoked
        core = object()

        def set_cordoned(self, flag):
            self.cordoned = flag

    class _Hist:
        def __init__(self):
            self.samples = []

        def labels(self, phase):
            outer = self

            class _L:
                def observe(self, v):
                    outer.samples.append((phase, v))

            return _L()

    class _Metrics:
        def __init__(self):
            self.drain_phase_seconds = _Hist()

    clk = ManualClock()
    with Storage(str(tmp_path / "m.db")) as s:
        metrics = _Metrics()
        plugin = _FakePlugin()
        d = DrainOrchestrator(
            operator=object(), plugin=plugin, storage=s, sitter=None,
            reconciler=None, metrics=metrics, deadline_s=100.0,
            clock=clk,
        )
        d.request_drain("test")
        clk.advance(3.0)
        d.tick()  # ACTIVE -> start drain (cordon + signal)
        d.tick()  # DRAINING -> drained (no residents: vacuously)
        assert d.state == "drained"
        assert metrics.drain_phase_seconds.samples[0][0] == PHASE_SIGNAL
        n_samples = len(metrics.drain_phase_seconds.samples)
        # restart: resume() must NOT re-observe already-observed phases
        metrics2 = _Metrics()
        d2 = DrainOrchestrator(
            operator=object(), plugin=plugin, storage=s, sitter=None,
            reconciler=None, metrics=metrics2, deadline_s=100.0,
            clock=clk,
        )
        d2.resume()
        assert d2.state == "drained"
        assert d2._phase_ts.get("cordon") == pytest.approx(
            1_000_000_000.0
        )
        assert metrics2.drain_phase_seconds.samples == []
        assert n_samples == len(metrics.drain_phase_seconds.samples)


# -- crash replay: the surviving journal must still tell the story ------------

BIND_FAILPOINTS = [
    "bind.pre_journal",
    "bind.post_journal",
    "bind.post_create",
    "bind.post_spec",
    "bind.post_checkpoint",
]


@pytest.mark.slow
def test_kill_at_every_failpoint_leaves_consistent_story(tmp_path):
    """For EVERY mid-bind crash window: crash, restart the manager over
    the surviving db, let the boot reconcile resolve the debris — the
    journal must then hold no phantom commits and no unresolved
    intents, and the crashed window's rollback/commit resolution must
    be VISIBLE as events (satellite of `make crash-replay-smoke`)."""
    for i, failpoint in enumerate(BIND_FAILPOINTS):
        d = tmp_path / f"f{i}"
        d.mkdir()
        c = Cluster(d)
        c.start()
        try:
            _admit(c, "crashy")
            c.kubelet.assign(
                "default", "crashy", "jax", ResourceTPUCore, CORE_IDS
            )
            with faults.armed(failpoint, "die-thread:1"):
                with pytest.raises(faults.DieThread):
                    c.manager.plugin.core._bind(
                        Device(CORE_IDS, ResourceTPUCore)
                    )
            c.manager.stop()
            mgr2 = TPUManager(c.opts)
            mgr2.run(block=False)  # boot pass resolves immediately
            c.manager = mgr2
            assert wait_until(
                lambda: not c.manager.storage.open_intents()
            ), f"{failpoint}: intent journal not drained"
            rows = c.manager.storage.timeline_rows()
            problems = tl.verify_bind_story(rows)
            assert problems == [], f"{failpoint}: {problems}"
            kinds = [r["kind"] for r in rows]
            # the restart boundary is visible inside the history
            assert kinds.count("agent_started") == 2, kinds
            if failpoint != "bind.pre_journal":
                # a journaled intent existed: its fate must be an
                # explicit event — a plugin-side rollback, or the
                # reconciler resolving/rolling it via a repair
                resolutions = [
                    r for r in rows
                    if r["kind"] == "bind_rollback"
                    or (r["kind"] == "reconcile_repair"
                        and r["attrs"].get("class", "").startswith(
                            "intent_"))
                ]
                assert resolutions, (
                    f"{failpoint}: no rollback/commit resolution event "
                    f"in {kinds}"
                )
            # the bind survived: a live committed record, and commit
            # evidence in the journal — a bind_commit event (replayed
            # windows) or the reconciler's roll-forward resolution
            # (post_checkpoint: the crash killed the thread before the
            # commit emit, so intent_committed IS the commit evidence)
            info = c.manager.storage.load("default", "crashy")
            assert info is not None, f"{failpoint}: bind not replayed"
            commits = [
                r for r in rows
                if r["kind"] == "bind_commit"
                or (r["kind"] == "reconcile_repair"
                    and r["attrs"].get("class") == "intent_committed")
            ]
            assert commits, f"{failpoint}: no commit evidence in {kinds}"
            assert commits[-1]["keys"]["pod"] == "default/crashy"
        finally:
            c.stop()


# -- fleet-scale ring accounting (ISSUE 13) -----------------------------------


def test_ring_accounting_at_10k_events(tmp_path):
    """The scale leg churns 10k+ events through the durable ring: the
    table must hold at the cap, the durable eviction counter must be
    EXACT, and the max(seq) - rows == evicted invariant (the 'bounded
    growth is itself observable' contract) must hold the whole way.
    Uses group-commit batching — 10k per-event commits would make this
    a disk benchmark, and the ring semantics are identical either way.
    """
    from elastic_tpu_agent.storage import Storage

    cap = 256
    total = 10_500
    s = Storage(str(tmp_path / "ring.db"), batch_window_s=0.005)
    try:
        for i in range(total):
            seq = s.timeline_append(float(i), "churn", {"i": i}, {}, cap)
            assert seq == i + 1  # AUTOINCREMENT never reuses
            if i % 2500 == 0:
                assert s.timeline_count() <= cap
        rows = s.timeline_rows()
        assert len(rows) == cap
        assert s.timeline_evicted_total() == total - cap
        # the invariant the doctor bundle checks: rows + evicted == max seq
        assert rows[-1]["seq"] - len(rows) == s.timeline_evicted_total()
        # survivors are exactly the newest cap events, in seq order
        assert [e["seq"] for e in rows] == list(
            range(total - cap + 1, total + 1)
        )
    finally:
        s.close()
    # the accounting is durable: a fresh connection agrees
    reopened = Storage(str(tmp_path / "ring.db"))
    try:
        assert reopened.timeline_count() == cap
        assert reopened.timeline_evicted_total() == total - cap
        assert reopened.timeline_cap_stored() == cap
    finally:
        reopened.close()


def test_ring_accounting_exact_under_concurrent_writers(tmp_path):
    """Fleet churn appends from many threads at once; the one-commit
    append+trim+counter transaction must keep rows+evicted == max(seq)
    exact regardless of interleaving."""
    import threading

    from elastic_tpu_agent.storage import Storage

    cap = 64
    writers, each = 4, 700
    s = Storage(str(tmp_path / "ring.db"), batch_window_s=0.005)
    try:
        def write(w):
            for i in range(each):
                s.timeline_append(float(i), "churn", {"w": w}, {}, cap)

        threads = [
            threading.Thread(target=write, args=(w,), daemon=True)
            for w in range(writers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        rows = s.timeline_rows()
        assert len(rows) == cap
        assert s.timeline_evicted_total() == writers * each - cap
        assert rows[-1]["seq"] == writers * each
    finally:
        s.close()
