"""Native hook chain tests: build with make, then drive the binaries the
way the container runtime would (state JSON on stdin, bundle config.json,
alloc specs / dev-scan fallback, rootfs injection via mknod).

Uses /dev/null and /dev/zero as stand-in TPU chardevs — device injection
is by major:minor, so any chardev proves the mechanism.
"""

import json
import os
import shutil
import stat
import subprocess
import sys

import pytest

NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
HOOK = os.path.join(NATIVE_DIR, "elastic-tpu-hook")
TOOLKIT = os.path.join(NATIVE_DIR, "elastic-tpu-container-toolkit")
MOUNT_TOOL = os.path.join(NATIVE_DIR, "mount_elastic_tpu")


@pytest.fixture(scope="session", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE_DIR], check=True, capture_output=True)


def make_bundle(tmp_path, env=None, rootfs_name="rootfs"):
    bundle = tmp_path / "bundle"
    rootfs = bundle / rootfs_name
    (rootfs / "dev").mkdir(parents=True)
    config = {
        "ociVersion": "1.0.2",
        "process": {"env": env or []},
        "root": {"path": rootfs_name},
    }
    (bundle / "config.json").write_text(json.dumps(config))
    return bundle, rootfs


def write_alloc_spec(tmp_path, alloc_hash, device_paths, chip_indexes=None,
                     env=None, hbm=None):
    alloc_dir = tmp_path / "alloc"
    alloc_dir.mkdir(exist_ok=True)
    spec = {
        "hash": alloc_hash,
        "chip_indexes": chip_indexes or list(range(len(device_paths))),
        "device_paths": device_paths,
        "env": env or {"TPU_VISIBLE_CHIPS": "0"},
    }
    if hbm is not None:
        spec["hbm_limit_bytes"] = hbm
    (alloc_dir / f"{alloc_hash}.json").write_text(json.dumps(spec))
    return str(alloc_dir)


def run_hook(bundle, pid=1, extra_env=None):
    state = json.dumps({"ociVersion": "1.0.2", "id": "c1", "pid": pid,
                        "bundle": str(bundle)})
    env = dict(os.environ)
    env["ELASTIC_TPU_TOOLKIT"] = TOOLKIT
    env.update(extra_env or {})
    return subprocess.run(
        [HOOK, "--verbose"], input=state.encode(), env=env,
        capture_output=True, timeout=30,
    )


# -- hook passthrough ---------------------------------------------------------


def test_hook_passthrough_without_tpu_env(tmp_path):
    bundle, rootfs = make_bundle(tmp_path, env=["PATH=/bin"])
    result = run_hook(bundle)
    assert result.returncode == 0, result.stderr
    assert os.listdir(rootfs / "dev") == []  # nothing injected


def test_hook_malformed_state_fails_loudly():
    result = subprocess.run([HOOK], input=b"not json", capture_output=True)
    assert result.returncode == 1
    assert b"malformed" in result.stderr


# -- full hook -> toolkit injection ------------------------------------------


def test_hook_injects_devices_from_alloc_spec(tmp_path):
    alloc_hash = "cafe1234"
    bundle, rootfs = make_bundle(tmp_path, env=[f"TPU={alloc_hash}"])
    alloc_dir = write_alloc_spec(
        tmp_path, alloc_hash, ["/dev/null", "/dev/zero"],
        chip_indexes=[2, 3],
        env={"TPU_VISIBLE_CHIPS": "0,1"}, hbm=8 * 1024**3,
    )
    result = run_hook(bundle, extra_env={"ELASTIC_TPU_ALLOC_DIR": alloc_dir})
    assert result.returncode == 0, result.stderr.decode()

    # dense renumbering: host null/zero appear as accel0/accel1
    for p, src in enumerate(["/dev/null", "/dev/zero"]):
        node = rootfs / "dev" / f"accel{p}"
        st = os.stat(node)
        assert stat.S_ISCHR(st.st_mode), f"{node} not a chardev"
        assert st.st_rdev == os.stat(src).st_rdev

    env_file = (rootfs / "run" / "elastic-tpu" / "env").read_text()
    assert "TPU_VISIBLE_CHIPS=0,1" in env_file
    assert f"ELASTIC_TPU_HBM_LIMIT_BYTES={8 * 1024**3}" in env_file
    spec_copy = json.loads(
        (rootfs / "run" / "elastic-tpu" / "alloc.json").read_text()
    )
    assert spec_copy["chip_indexes"] == [2, 3]


def test_toolkit_idempotent_rerun(tmp_path):
    alloc_hash = "beef5678"
    bundle, rootfs = make_bundle(tmp_path, env=[f"TPU={alloc_hash}"])
    alloc_dir = write_alloc_spec(tmp_path, alloc_hash, ["/dev/null"])
    for _ in range(2):  # prestart may run after createRuntime already did
        result = run_hook(bundle, extra_env={"ELASTIC_TPU_ALLOC_DIR": alloc_dir})
        assert result.returncode == 0, result.stderr.decode()
    st = os.stat(rootfs / "dev" / "accel0")
    assert st.st_rdev == os.stat("/dev/null").st_rdev


def test_gpu_env_compat(tmp_path):
    """Scheduler stacks that still set GPU=<hash> keep working."""
    alloc_hash = "00c0ffee"
    bundle, rootfs = make_bundle(tmp_path, env=[f"GPU={alloc_hash}"])
    alloc_dir = write_alloc_spec(tmp_path, alloc_hash, ["/dev/null"])
    result = run_hook(bundle, extra_env={"ELASTIC_TPU_ALLOC_DIR": alloc_dir})
    assert result.returncode == 0, result.stderr.decode()
    assert (rootfs / "dev" / "accel0").exists()


def test_missing_allocation_fails(tmp_path):
    bundle, _ = make_bundle(tmp_path, env=["TPU=deadbeef"])
    empty = tmp_path / "empty-alloc"
    empty_dev = tmp_path / "empty-dev"
    empty.mkdir()
    empty_dev.mkdir()
    result = run_hook(
        bundle,
        extra_env={
            "ELASTIC_TPU_ALLOC_DIR": str(empty),
            "ELASTIC_TPU_DEV_DIR": str(empty_dev),
        },
    )
    assert result.returncode == 1
    assert b"no allocation found" in result.stderr


# -- dev-scan fallback resolution --------------------------------------------


def test_devscan_fallback_resolves_links(tmp_path):
    """Without an alloc spec the toolkit falls back to scanning
    /dev/elastic-tpu-<hash>-* symlinks (the reference hook's only
    mechanism). Targets point at /dev/accelN which does not exist here, so
    injection fails — but the error must prove the right chips were
    resolved in the right order."""
    alloc_hash = "12ab34cd"
    bundle, _ = make_bundle(tmp_path, env=[f"TPU={alloc_hash}"])
    dev_dir = tmp_path / "dev"
    dev_dir.mkdir()
    os.symlink("/dev/accel7", dev_dir / f"elastic-tpu-{alloc_hash}-0")
    os.symlink("/dev/accel2", dev_dir / f"elastic-tpu-{alloc_hash}-1")
    empty = tmp_path / "empty-alloc"
    empty.mkdir()
    result = run_hook(
        bundle,
        extra_env={
            "ELASTIC_TPU_ALLOC_DIR": str(empty),
            "ELASTIC_TPU_DEV_DIR": str(dev_dir),
        },
    )
    assert result.returncode == 1
    # position 0 resolved first -> tried /dev/accel7 first
    assert b"/dev/accel7" in result.stderr


def test_devscan_fallback_injects_real_chardev(tmp_path):
    """Same fallback path but with a resolvable target: symlink ->
    a chardev staged as <dev>/accel5."""
    alloc_hash = "77ee66dd"
    bundle, rootfs = make_bundle(tmp_path, env=[f"TPU={alloc_hash}"])
    dev_dir = tmp_path / "dev"
    dev_dir.mkdir()
    # stage a fake host chardev dir: accel5 is a symlink to a real chardev
    os.symlink("/dev/null", dev_dir / "accel5")
    os.symlink(str(dev_dir / "accel5"), dev_dir / f"elastic-tpu-{alloc_hash}-0")
    empty = tmp_path / "empty-alloc"
    empty.mkdir()
    result = run_hook(
        bundle,
        extra_env={
            "ELASTIC_TPU_ALLOC_DIR": str(empty),
            "ELASTIC_TPU_DEV_DIR": str(dev_dir),
        },
    )
    assert result.returncode == 0, result.stderr.decode()
    st = os.stat(rootfs / "dev" / "accel0")
    assert stat.S_ISCHR(st.st_mode)
    env_file = (rootfs / "run" / "elastic-tpu" / "env").read_text()
    assert "TPU_VISIBLE_CHIPS=0" in env_file
    # dev-scan fallback generates the compat spelling too (older libtpu)
    assert "TPU_VISIBLE_DEVICES=0" in env_file


# -- libtpu install -----------------------------------------------------------


def test_libtpu_copied_when_missing(tmp_path):
    alloc_hash = "feedf00d"
    bundle, rootfs = make_bundle(tmp_path, env=[f"TPU={alloc_hash}"])
    alloc_dir = write_alloc_spec(tmp_path, alloc_hash, ["/dev/null"])
    fake_libtpu = tmp_path / "libtpu.so"
    fake_libtpu.write_bytes(b"\x7fELF-fake-libtpu")
    result = run_hook(
        bundle,
        extra_env={
            "ELASTIC_TPU_ALLOC_DIR": alloc_dir,
            "ELASTIC_TPU_LIBTPU": str(fake_libtpu),
        },
    )
    assert result.returncode == 0, result.stderr.decode()
    assert (rootfs / "usr" / "lib" / "libtpu.so").read_bytes() == (
        b"\x7fELF-fake-libtpu"
    )


# -- mount_elastic_tpu (attach to running container) -------------------------


def test_mount_tool_attaches_into_mount_namespace(tmp_path):
    """Spawn a process in its own mount namespace, attach /dev/null as a
    TPU node inside it, verify via the victim's /proc root."""
    if shutil.which("unshare") is None:
        pytest.skip("unshare not available")
    probe = subprocess.run(
        ["unshare", "-m", "true"], capture_output=True
    )
    if probe.returncode != 0:
        pytest.skip("mount namespaces not permitted here")
    victim = subprocess.Popen(
        ["unshare", "-m", "sleep", "30"],
    )
    try:
        import time

        # wait for the sleep child inside the unshare wrapper
        target = str(tmp_path / "accel-target")
        deadline = time.monotonic() + 5
        ns_pid = None
        while time.monotonic() < deadline and ns_pid is None:
            try:
                kids = subprocess.run(
                    ["pgrep", "-P", str(victim.pid)],
                    capture_output=True, text=True,
                ).stdout.split()
                ns_pid = kids[0] if kids else None
            except Exception:
                pass
            if ns_pid is None:
                time.sleep(0.05)
        pid = ns_pid or str(victim.pid)
        result = subprocess.run(
            [MOUNT_TOOL, pid, "/dev/null", target],
            capture_output=True, text=True, timeout=10,
        )
        assert result.returncode == 0, result.stderr
        st = os.stat(f"/proc/{pid}/root{target}")
        assert stat.S_ISCHR(st.st_mode)
        assert st.st_rdev == os.stat("/dev/null").st_rdev
    finally:
        victim.kill()
        victim.wait()
