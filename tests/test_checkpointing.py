"""Workload checkpoint/resume (workloads/checkpointing.py, orbax-backed):
sharded round-trip on the 8-device CPU mesh, resume continuity, retention,
and the runner's end-to-end resume path in a fresh subprocess."""

import json
import os
import subprocess
import sys

import pytest

import jax
import numpy as np

from elastic_tpu_agent.workloads.checkpointing import TrainCheckpointer
from elastic_tpu_agent.workloads.transformer import (
    ModelConfig,
    make_mesh,
    make_train_step,
)

TINY = ModelConfig(vocab=128, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                   max_seq=32)


def _setup(tmp_path):
    mesh = make_mesh(8, dp=4, sp=1, tp=2)
    step_fn, init_all, _ = make_train_step(TINY, mesh)
    params, opt = init_all(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, 17), 0, TINY.vocab)
    return mesh, step_fn, params, opt, toks


def _trees_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_sharded_roundtrip_preserves_values_and_shardings(tmp_path):
    _, step_fn, params, opt, toks = _setup(tmp_path)
    params, opt, _ = step_fn(params, opt, toks)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(0, params, opt)
    ckpt.wait()

    r_params, r_opt, step = ckpt.restore(params, opt)
    assert step == 0
    _trees_equal(params, r_params)
    _trees_equal(opt, r_opt)
    # restored arrays keep their mesh layout (tp-sharded FF weights)
    orig = params["layers"][0]["w1"].sharding
    rest = r_params["layers"][0]["w1"].sharding
    assert rest.spec == orig.spec
    ckpt.close()


def test_resume_matches_uninterrupted_run(tmp_path):
    """save@k, 'crash', restore, continue == straight-through run."""
    _, step_fn, params, opt, toks = _setup(tmp_path)
    p1, o1 = params, opt
    for _ in range(2):
        p1, o1, _ = step_fn(p1, o1, toks)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(1, p1, o1)
    ckpt.wait()
    # straight-through: 2 more steps
    p_direct, o_direct = p1, o1
    for _ in range(2):
        p_direct, o_direct, _ = step_fn(p_direct, o_direct, toks)

    # "new process": restore and run the same 2 steps
    ckpt2 = TrainCheckpointer(str(tmp_path / "ckpt"))
    p2, o2, step = ckpt2.restore(params, opt)
    assert step == 1
    for _ in range(2):
        p2, o2, _ = step_fn(p2, o2, toks)
    _trees_equal(p_direct, p2)
    ckpt.close()
    ckpt2.close()


def test_retention_keeps_newest(tmp_path):
    _, _, params, opt, _ = _setup(tmp_path)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), keep=2)
    for s in range(4):
        ckpt.save(s, params, opt)
    ckpt.wait()
    assert ckpt.latest_step == 3
    _, _, step = ckpt.restore(params, opt)
    assert step == 3
    # evicted steps really are gone
    import pytest as _pytest

    with _pytest.raises(Exception):
        ckpt.restore(params, opt, step=0)
    ckpt.close()


def test_restore_at_changed_world_size_continues_loss_curve(tmp_path):
    """The elastic-reform resume (ISSUE 14 satellite): a checkpoint
    saved on a dp=4 mesh (8 devices) restores onto a SMALLER dp=3 mesh
    (6 devices) — shardings re-laid-out by orbax onto the new mesh —
    and the next step's loss matches the uninterrupted full-mesh run on
    the same global batch. Today only same-shape resume was pinned;
    this is exactly what a workload does after TPUSliceReformed shrinks
    its world."""
    mesh8 = make_mesh(8, dp=4, sp=1, tp=2)
    step8, init8, _ = make_train_step(TINY, mesh8)
    params, opt = init8(jax.random.key(0))
    # global batch 12: divisible by BOTH dp=4 and dp=3
    toks = jax.random.randint(jax.random.key(1), (12, 17), 0, TINY.vocab)
    for _ in range(2):
        params, opt, _ = step8(params, opt, toks)
    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"))
    ckpt.save(1, params, opt)
    ckpt.wait()
    ckpt.close()

    # the reformed world: 3 dp ranks over 6 of the 8 devices
    mesh6 = make_mesh(6, dp=3, sp=1, tp=2)
    step6, init6, _ = make_train_step(TINY, mesh6)
    p_like, o_like = init6(jax.random.key(0))
    ckpt2 = TrainCheckpointer(str(tmp_path / "ckpt"))
    r_params, r_opt, step = ckpt2.restore(p_like, o_like)
    ckpt2.close()
    assert step == 1
    # restored VALUES are the full-mesh values...
    _trees_equal(params, r_params)
    # ...but laid out on the smaller mesh
    assert r_params["layers"][0]["w1"].sharding.mesh.shape["dp"] == 3

    # loss-curve continuity: one more step on each world, same batch
    _, _, loss_direct = step8(params, opt, toks)
    _, _, loss_resumed = step6(r_params, r_opt, toks)
    np.testing.assert_allclose(
        np.asarray(loss_resumed), np.asarray(loss_direct),
        rtol=2e-4, atol=1e-5,
    )


@pytest.mark.slow
def test_runner_resumes_from_checkpoint(tmp_path):
    """Two real runner processes sharing a checkpoint dir: the second
    resumes where the first stopped."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": os.pathsep.join(
            [os.path.join(os.path.dirname(__file__), "..")]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        ).rstrip(os.pathsep),
    }
    cmd = [
        sys.executable, "-m", "elastic_tpu_agent.workloads.runner",
        "--preset", "tiny", "--steps", "4", "--batch", "4", "--seq", "32",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--checkpoint-every", "2",
    ]
    out1 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=300)
    assert out1.returncode == 0, out1.stderr[-2000:]
    r1 = json.loads(out1.stdout.strip().splitlines()[-1])
    assert r1["start_step"] == 0 and r1["steps"] == 4

    out2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=300)
    assert out2.returncode == 0, out2.stderr[-2000:]
    r2 = json.loads(out2.stdout.strip().splitlines()[-1])
    # first run saved at steps 1 and 3 -> second run resumes at 4
    assert r2["start_step"] == 4
