"""Mixed-precision weight storage (f32 masters in opt_state) and
ZeRO-1 optimizer sharding (workloads/transformer.py make_train_step).

The two levers the perf doc's ceiling analysis names: bf16 param
storage kills the per-step f32->bf16 weight casts and halves weight
HBM reads; zero1 divides optimizer HBM by dp. Neither may change the
training math beyond rounding — pinned here against the baseline
configuration on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from elastic_tpu_agent.workloads import (
    ModelConfig,
    make_mesh,
    make_train_step,
)
from elastic_tpu_agent.workloads.transformer import ema_params

TINY = ModelConfig(
    vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=64
)


def _tokens(key, n=3, batch=8, seq=17):
    return jax.random.randint(key, (n, batch, seq), 0, TINY.vocab)


def _run(mesh, steps=3, **kwargs):
    train_step, init_all, _ = make_train_step(TINY, mesh, **kwargs)
    params, opt_state = init_all(jax.random.key(0))
    toks = _tokens(jax.random.key(1), n=steps)
    losses = []
    for i in range(steps):
        params, opt_state, loss = train_step(params, opt_state, toks[i])
        losses.append(float(loss))
    return params, opt_state, losses


def test_master_weights_stores_cfg_dtype_and_learns():
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    params, opt_state, losses = _run(mesh, master_weights=True)
    leaf = params["layers"][0]["w1"]
    assert leaf.dtype == TINY.dtype            # bf16 live tree
    inner, masters = opt_state
    assert masters["layers"][0]["w1"].dtype == jnp.float32
    assert losses[-1] < losses[0], losses


def test_master_weights_matches_f32_storage_trajectory():
    """bf16 storage reads the same values the per-use casts produced,
    so the loss trajectory must track the f32-storage baseline to
    bf16 rounding."""
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    _, _, base = _run(mesh, master_weights=False)
    _, _, mixed = _run(mesh, master_weights=True)
    np.testing.assert_allclose(base, mixed, rtol=2e-2, atol=2e-2)


def test_master_weights_roundtrip_is_masters_rounded():
    """The live tree after a step is exactly the f32 masters rounded
    to cfg.dtype — no drift channel between the two trees."""
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    params, (inner, masters), _ = _run(mesh, master_weights=True)
    got = np.asarray(params["layers"][0]["w1"], np.float32)
    want = np.asarray(
        masters["layers"][0]["w1"].astype(TINY.dtype), np.float32
    )
    np.testing.assert_array_equal(got, want)


def test_zero1_shards_opt_state_over_dp():
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    _, opt_state, _ = _run(mesh, zero1=True)
    mu = opt_state[0].mu  # adamw: (ScaleByAdamState, ...) chain
    w1_mu = mu["layers"][0]["w1"]
    # param sharding P(None, "tp") gains "dp" on the free axis
    assert w1_mu.sharding.spec == P("dp", "tp"), w1_mu.sharding.spec
    shard_shapes = {s.data.shape for s in w1_mu.addressable_shards}
    assert shard_shapes == {(TINY.d_model // 2, TINY.d_ff // 4)}


def test_zero1_loss_equals_unsharded():
    """ZeRO-1 is a LAYOUT change: per-step losses must match the
    replicated-optimizer run to reduction-order noise."""
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    _, _, base = _run(mesh, zero1=False)
    _, _, z1 = _run(mesh, zero1=True)
    np.testing.assert_allclose(base, z1, rtol=1e-5, atol=1e-5)


def test_zero1_with_master_weights_and_ema():
    """The full stack: bf16 live tree, dp-sharded f32 masters, moments
    AND EMA; learns, and the EMA tree is extractable and dp-sharded."""
    mesh = make_mesh(8, dp=4, sp=1, tp=2)
    params, opt_state, losses = _run(
        mesh, master_weights=True, zero1=True, ema_decay=0.9,
    )
    assert losses[-1] < losses[0]
    inner, masters = opt_state
    assert "dp" in masters["layers"][0]["w1"].sharding.spec
    ema = ema_params(opt_state)
    assert ema is not None
    assert "dp" in ema["layers"][0]["w1"].sharding.spec
    # EMA tracks the f32 masters in this mode
    assert ema["layers"][0]["w1"].dtype == jnp.float32


def test_zero1_with_grad_accumulation():
    mesh = make_mesh(8, dp=2, sp=1, tp=4)
    train_step, init_all, _ = make_train_step(
        TINY, mesh, accum_steps=2, master_weights=True, zero1=True,
    )
    params, opt_state = init_all(jax.random.key(0))
    # one fixed batch repeated: the loss must strictly fall
    toks = jax.random.randint(
        jax.random.key(1), (2, 8, 17), 0, TINY.vocab
    )
    losses = []
    for _ in range(4):
        params, opt_state, loss = train_step(params, opt_state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
