"""Heterogeneous-generation advertisement (ISSUE 8 satellite).

The generation table (tpu/topology.CHIP_SPECS) has carried v4/v5e/v6e
core-count/HBM shapes since the seed, but nothing exercised MIXED
shapes: every plugin/operator test ran one v5litepod node. These tests
parametrize the advertisement pipeline over generations — device-list
capacity (core units, HBM MiB units), per-chip facts on the discovered
inventory, canonical TPU_VISIBLE_CHIPS ordering through a real bind,
and a FleetSim whose nodes run DIFFERENT generations side by side.
"""

import pytest

from elastic_tpu_agent.common import (
    AnnotationAssumed,
    BytesPerMemoryUnit,
    ResourceTPUCore,
    ResourceTPUMemory,
    TPUPercentEachChip,
    container_annotation,
)
from elastic_tpu_agent.plugins.tpushare import (
    CORE_ENDPOINT,
    core_device_id,
    mem_device_id,
)
from elastic_tpu_agent.slices.packing import canonical_chip_order
from elastic_tpu_agent.tpu.stub import StubOperator
from elastic_tpu_agent.tpu.topology import (
    CHIP_SPECS,
    chip_grid,
    parse_accelerator_type,
)
from elastic_tpu_agent.types import Device

from test_e2e import Cluster, wait_until

from fake_apiserver import make_pod

# One single-host accelerator type per generation under test: the
# fleet-relevant mix (v4 pods, v5e lite pods, v6e) with per-generation
# chips/host, cores/chip and HBM/chip all differing.
GENERATIONS = [
    ("v4", "v4-8"),            # 4 chips/host, 2 cores/chip, 32 GiB
    ("v5e", "v5litepod-8"),    # 8 chips/host, 1 core/chip, 16 GiB
    ("v6e", "v6e-8"),          # 8 chips/host, 1 core/chip, 32 GiB
]


@pytest.mark.parametrize("family,acc", GENERATIONS)
def test_stub_inventory_matches_generation_spec(tmp_path, family, acc):
    """The discovered chips carry the generation's core/HBM facts."""
    spec = CHIP_SPECS[family]
    topo = parse_accelerator_type(acc)
    op = StubOperator(str(tmp_path / "dev"), acc)
    devs = op.devices()
    assert len(devs) == topo.chips_per_host
    for chip in devs:
        assert chip.hbm_bytes == spec.hbm_bytes
        assert chip.cores == spec.cores_per_chip
        assert family in chip.uuid


@pytest.mark.parametrize("family,acc", GENERATIONS)
def test_device_list_capacity_per_generation(tmp_path, family, acc):
    """Advertised fake-device capacity is the generation's shape: 100
    core units per chip; one memory unit per MiB of that generation's
    HBM (v4 advertises HALF the per-chip units of... no — v4 has 32 GiB
    like v6e but only 4 chips; v5e has 16 GiB on 8 chips — the three
    node totals all differ)."""
    from elastic_tpu_agent.plugins.base import PluginConfig
    from elastic_tpu_agent.plugins.tpushare import TPUSharePlugin
    from elastic_tpu_agent.storage import Storage

    from fake_kubelet import FakeSitter

    spec = CHIP_SPECS[family]
    topo = parse_accelerator_type(acc)
    op = StubOperator(str(tmp_path / "dev"), acc)
    config = PluginConfig(
        device_plugin_dir=str(tmp_path / "dp"),
        pod_resources_socket=str(tmp_path / "pr.sock"),
        operator=op,
        sitter=FakeSitter(),
        storage=Storage(str(tmp_path / "meta.db")),
        locator_factory=lambda r: None,
        extra={"alloc_spec_dir": str(tmp_path / "alloc")},
    )
    plugin = TPUSharePlugin(config)
    n_chips = topo.chips_per_host
    assert len(plugin.core._device_list()) == n_chips * TPUPercentEachChip
    units_per_chip = spec.hbm_bytes // BytesPerMemoryUnit
    assert plugin.memory._mib_per_chip == units_per_chip
    assert len(plugin.memory._device_list()) == n_chips * units_per_chip
    # memory request packing derives from the generation's HBM: a
    # request for 1.5 chips' worth of MiB must span 2 chips
    assert plugin.memory._chips_for_request(
        units_per_chip + units_per_chip // 2
    ) == 2


@pytest.mark.parametrize("family,acc", GENERATIONS)
def test_bind_env_and_packing_per_generation(tmp_path, family, acc):
    """A real two-chip bind on each generation: TPU_VISIBLE_CHIPS is
    the dense canonical (grid-walk) renumbering, the virtual links
    resolve to the annotated physical chips, and the memory sibling's
    HBM quota reflects the generation's chip size."""
    spec = CHIP_SPECS[family]
    topo = parse_accelerator_type(acc)
    c = Cluster(tmp_path, operator_kind=f"stub:{acc}")
    c.start()
    try:
        # annotate the two chips in NON-canonical order: the bind must
        # re-order them via the grid walk, not trust annotation order
        chips = [topo.chips_per_host - 1, 0]
        want_order = canonical_chip_order(chips, topo.chips_per_host)
        assert want_order == sorted(
            chips,
            key=lambda i: (chip_grid(topo.chips_per_host)[i][1],
                           chip_grid(topo.chips_per_host)[i][0]),
        )
        c.apiserver.upsert_pod(make_pod(
            "default", "het-0", c.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): ",".join(map(str, chips)),
            },
            containers=[{"name": "jax"}],
        ))
        assert wait_until(
            lambda: c.manager.sitter.get_pod("default", "het-0") is not None
        )
        ids = [core_device_id(chips[0], u) for u in range(100)] + [
            core_device_id(chips[1], u) for u in range(100)
        ]
        resp = c.kubelet.kubelet_allocate_flow(
            CORE_ENDPOINT, "default", "het-0", "jax", ResourceTPUCore, ids
        )
        env = dict(resp.container_responses[0].envs)
        assert env["TPU_VISIBLE_CHIPS"] == "0,1"
        # the spec on disk records the canonical physical order
        rec = c.manager.storage.load("default", "het-0").allocations[
            "jax"
        ][ResourceTPUCore]
        assert rec.chip_indexes == want_order
        spec_doc = c.manager.plugin.core.read_alloc_spec(
            Device(ids, ResourceTPUCore).hash
        )
        assert spec_doc["chip_indexes"] == want_order
        assert [
            p.rsplit("/accel", 1)[1] for p in spec_doc["device_paths"]
        ] == [str(i) for i in want_order]
        # memory granularity sanity for this generation
        assert (
            c.manager.plugin.memory._mib_per_chip
            == spec.hbm_bytes // BytesPerMemoryUnit
        )
    finally:
        c.stop()


def test_fleet_sim_mixes_generations(tmp_path):
    """FleetSim runs DIFFERENT generations per node: each agent
    advertises its own generation's chip count/HBM, and a bind lands on
    every node of the mixed fleet."""
    from elastic_tpu_agent.sim import FleetSim

    kinds = [f"stub:{acc}" for _, acc in GENERATIONS]
    sim = FleetSim(
        str(tmp_path), nodes=3, operator_kinds=kinds,
        reconcile_period_s=30.0,
    )
    try:
        sim.start()
        for i, (family, acc) in enumerate(GENERATIONS):
            spec = CHIP_SPECS[family]
            topo = parse_accelerator_type(acc)
            node = sim.nodes[i]
            assert node.operator_kind == kinds[i]
            devs = node.manager.operator.devices()
            assert len(devs) == topo.chips_per_host, acc
            assert {d.hbm_bytes for d in devs} == {spec.hbm_bytes}
            assert (
                node.manager.plugin.memory._mib_per_chip
                == spec.hbm_bytes // BytesPerMemoryUnit
            )
        refs = sim.admit_pods(pods_per_node=2)
        sim.wait_synced(refs)
        for ref in refs:
            sim.bind_pod(ref)
        assert sim.stored_binds() == {
            node.name: 2 for node in sim.nodes
        }
        # pods were spread over each node's OWN chip count (v4 node has
        # 4 chips, v5e/v6e nodes 8) — the admission used per-node shapes
        assert {r.chip for r in refs if r.node_idx == 0} <= set(range(4))
    finally:
        sim.stop()
