"""Full-chain integration: every layer of the system in one flow.

agent (Allocate/PreStart writes the alloc spec) -> native OCI hook ->
native container toolkit (mknod devices, write /run/elastic-tpu/env into
the rootfs) -> workload runner in a real subprocess reading that env file
and training. No layer is mocked except the TPU chardevs themselves
(/dev/null / /dev/zero stand-ins — injection is by major:minor).
"""

import json
import os
import stat
import subprocess
import sys

import pytest

from elastic_tpu_agent.common import (
    AnnotationAssumed,
    AnnotationSliceName,
    AnnotationSliceWorkerHosts,
    AnnotationSliceWorkerID,
    ResourceTPUMemory,
    container_annotation,
)
from elastic_tpu_agent.plugins.tpushare import MEM_ENDPOINT, mem_device_id
from elastic_tpu_agent.types import Device

from fake_apiserver import make_pod
from test_e2e import Cluster, wait_until
from test_native import HOOK, NATIVE_DIR, TOOLKIT

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(scope="module", autouse=True)
def build_native():
    subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                   capture_output=True)


def test_agent_to_toolkit_to_runner(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    try:
        # 1. scheduler: fractional HBM pod with QoS + slice annotations
        half_gib_units = 8 * 1024  # 8 GiB of the 16 GiB chip
        c.apiserver.upsert_pod(
            make_pod(
                "ml", "chain", c.node,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "1",
                    AnnotationSliceName: "v5p-16",
                    AnnotationSliceWorkerID: "1",
                    AnnotationSliceWorkerHosts: "host-a,host-b",
                },
                containers=[{"name": "jax"}],
            )
        )
        assert wait_until(
            lambda: c.manager.sitter.get_pod("ml", "chain") is not None
        )
        ids = [mem_device_id(1, u) for u in range(half_gib_units)]
        c.kubelet.kubelet_allocate_flow(
            MEM_ENDPOINT, "ml", "chain", "jax", ResourceTPUMemory, ids
        )
        dev_hash = Device(ids, ResourceTPUMemory).hash
        alloc_dir = str(c.tmp / "alloc")
        assert os.path.exists(os.path.join(alloc_dir, f"{dev_hash}.json"))

        # 2. container runtime: OCI createRuntime hook -> toolkit.
        # The alloc spec's device path /dev/accel1 doesn't exist here;
        # point it at a stand-in chardev the way test_native does.
        spec_path = os.path.join(alloc_dir, f"{dev_hash}.json")
        spec = json.load(open(spec_path))
        spec["device_paths"] = ["/dev/null"]
        json.dump(spec, open(spec_path, "w"))

        bundle = tmp_path / "bundle"
        rootfs = bundle / "rootfs"
        (rootfs / "dev").mkdir(parents=True)
        (bundle / "config.json").write_text(json.dumps({
            "ociVersion": "1.0.2",
            "process": {"env": [f"TPU={dev_hash}"]},
            "root": {"path": "rootfs"},
        }))
        state = json.dumps({"ociVersion": "1.0.2", "id": "c1", "pid": 1,
                            "bundle": str(bundle)})
        result = subprocess.run(
            [HOOK], input=state.encode(),
            env={**os.environ, "ELASTIC_TPU_TOOLKIT": TOOLKIT,
                 "ELASTIC_TPU_ALLOC_DIR": alloc_dir},
            capture_output=True, timeout=30,
        )
        assert result.returncode == 0, result.stderr.decode()

        # toolkit injected the (stand-in) chardev, densely renumbered
        st = os.stat(rootfs / "dev" / "accel0")
        assert stat.S_ISCHR(st.st_mode)
        env_file = rootfs / "run" / "elastic-tpu" / "env"
        content = env_file.read_text()
        assert f"ELASTIC_TPU_HBM_LIMIT_BYTES={8 * 1024**3}" in content
        assert "TPU_WORKER_ID=1" in content
        assert "TPU_WORKER_HOSTNAMES=host-a,host-b" in content

        # 3. the workload runner consumes the toolkit-written env file.
        # Agent env is authoritative (load_alloc_env overrides ambient
        # env), so a multi-host TPU_WORKER_HOSTNAMES would make the
        # runner genuinely dial jax.distributed at host-a — unreachable
        # here. Drop just that key to exercise the single-host path; the
        # override semantics themselves are asserted below via
        # TPU_WORKER_ID landing despite the image's ambient TPU env.
        runner_env_file = tmp_path / "env-single-host"
        runner_env_file.write_text(
            "".join(
                line for line in env_file.read_text().splitlines(True)
                if not line.startswith("TPU_WORKER_HOSTNAMES=")
            )
        )
        out = subprocess.run(
            [sys.executable, "-m", "elastic_tpu_agent.workloads.runner",
             "--preset", "tiny", "--steps", "2", "--batch", "2",
             "--seq", "32"],
            env={
                **os.environ,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO,
                "ELASTIC_TPU_ENV_FILE": str(runner_env_file),
            },
            capture_output=True, text=True, timeout=300,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout.strip().splitlines()[-1])
        applied = report["alloc_env"]
        assert applied["ELASTIC_TPU_HBM_LIMIT_BYTES"] == str(8 * 1024**3)
        assert applied["TPU_WORKER_ID"] == "1"
        assert applied["TPU_ACCELERATOR_TYPE"] == "v5p-16"
        assert report["final_loss"] > 0
    finally:
        c.stop()


def test_dual_resource_container_gets_devices_and_hbm_env(tmp_path):
    """One container requesting BOTH tpu-core and tpu-memory: kubelet merges
    the two Allocate env maps in undefined order, so the hook may resolve
    either hash. Every spec file carries the union (reference defect
    gpushare.go:79-82/204-207: only the winner's spec was injected), so
    whichever wins, the container ends with the devices AND the HBM quota."""
    from elastic_tpu_agent.common import ResourceTPUCore
    from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, core_device_id

    c = Cluster(tmp_path)
    c.start()
    try:
        half_gib_units = 8 * 1024
        c.apiserver.upsert_pod(
            make_pod(
                "ml", "dual", c.node,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "1",
                },
                containers=[{"name": "jax"}],
            )
        )
        assert wait_until(
            lambda: c.manager.sitter.get_pod("ml", "dual") is not None
        )
        core_ids = [core_device_id(1, u) for u in range(50)]
        mem_ids = [mem_device_id(1, u) for u in range(half_gib_units)]
        c.kubelet.kubelet_allocate_flow(
            CORE_ENDPOINT, "ml", "dual", "jax", ResourceTPUCore, core_ids
        )
        c.kubelet.kubelet_allocate_flow(
            MEM_ENDPOINT, "ml", "dual", "jax", ResourceTPUMemory, mem_ids
        )
        core_hash = Device(core_ids, ResourceTPUCore).hash
        mem_hash = Device(mem_ids, ResourceTPUMemory).hash
        alloc_dir = str(c.tmp / "alloc")

        # both spec files carry the union
        for h in (core_hash, mem_hash):
            spec = json.load(open(os.path.join(alloc_dir, f"{h}.json")))
            assert spec["env"]["ELASTIC_TPU_CORE_UNITS"] == "50", h
            assert spec["env"]["ELASTIC_TPU_HBM_LIMIT_BYTES"] == str(
                8 * 1024**3
            ), h
            assert spec["device_paths"] == ["/dev/accel1"], h
            assert spec["resources"] == sorted(
                [ResourceTPUCore, ResourceTPUMemory]
            ), h

        # drive the native hook with EACH hash: identical injection
        for n, h in enumerate((core_hash, mem_hash)):
            spec_path = os.path.join(alloc_dir, f"{h}.json")
            spec = json.load(open(spec_path))
            spec["device_paths"] = ["/dev/null"]
            json.dump(spec, open(spec_path, "w"))
            bundle = tmp_path / f"bundle{n}"
            rootfs = bundle / "rootfs"
            (rootfs / "dev").mkdir(parents=True)
            (bundle / "config.json").write_text(json.dumps({
                "ociVersion": "1.0.2",
                "process": {"env": [f"TPU={h}"]},
                "root": {"path": "rootfs"},
            }))
            state = json.dumps({"ociVersion": "1.0.2", "id": f"c{n}",
                                "pid": 1, "bundle": str(bundle)})
            result = subprocess.run(
                [HOOK], input=state.encode(),
                env={**os.environ, "ELASTIC_TPU_TOOLKIT": TOOLKIT,
                     "ELASTIC_TPU_ALLOC_DIR": alloc_dir},
                capture_output=True, timeout=30,
            )
            assert result.returncode == 0, result.stderr.decode()
            st = os.stat(rootfs / "dev" / "accel0")
            assert stat.S_ISCHR(st.st_mode)
            content = (rootfs / "run" / "elastic-tpu" / "env").read_text()
            assert f"ELASTIC_TPU_HBM_LIMIT_BYTES={8 * 1024**3}" in content, h
            assert "ELASTIC_TPU_CORE_UNITS=50" in content, h
    finally:
        c.stop()


def test_single_resource_release_demerges_sibling_spec(tmp_path):
    """Releasing ONE of a container's two resources must restore the
    surviving sibling's spec to its own content — the merged union would
    otherwise keep naming the freed resource's env/devices (ADVICE r2/r3,
    VERDICT r3 weak #9)."""
    from elastic_tpu_agent.common import ResourceTPUCore
    from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, core_device_id
    from elastic_tpu_agent.types import PodContainer

    c = Cluster(tmp_path)
    c.start()
    try:
        c.apiserver.upsert_pod(
            make_pod(
                "ml", "demerge", c.node,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "1",
                },
                containers=[{"name": "jax"}],
            )
        )
        assert wait_until(
            lambda: c.manager.sitter.get_pod("ml", "demerge") is not None
        )
        core_ids = [core_device_id(1, u) for u in range(50)]
        mem_ids = [mem_device_id(1, u) for u in range(1024)]
        c.kubelet.kubelet_allocate_flow(
            CORE_ENDPOINT, "ml", "demerge", "jax", ResourceTPUCore, core_ids
        )
        c.kubelet.kubelet_allocate_flow(
            MEM_ENDPOINT, "ml", "demerge", "jax", ResourceTPUMemory, mem_ids
        )
        core_hash = Device(core_ids, ResourceTPUCore).hash
        mem_hash = Device(mem_ids, ResourceTPUMemory).hash
        alloc_dir = str(c.tmp / "alloc")
        mem_spec_path = os.path.join(alloc_dir, f"{mem_hash}.json")

        # merged: the mem spec names the core allocation too
        merged = json.load(open(mem_spec_path))
        assert "ELASTIC_TPU_CORE_UNITS" in merged["env"]
        assert ResourceTPUCore in merged["resources"]

        owner = PodContainer("ml", "demerge", "jax")
        c.manager.plugin.core.remove_alloc_spec(core_hash, owner=owner)

        assert not os.path.exists(os.path.join(alloc_dir, f"{core_hash}.json"))
        demerged = json.load(open(mem_spec_path))
        assert "ELASTIC_TPU_CORE_UNITS" not in demerged["env"], (
            "sibling spec still carries the released resource's env"
        )
        assert demerged["resources"] == [ResourceTPUMemory]
        # its own content is intact
        assert demerged["env"]["ELASTIC_TPU_HBM_LIMIT_BYTES"] == str(1024**3)
        assert demerged["device_paths"] == ["/dev/accel1"]
    finally:
        c.stop()


def test_dual_resource_concurrent_prestarts_still_merge(tmp_path):
    """Core and memory PreStarts racing for the same container must not
    miss each other's spec (the bind lock spans sibling discovery, spec
    write, and the storage save that publishes the allocation)."""
    import threading

    from elastic_tpu_agent.common import ResourceTPUCore
    from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, core_device_id

    c = Cluster(tmp_path)
    c.start()
    try:
        c.apiserver.upsert_pod(
            make_pod(
                "ml", "race", c.node,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "0",
                },
                containers=[{"name": "jax"}],
            )
        )
        assert wait_until(
            lambda: c.manager.sitter.get_pod("ml", "race") is not None
        )
        core_ids = [core_device_id(0, u) for u in range(50)]
        mem_ids = [mem_device_id(0, u) for u in range(1024)]
        errs = []

        def flow(endpoint, resource, ids):
            try:
                c.kubelet.kubelet_allocate_flow(
                    endpoint, "ml", "race", "jax", resource, ids
                )
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t1 = threading.Thread(
            target=flow, args=(CORE_ENDPOINT, ResourceTPUCore, core_ids)
        )
        t2 = threading.Thread(
            target=flow, args=(MEM_ENDPOINT, ResourceTPUMemory, mem_ids)
        )
        t1.start(); t2.start(); t1.join(30); t2.join(30)
        assert not errs, errs

        alloc_dir = str(c.tmp / "alloc")
        for dev in (Device(core_ids, ResourceTPUCore),
                    Device(mem_ids, ResourceTPUMemory)):
            spec = json.load(
                open(os.path.join(alloc_dir, f"{dev.hash}.json"))
            )
            assert spec["env"]["ELASTIC_TPU_CORE_UNITS"] == "50"
            assert spec["env"]["ELASTIC_TPU_HBM_LIMIT_BYTES"] == str(
                1024 * 1024 * 1024
            )
        # both allocation records survived the racing read-modify-write
        info = c.manager.storage.load("ml", "race")
        assert set(info.allocations["jax"]) == {
            ResourceTPUCore, ResourceTPUMemory
        }
    finally:
        c.stop()


def test_scheduler_spread_carries_all_chips_on_nri_path(tmp_path):
    """Scheduler spread (annotation names MORE chips than Allocate's
    minimum packing): the bind must materialize EVERY annotated chip
    into the alloc spec, and the NRI adjustment must carry a
    LinuxDevice (device-cgroup allow) for each — Allocate's
    DeviceSpec fast path only covered its ceil(units/chip) guess.

    The hooks.d path cannot fix up the cgroup after Allocate (mknod
    adds nodes but no allow rules for non-privileged containers);
    that limitation is documented in docs/operations.md — NRI is the
    supported path for spread placements."""
    from elastic_tpu_agent.nri import adjustment_from_spec
    from elastic_tpu_agent.common import ResourceTPUCore
    from elastic_tpu_agent.plugins.tpushare import (
        CORE_ENDPOINT,
        core_device_id,
    )

    c = Cluster(tmp_path)
    c.start()
    try:
        # 40 core-units => Allocate assumes ceil(40/100) = 1 chip, but
        # the scheduler spread the request over chips 0,2,3
        c.apiserver.upsert_pod(
            make_pod(
                "ml", "spread", c.node,
                annotations={
                    AnnotationAssumed: "true",
                    container_annotation("jax"): "0,2,3",
                },
                containers=[{"name": "jax"}],
            )
        )
        assert wait_until(
            lambda: c.manager.sitter.get_pod("ml", "spread") is not None
        )
        ids = [core_device_id(0, u) for u in range(40)]
        c.kubelet.kubelet_allocate_flow(
            CORE_ENDPOINT, "ml", "spread", "jax", ResourceTPUCore, ids
        )
        dev_hash = Device(ids, ResourceTPUCore).hash
        spec_path = os.path.join(
            str(c.tmp / "alloc"), f"{dev_hash}.json"
        )
        assert os.path.exists(spec_path)
        spec = json.load(open(spec_path))
        # the bind honored the SCHEDULER's placement, not the guess
        assert spec["chip_indexes"] == [0, 2, 3]
        assert len(spec["device_paths"]) == 3

        # NRI: every spread chip becomes a LinuxDevice entry (cgroup
        # allow), densely renumbered for the container
        spec["device_paths"] = ["/dev/null"] * 3  # stand-in chardevs
        adjust = adjustment_from_spec(spec)
        devs = [(d.path, d.type) for d in adjust.linux.devices]
        assert devs == [
            ("/dev/accel0", "c"),
            ("/dev/accel1", "c"),
            ("/dev/accel2", "c"),
        ]
        st = os.stat("/dev/null")
        assert all(
            d.major == os.major(st.st_rdev)
            and d.minor == os.minor(st.st_rdev)
            for d in adjust.linux.devices
        )
    finally:
        c.stop()
