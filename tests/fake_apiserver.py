"""Fake Kubernetes apiserver: the tiny surface the agent touches.

Serves list/watch/get pods (node fieldSelector honored) and get node over
plain HTTP, enough to drive the Sitter's informer loop and the GC's
apiserver-NotFound checks hermetically.

Hardened for thousand-pod fleets (scale harness, sim/scale.py):

- pod LISTs are PAGINATED server-side: ``limit``/``continue`` are
  honored and a ``max_page_size`` cap is ENFORCED even when the client
  asks for more (or for nothing) — so a client that forgets to follow
  ``continue`` sees truncated lists in tests instead of silently
  working against an unrealistically chatty fake;
- every request is counted in ``request_counts`` by operation kind
  (``pod_list``, ``pod_list_pages``, ``pod_watch``, ``pod_get``,
  ``event_post``, ``crd_*``, ...), so request amplification is
  assertable AT THE SOURCE rather than inferred from client-side
  counters;
- first-class BROWNOUT injection (``set_brownout``/``clear_brownout``):
  a seeded per-operation error rate + latency window, togglable
  mid-run, replacing the ad-hoc monkeypatching chaos tests used to do.
  Browned requests answer 503 ServiceUnavailable (the real apiserver's
  overload answer, which KubeClient surfaces as KubeError — NEVER
  NotFound, so GC cannot misread an outage as deletion) and are
  counted under ``<op>_failed`` while served ones keep counting under
  ``<op>`` — failed-vs-served is distinguishable at the source.
"""

from __future__ import annotations

import json
import queue
import random
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class FakeAPIServer:
    # Server-side pagination cap on pod LISTs: pages never exceed this
    # many items regardless of the client's ``limit`` (kube-apiservers
    # cap page sizes the same way). Small enough that the scale
    # harness's fleets actually exercise multi-page listing.
    DEFAULT_MAX_PAGE_SIZE = 500

    def __init__(self, max_page_size: int = DEFAULT_MAX_PAGE_SIZE) -> None:
        self._lock = threading.Lock()
        self._pods: Dict[Tuple[str, str], dict] = {}
        self._nodes: Dict[str, dict] = {}
        self._crds: Dict[str, dict] = {}  # ElasticTPU objects by name
        self._rv = 0
        self._events: List[tuple] = []  # (rv, event) log for watch replay
        self.core_events: List[dict] = []  # POSTed core/v1 Event objects
        self._watchers: List[queue.Queue] = []
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.max_page_size = max(1, max_page_size)
        # operation kind -> requests served; the scale harness divides
        # these by binds for apiserver-side request amplification.
        self.request_counts: Dict[str, int] = {}
        # Continuation snapshots: a real apiserver's continue token is
        # pinned to the resourceVersion of the FIRST page — later pages
        # never skip or duplicate objects because of concurrent
        # writes. Token = "<snap_id>:<offset>" over a frozen key list;
        # keys resolve to current objects (deleted ones drop out, which
        # is within real list semantics). Bounded: abandoned snapshots
        # age out.
        self._list_snapshots: Dict[int, Tuple[list, str]] = {}
        self._snap_seq = 0
        # Active brownout (None = healthy). Set/replaced/cleared under
        # the lock so a mid-run toggle takes effect on the next request.
        self._brownout: Optional[dict] = None

    # -- brownout injection (chaos-matrix seam, sim/chaos.py) -----------------

    def set_brownout(
        self,
        ops=None,
        error_rate: float = 0.0,
        latency_s: float = 0.0,
        seed: int = 0,
    ) -> None:
        """Brown the apiserver out: every subsequent request whose
        operation kind is in ``ops`` (None = every kind except
        ``pod_watch``) is delayed ``latency_s`` and then fails with 503
        with probability ``error_rate``, decided by a private
        ``random.Random(seed)`` stream — same seed, same request
        sequence, same failures. Replaces any active brownout
        (togglable mid-run); ``clear_brownout()`` heals instantly."""
        if not 0.0 <= error_rate <= 1.0:
            raise ValueError(f"error_rate out of [0,1]: {error_rate}")
        with self._lock:
            self._brownout = {
                "ops": frozenset(ops) if ops is not None else None,
                "error_rate": float(error_rate),
                "latency_s": max(0.0, float(latency_s)),
                "rng": random.Random(seed),
                "failed": 0,
                "delayed": 0,
            }

    def clear_brownout(self) -> Optional[dict]:
        """End the brownout; returns its stats (failed/delayed counts)."""
        with self._lock:
            b, self._brownout = self._brownout, None
            if b is None:
                return None
            return {"failed": b["failed"], "delayed": b["delayed"]}

    def _brownout_decide(self, kind: str) -> Tuple[float, bool]:
        """(delay_s, fail) for one request of ``kind`` under the active
        brownout — (0, False) when healthy or the kind isn't browned.
        The rng draw happens under the lock: concurrent handler threads
        consume the seeded stream in arrival order, which is as
        deterministic as a threaded server can be (single-threaded
        drivers get exact replay)."""
        with self._lock:
            b = self._brownout
            if b is None or (b["ops"] is not None and kind not in b["ops"]):
                return 0.0, False
            fail = b["rng"].random() < b["error_rate"]
            if fail:
                b["failed"] += 1
            if b["latency_s"] > 0:
                b["delayed"] += 1
            return b["latency_s"], fail

    def _snapshot_page(self, node: str, cont: str, limit: int):
        """(keys_page, rv, next_continue) for one paginated pod LIST."""
        with self._lock:
            if cont:
                try:
                    snap_id, _, off = cont.partition(":")
                    snap_id, offset = int(snap_id), int(off)
                except ValueError:
                    snap_id, offset = -1, 0
                keys, rv = self._list_snapshots.get(snap_id, (None, ""))
                if keys is None:
                    return [], str(self._rv), None  # expired: end the list
            else:
                keys = sorted(
                    key for key, p in self._pods.items()
                    if not node
                    or p.get("spec", {}).get("nodeName") == node
                )
                rv = str(self._rv)
                offset = 0
                snap_id = None
                if len(keys) > limit:
                    self._snap_seq += 1
                    snap_id = self._snap_seq
                    self._list_snapshots[snap_id] = (keys, rv)
                    for old in [
                        s for s in self._list_snapshots
                        if s <= self._snap_seq - 32
                    ]:
                        del self._list_snapshots[old]
            page = keys[offset:offset + limit]
            items = [
                self._pods[k] for k in page if k in self._pods
            ]
            next_cont = None
            if snap_id is not None and offset + limit < len(keys):
                next_cont = f"{snap_id}:{offset + limit}"
            return items, rv, next_cont

    def _count(self, kind: str, n: int = 1) -> None:
        with self._lock:
            self.request_counts[kind] = self.request_counts.get(kind, 0) + n

    def requests_total(self) -> int:
        """All requests served, watches excluded (a watch is one
        long-lived connection, not per-object traffic)."""
        with self._lock:
            return sum(
                v for k, v in self.request_counts.items()
                if k not in ("pod_watch",)
            )

    # -- state manipulation (test driver side) --------------------------------

    def upsert_pod(self, pod: dict) -> None:
        key = (pod["metadata"]["namespace"], pod["metadata"]["name"])
        with self._lock:
            self._rv += 1
            pod.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            etype = "MODIFIED" if key in self._pods else "ADDED"
            self._pods[key] = pod
            self._notify({"type": etype, "object": pod})

    def has_pod(self, namespace: str, name: str) -> bool:
        with self._lock:
            return (namespace, name) in self._pods

    def get_pod(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            pod = self._pods.get((namespace, name))
            return json.loads(json.dumps(pod)) if pod is not None else None

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop((namespace, name), None)
            if pod is not None:
                self._rv += 1
                self._notify({"type": "DELETED", "object": pod})

    def add_node(self, name: str, annotations: Optional[dict] = None) -> None:
        with self._lock:
            self._nodes[name] = {
                "metadata": {"name": name, "annotations": annotations or {}}
            }

    def annotate_node(self, name: str, key: str, value: Optional[str]) -> None:
        """Set (or, with ``value=None``, remove) one node annotation —
        the driver side of the operator-requested drain trigger."""
        with self._lock:
            node = self._nodes.setdefault(
                name, {"metadata": {"name": name, "annotations": {}}}
            )
            ann = node["metadata"].setdefault("annotations", {})
            if value is None:
                ann.pop(key, None)
            else:
                ann[key] = value

    def _notify(self, event: dict) -> None:
        self._events.append((self._rv, event))
        del self._events[:-1000]
        for q in list(self._watchers):
            q.put(event)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> str:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Responses go out as two writes (headers, body); with Nagle
            # on, the body segment waits out the client's delayed ACK —
            # ~40ms PER RESPONSE, which made every CRD/event write look
            # 40ms slow and wrecked drain-rate numbers.
            disable_nagle_algorithm = True

            def log_message(self, *args):  # noqa: D102
                pass

            def _json(self, code: int, body: dict) -> None:
                raw = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _gate(self, kind: str) -> bool:
                """Count one request of ``kind``, applying the active
                brownout: delay first (slow apiserver), then 503 with
                the browned probability. True = this request was
                answered with the failure and the caller must return;
                False = proceed (counted as served)."""
                delay_s, fail = outer._brownout_decide(kind)
                if delay_s > 0:
                    time.sleep(delay_s)
                if fail:
                    outer._count(kind + "_failed")
                    self._json(503, {
                        "kind": "Status", "code": 503,
                        "reason": "ServiceUnavailable",
                        "message": "injected brownout",
                    })
                    return True
                outer._count(kind)
                return False

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                parts = [p for p in parsed.path.split("/") if p]
                # /api/v1/pods
                if parts[:3] == ["api", "v1", "pods"]:
                    node = params.get("fieldSelector", "").partition("=")[2]
                    if params.get("watch") == "true":
                        outer._count("pod_watch")
                        return self._watch(node, params)
                    if self._gate("pod_list_pages"):
                        return
                    cont = params.get("continue", "")
                    if not cont:
                        # pages of one logical LIST count once
                        outer._count("pod_list")
                    try:
                        want = int(params.get("limit", "") or 0)
                    except ValueError:
                        want = 0
                    # ENFORCED server-side: the cap applies even to
                    # clients that ask for more, or for nothing.
                    limit = min(
                        want if want > 0 else outer.max_page_size,
                        outer.max_page_size,
                    )
                    page, rv, next_cont = outer._snapshot_page(
                        node, cont, limit
                    )
                    meta = {"resourceVersion": rv}
                    if next_cont is not None:
                        meta["continue"] = next_cont
                    return self._json(
                        200,
                        {
                            "kind": "PodList",
                            "items": page,
                            "metadata": meta,
                        },
                    )
                # /api/v1/namespaces/{ns}/pods/{name}
                if (
                    len(parts) == 6
                    and parts[:3] == ["api", "v1", "namespaces"]
                    and parts[4] == "pods"
                ):
                    ns, name = parts[3], parts[5]
                    if self._gate("pod_get"):
                        return
                    with outer._lock:
                        pod = outer._pods.get((ns, name))
                    if pod is None:
                        return self._json(404, {"kind": "Status", "code": 404})
                    return self._json(200, pod)
                # /api/v1/nodes/{name}
                if len(parts) == 4 and parts[:3] == ["api", "v1", "nodes"]:
                    if self._gate("node_get"):
                        return
                    with outer._lock:
                        node_obj = outer._nodes.get(parts[3])
                    if node_obj is None:
                        return self._json(404, {"kind": "Status", "code": 404})
                    return self._json(200, node_obj)
                # /apis/elasticgpu.io/v1alpha1/elastictpus[/name]
                if self._crd_parts(parts) is not None:
                    name = self._crd_parts(parts)
                    if self._gate("crd_list" if name == "" else "crd_get"):
                        return
                    with outer._lock:
                        if name == "":
                            items = list(outer._crds.values())
                        else:
                            obj = outer._crds.get(name)
                    if name == "":
                        selector = params.get("labelSelector", "")
                        if selector and "=" in selector:
                            k, _, v = selector.partition("=")
                            items = [
                                m for m in items
                                if m.get("metadata", {})
                                .get("labels", {})
                                .get(k) == v
                            ]
                        return self._json(200, {"items": items})
                    if obj is None:
                        return self._json(404, {"kind": "Status", "code": 404})
                    return self._json(200, obj)
                return self._json(404, {"kind": "Status", "code": 404})

            @staticmethod
            def _crd_parts(parts):
                """For a CRD path return the resource name ("" for the
                collection); None when this is not the elastictpus API."""
                if parts[:4] != [
                    "apis", "elasticgpu.io", "v1alpha1", "elastictpus",
                ]:
                    return None
                if len(parts) == 4:
                    return ""
                if len(parts) == 5:
                    return parts[4]
                return None

            @staticmethod
            def _crd_status_name(parts):
                """Name for /apis/.../elastictpus/<name>/status, else None."""
                if (
                    len(parts) == 6
                    and parts[:4]
                    == ["apis", "elasticgpu.io", "v1alpha1", "elastictpus"]
                    and parts[5] == "status"
                ):
                    return parts[4]
                return None

            def _read_body(self):
                length = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(length)) if length else {}

            def do_POST(self):  # noqa: N802
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                # core/v1 Event create: /api/v1/namespaces/<ns>/events
                if (
                    len(parts) == 5
                    and parts[:3] == ["api", "v1", "namespaces"]
                    and parts[4] == "events"
                ):
                    obj = self._read_body()
                    if self._gate("event_post"):
                        return
                    with outer._lock:
                        outer._rv += 1
                        obj.setdefault("metadata", {})[
                            "resourceVersion"
                        ] = str(outer._rv)
                        outer.core_events.append(obj)
                    return self._json(201, obj)
                # Creates go to the collection URL only; a real apiserver
                # rejects POST-to-named-resource and duplicate creates.
                if self._crd_parts(parts) == "":
                    obj = self._read_body()
                    if self._gate("crd_create"):
                        return
                    # Status subresource semantics (the CRD declares
                    # `subresources: status: {}`): a real apiserver DROPS
                    # status on main-endpoint creates.
                    obj["status"] = {}
                    name = obj.get("metadata", {}).get("name", "")
                    with outer._lock:
                        exists = name in outer._crds
                        if not exists:
                            outer._rv += 1
                            obj.setdefault("metadata", {})[
                                "resourceVersion"
                            ] = str(outer._rv)
                            outer._crds[name] = obj
                    if exists:
                        return self._json(
                            409, {"kind": "Status", "code": 409,
                                  "reason": "AlreadyExists"}
                        )
                    return self._json(201, obj)
                return self._json(404, {"kind": "Status", "code": 404})

            @staticmethod
            def _rv_error(body, existing):
                """Custom resources never allow unconditional updates: a
                missing resourceVersion is 422 Invalid, a stale one is 409
                Conflict (apiextensions strategy semantics). Returns a
                (code, body) error response, or None when the update may
                proceed."""
                rv = body.get("metadata", {}).get("resourceVersion", "")
                if not rv:
                    return (
                        422, {"kind": "Status", "code": 422,
                              "reason": "Invalid",
                              "message": "metadata.resourceVersion: "
                                         "must be specified for an update"},
                    )
                if rv != existing.get("metadata", {}).get(
                    "resourceVersion", ""
                ):
                    return (
                        409, {"kind": "Status", "code": 409,
                              "reason": "Conflict"},
                    )
                return None

            def do_PUT(self):  # noqa: N802
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                status_name = self._crd_status_name(parts)
                if status_name:
                    # PUT /status: only the status field is applied.
                    obj = self._read_body()
                    if self._gate("crd_status_update"):
                        return
                    err = updated = None
                    with outer._lock:
                        existing = outer._crds.get(status_name)
                        if existing is None:
                            err = (404, {"kind": "Status", "code": 404})
                        else:
                            err = self._rv_error(obj, existing)
                        if err is None:
                            outer._rv += 1
                            existing["status"] = obj.get("status", {})
                            existing["metadata"]["resourceVersion"] = str(
                                outer._rv
                            )
                            updated = existing
                    if err is not None:
                        return self._json(*err)
                    return self._json(200, updated)
                name = self._crd_parts(parts)
                if name:
                    obj = self._read_body()
                    if self._gate("crd_update"):
                        return
                    err = None
                    with outer._lock:
                        prior = outer._crds.get(name)
                        if prior is None:
                            err = (404, {"kind": "Status", "code": 404})
                        else:
                            err = self._rv_error(obj, prior)
                        if err is None:
                            # Main-endpoint update: status is PRESERVED from
                            # the stored object, never taken from the request
                            # (real apiserver behavior with the status
                            # subresource).
                            obj["status"] = prior.get("status", {})
                            outer._rv += 1
                            obj.setdefault("metadata", {})[
                                "resourceVersion"
                            ] = str(outer._rv)
                            outer._crds[name] = obj
                    if err is not None:
                        return self._json(*err)
                    return self._json(200, obj)
                return self._json(404, {"kind": "Status", "code": 404})

            def do_PATCH(self):  # noqa: N802
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                # merge-patch a pod: /api/v1/namespaces/<ns>/pods/<name>
                # (only metadata.annotations merge semantics are
                # implemented — the one shape the agent sends: the drain
                # orchestrator's elasticgpu.io/draining stamp; a None
                # value deletes the key, per RFC 7386)
                if (
                    len(parts) == 6
                    and parts[:3] == ["api", "v1", "namespaces"]
                    and parts[4] == "pods"
                ):
                    ns, name = parts[3], parts[5]
                    patch = self._read_body()
                    if self._gate("pod_patch"):
                        return
                    with outer._lock:
                        pod = outer._pods.get((ns, name))
                        if pod is None:
                            return self._json(
                                404, {"kind": "Status", "code": 404}
                            )
                        ann_patch = (
                            patch.get("metadata", {}) or {}
                        ).get("annotations")
                        if ann_patch is not None:
                            ann = pod.setdefault("metadata", {}).setdefault(
                                "annotations", {}
                            )
                            for k, v in ann_patch.items():
                                if v is None:
                                    ann.pop(k, None)
                                else:
                                    ann[k] = v
                        outer._rv += 1
                        pod["metadata"]["resourceVersion"] = str(outer._rv)
                        outer._notify({"type": "MODIFIED", "object": pod})
                        return self._json(200, pod)
                return self._json(404, {"kind": "Status", "code": 404})

            def do_DELETE(self):  # noqa: N802
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                name = self._crd_parts(parts)
                if name:
                    if self._gate("crd_delete"):
                        return
                    with outer._lock:
                        outer._crds.pop(name, None)
                    return self._json(200, {"kind": "Status", "code": 200})
                return self._json(404, {"kind": "Status", "code": 404})

            def _watch(self, node: str, params: dict) -> None:
                timeout = float(params.get("timeoutSeconds", "30"))
                try:
                    since_rv = int(params.get("resourceVersion", "0") or 0)
                except ValueError:
                    since_rv = 0
                q: queue.Queue = queue.Queue()
                with outer._lock:
                    # Replay events after the client's resourceVersion so
                    # nothing falls in the list->watch gap (real apiserver
                    # semantics).
                    for rv, event in outer._events:
                        if rv > since_rv:
                            q.put(event)
                    outer._watchers.append(q)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def send_chunk(data: bytes) -> None:
                        self.wfile.write(hex(len(data))[2:].encode())
                        self.wfile.write(b"\r\n")
                        self.wfile.write(data)
                        self.wfile.write(b"\r\n")
                        self.wfile.flush()

                    import time

                    end = time.monotonic() + timeout
                    while time.monotonic() < end:
                        try:
                            event = q.get(timeout=0.2)
                        except queue.Empty:
                            continue
                        obj = event.get("object", {})
                        if node and obj.get("spec", {}).get("nodeName") != node:
                            continue
                        send_chunk(
                            (json.dumps(event) + "\n").encode()
                        )
                    send_chunk(b"")  # terminating chunk
                except (BrokenPipeError, ConnectionResetError):
                    pass
                finally:
                    with outer._lock:
                        if q in outer._watchers:
                            outer._watchers.remove(q)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="fake-apiserver"
        )
        self._thread.start()
        host, port = self._httpd.server_address
        return f"http://{host}:{port}"

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()


def make_pod(
    namespace: str,
    name: str,
    node: str,
    annotations: Optional[dict] = None,
    containers: Optional[list] = None,
) -> dict:
    return {
        "metadata": {
            "namespace": namespace,
            "name": name,
            "annotations": annotations or {},
        },
        "spec": {
            "nodeName": node,
            "containers": containers or [{"name": "main"}],
        },
    }
