"""Chip health monitoring: a chip losing its device node flips its fake
devices to Unhealthy on the live ListAndWatch stream, recovery flips them
back, and both transitions surface as node Events and metrics.

The reference got device health from NVML XIDs implicitly and never
propagated it; TPU has no NVML, so health is an agent feature here
(operator.healthy_indexes -> plugin.apply_health -> ListAndWatch)."""

import os
import queue
import threading

import pytest

from elastic_tpu_agent import rpc
from elastic_tpu_agent.plugins.tpushare import CORE_ENDPOINT, MEM_ENDPOINT

from test_e2e import Cluster, wait_until
from test_plugins import harness  # noqa: F401 - reuse the plugin harness


def _stream_responses(client, out_queue, stop):
    try:
        for resp in client.list_and_watch():
            out_queue.put(resp)
            if stop.is_set():
                return
    except Exception:  # noqa: BLE001 - stream torn down at test end
        pass


def _health_by_chip(resp):
    by_chip = {}
    for dev in resp.devices:
        chip = int(dev.ID.split("-")[2])
        by_chip.setdefault(chip, set()).add(dev.health)
    return by_chip


def test_unhealthy_chip_propagates_to_listandwatch(harness):  # noqa: F811
    client = harness.kubelet.plugin_client(CORE_ENDPOINT)
    q: "queue.Queue" = queue.Queue()
    stop = threading.Event()
    t = threading.Thread(
        target=_stream_responses, args=(client, q, stop), daemon=True
    )
    t.start()
    first = q.get(timeout=10)
    assert all(
        h == {rpc.HEALTHY} for h in _health_by_chip(first).values()
    )

    # chip 2 dies
    harness.operator.set_unhealthy({2})
    assert harness.plugin.health_once()
    resp = q.get(timeout=10)
    by_chip = _health_by_chip(resp)
    assert by_chip[2] == {rpc.UNHEALTHY}
    for chip in (0, 1, 3):
        assert by_chip[chip] == {rpc.HEALTHY}

    # chip 2 recovers
    harness.operator.set_unhealthy(set())
    assert harness.plugin.health_once()
    resp = q.get(timeout=10)
    assert all(
        h == {rpc.HEALTHY} for h in _health_by_chip(resp).values()
    )
    stop.set()


def test_health_poll_idempotent_when_unchanged(harness):  # noqa: F811
    assert not harness.plugin.health_once()
    harness.operator.set_unhealthy({1})
    assert harness.plugin.health_once()
    assert not harness.plugin.health_once()  # no change -> no resend


def test_memory_plugin_tracks_health_too(harness):  # noqa: F811
    harness.operator.set_unhealthy({0})
    harness.plugin.health_once()
    mem_list = harness.plugin.memory._device_list()
    unhealthy = {d.ID for d in mem_list if d.health == rpc.UNHEALTHY}
    assert unhealthy and all(i.startswith("tpu-mem-0-") for i in unhealthy)


@pytest.fixture()
def cluster(tmp_path):
    c = Cluster(tmp_path)
    c.start()
    yield c
    c.stop()


def test_health_transitions_emit_node_events(cluster):
    plugin = cluster.manager.plugin
    cluster.manager.operator.set_unhealthy({1, 3})
    plugin.health_once()
    cluster.manager.operator.set_unhealthy({1})
    plugin.health_once()
    assert cluster.manager.events.flush()
    evs = cluster.apiserver.core_events
    bad = [e for e in evs if e["reason"] == "TPUChipUnhealthy"]
    good = [e for e in evs if e["reason"] == "TPUChipHealthy"]
    assert len(bad) == 2 and all(e["type"] == "Warning" for e in bad)
    assert {e["message"].split()[2] for e in bad} == {"1", "3"}
    assert len(good) == 1 and "chip 3 recovered" in good[0]["message"]


def test_bound_pod_warned_when_its_chip_dies(cluster):
    from elastic_tpu_agent.common import (
        AnnotationAssumed,
        ResourceTPUCore,
        container_annotation,
    )
    from elastic_tpu_agent.plugins.tpushare import core_device_id
    from fake_apiserver import make_pod

    cluster.apiserver.upsert_pod(
        make_pod(
            "default", "victim", cluster.node,
            annotations={
                AnnotationAssumed: "true",
                container_annotation("jax"): "2",
            },
            containers=[{"name": "jax"}],
        )
    )
    assert wait_until(
        lambda: cluster.manager.sitter.get_pod("default", "victim")
        is not None
    )
    ids = [core_device_id(2, i) for i in range(100)]
    cluster.kubelet.kubelet_allocate_flow(
        CORE_ENDPOINT, "default", "victim", "jax", ResourceTPUCore, ids
    )
    cluster.manager.operator.set_unhealthy({2})
    cluster.manager.plugin.health_once()
    assert cluster.manager.events.flush()
    pod_warnings = [
        e for e in cluster.apiserver.core_events
        if e["reason"] == "TPUChipUnhealthy"
        and e["involvedObject"]["kind"] == "Pod"
    ]
    assert len(pod_warnings) == 1
    assert pod_warnings[0]["involvedObject"]["name"] == "victim"
    assert "chip(s) 2" in pod_warnings[0]["message"]


def test_tpuvm_health_follows_device_nodes(tmp_path):
    """The tpu-vm operator's health source is /dev/accel* presence."""
    from elastic_tpu_agent.tpu.tpuvm import TPUVMOperator

    scan = tmp_path / "hostdev"
    scan.mkdir()
    for i in range(4):
        (scan / f"accel{i}").touch()
    op = TPUVMOperator(
        str(tmp_path / "dev"), host_dev_scan_root=str(scan),
        metadata=lambda attr: None,
        env={"TPU_ACCELERATOR_TYPE": "v5litepod-4"},
    )
    os.makedirs(str(tmp_path / "dev"), exist_ok=True)
    assert op.healthy_indexes() == {0, 1, 2, 3}
    (scan / "accel2").unlink()
    assert op.healthy_indexes() == {0, 1, 3}


def test_health_loop_runs_periodically(tmp_path):
    """The manager-started loop picks up operator changes by itself."""
    from elastic_tpu_agent.plugins.tpushare import TPUSharePlugin

    period = TPUSharePlugin.HEALTH_PERIOD_S
    TPUSharePlugin.HEALTH_PERIOD_S = 0.05  # fast poll for the test
    c = Cluster(tmp_path)
    try:
        c.start()
        c.manager.operator.set_unhealthy({2})
        assert wait_until(
            lambda: c.manager.plugin.core._unhealthy_chips == {2}
        )
    finally:
        TPUSharePlugin.HEALTH_PERIOD_S = period
        c.stop()


def _tpuvm_op(tmp_path, **kw):
    from elastic_tpu_agent.tpu.tpuvm import TPUVMOperator

    scan = tmp_path / "hostdev"
    scan.mkdir(exist_ok=True)
    for i in range(4):
        (scan / f"accel{i}").touch()
    os.makedirs(str(tmp_path / "dev"), exist_ok=True)
    kw.setdefault("metadata", lambda attr: None)
    kw.setdefault("env", {"TPU_ACCELERATOR_TYPE": "v5litepod-4"})
    kw.setdefault("maintenance", lambda: "NONE")
    return TPUVMOperator(
        str(tmp_path / "dev"), host_dev_scan_root=str(scan), **kw
    )


def test_maintenance_event_no_longer_fails_health(tmp_path):
    """A GCE maintenance event does NOT flip chips unhealthy any more —
    that stranded resident workloads with no checkpoint signal. The
    value is surfaced via maintenance_event() for the drain
    orchestrator (drain.py), which responds with cordon + graceful
    drain instead."""
    state = {"event": "NONE"}
    op = _tpuvm_op(tmp_path, maintenance=lambda: state["event"])
    op._maint_next_poll = 0.0
    assert op.healthy_indexes() == {0, 1, 2, 3}

    state["event"] = "MIGRATE_ON_HOST_MAINTENANCE"
    op._maint_next_poll = 0.0
    assert op.maintenance_event() == "MIGRATE_ON_HOST_MAINTENANCE"
    assert op.healthy_indexes() == {0, 1, 2, 3}, (
        "maintenance must not fail health — the drain owns the response"
    )
    assert 0 not in op.health_reasons()

    state["event"] = "NONE"
    op._maint_next_poll = 0.0
    assert op.maintenance_event() == "NONE"
    assert op.healthy_indexes() == {0, 1, 2, 3}


def test_maintenance_fetch_failure_backs_off(tmp_path):
    """Non-GCE hosts (kind, CI) have no metadata endpoint: one failed
    fetch must back off instead of paying the timeout on every drain
    poll tick."""
    calls = {"n": 0}

    def failing():
        calls["n"] += 1
        return None

    op = _tpuvm_op(tmp_path, maintenance=failing)
    assert op.maintenance_event() is None
    assert op.maintenance_event() is None
    assert calls["n"] == 1, "no backoff after transport failure"


def test_preempted_endpoint_and_backoff(tmp_path):
    """The spot-preemption poll: TRUE reads preempted; an unreachable
    endpoint reads False and backs off like the maintenance poll."""
    state = {"value": "FALSE"}
    calls = {"n": 0}

    def fetch():
        calls["n"] += 1
        return state["value"]

    op = _tpuvm_op(tmp_path, preemption=fetch)
    assert op.preempted() is False
    state["value"] = "TRUE"
    op._preempt_next_poll = 0.0
    assert op.preempted() is True
    # unreachable endpoint: cached False under the error backoff
    op2 = _tpuvm_op(tmp_path, preemption=lambda: None)
    assert op2.preempted() is False
    assert op2.preempted() is False


def test_maintenance_poll_ttl_env_override(tmp_path):
    """Satellite: the hardcoded poll TTL is configurable — constructor
    arg and ELASTIC_TPU_MAINTENANCE_POLL_TTL env override (tests/fast
    drain reaction)."""
    op = _tpuvm_op(
        tmp_path, maintenance=lambda: "NONE",
        env={
            "TPU_ACCELERATOR_TYPE": "v5litepod-4",
            "ELASTIC_TPU_MAINTENANCE_POLL_TTL": "0.01",
            "ELASTIC_TPU_MAINTENANCE_ERROR_BACKOFF": "0.02",
        },
    )
    assert op._maint_poll_ttl_s == 0.01
    assert op._maint_error_backoff_s == 0.02
    op2 = _tpuvm_op(
        tmp_path, maintenance=lambda: "NONE",
        maintenance_poll_ttl_s=1.5,
    )
    assert op2._maint_poll_ttl_s == 1.5


def test_sysfs_fatal_counter_marks_chip_unhealthy_sticky(tmp_path):
    """A rising fatal-error counter under /sys/class/accel/accelN flips
    the chip unhealthy and keeps it so (sticky) even if the counter stops
    moving; correctable counters are ignored; pre-existing nonzero values
    are baseline, not a signal."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel1" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("7\n")  # pre-existing count: baseline, not a fault
    correctable = err_dir / "aer_dev_correctable"
    correctable.write_text("0\n")

    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    assert op.healthy_indexes() == {0, 1, 2, 3}

    # correctable noise: ignored
    correctable.write_text("5000\n")
    assert op.healthy_indexes() == {0, 1, 2, 3}

    # fatal counter rises past baseline: chip 1 out, sticky
    fatal.write_text("8\n")
    assert op.healthy_indexes() == {0, 2, 3}
    assert "fatal" in op.health_reasons()[1]
    fatal.write_text("8\n")
    assert op.healthy_indexes() == {0, 2, 3}, "error chip must stay out"


def test_health_flip_reason_lands_in_node_event(tmp_path):
    """The health-flip reason travels through health_once into the
    TPUChipUnhealthy node event (the ListAndWatch machinery test already
    covers device flips; this pins the reason string). Driven by a
    rising sysfs fatal counter — maintenance events no longer fail
    health (the drain orchestrator owns that response)."""
    from elastic_tpu_agent.plugins.base import PluginConfig
    from elastic_tpu_agent.plugins.tpushare import TPUSharePlugin
    from elastic_tpu_agent.storage import Storage

    from fake_kubelet import FakeSitter

    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel1" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("0\n")
    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))

    class RecEvents:
        def __init__(self):
            self.node_events = []

        def node_event(self, reason, message, type_="Normal"):
            self.node_events.append((reason, message))

        def pod_event(self, *a, **k):
            pass

    events = RecEvents()
    config = PluginConfig(
        device_plugin_dir=str(tmp_path / "dp"),
        pod_resources_socket=str(tmp_path / "pr.sock"),
        operator=op,
        sitter=FakeSitter(),
        storage=Storage(str(tmp_path / "meta.db")),
        locator_factory=lambda r: None,
        events=events,
        extra={"alloc_spec_dir": str(tmp_path / "alloc")},
    )
    plugin = TPUSharePlugin(config)
    plugin.health_once()
    assert events.node_events == []

    fatal.write_text("3\n")  # chip 1's fatal counter rises past baseline
    assert plugin.health_once()
    assert len(events.node_events) == 1
    reason, message = events.node_events[0]
    assert reason == "TPUChipUnhealthy"
    assert "aer_dev_fatal" in message


def test_sysfs_counters_reachable_through_symlinks(tmp_path):
    """Real sysfs shape: /sys/class/accel/accelN is a symlink into
    /sys/devices/..., and accelN/device links to the PCI dir holding the
    AER counters — both must be traversed."""
    devices = tmp_path / "devices" / "platform" / "tpu1"
    pci = tmp_path / "devices" / "pci0000" / "0000:00:05.0"
    devices.mkdir(parents=True)
    pci.mkdir(parents=True)
    (devices / "device").symlink_to(pci)
    sys_root = tmp_path / "class_accel"
    sys_root.mkdir()
    (sys_root / "accel1").symlink_to(devices)
    fatal = pci / "aer_dev_fatal"
    fatal.write_text("0\n")

    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    assert op.healthy_indexes() == {0, 1, 2, 3}
    fatal.write_text("1\n")
    assert op.healthy_indexes() == {0, 2, 3}


_REAL_AER_FATAL = """\
TLP 0
FCP 0
CmpltTO 0
CmpltAbrt 0
UnxCmplt 0
RxOF 0
MalfTLP 0
ECRC 0
UnsupReq 0
ACSViol 0
UncorrIntErr 0
BlockedTLP 0
AtomicOpBlocked 0
TLPBlockedErr 0
PoisonTLPBlocked 0
TOTAL_ERR_FATAL 0
"""


def test_real_aer_table_format_is_parsed(tmp_path):
    """Real aer_dev_fatal/aer_dev_uncorrectable files are multi-line
    'ERROR_NAME count' tables, not single integers — the parse must read
    them or the health signal never fires in production (ADVICE r2/r3)."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel1" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text(_REAL_AER_FATAL)

    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    assert op.healthy_indexes() == {0, 1, 2, 3}
    # one malformed TLP: TOTAL_ERR_FATAL rises 0 -> 1
    fatal.write_text(
        _REAL_AER_FATAL.replace("MalfTLP 0", "MalfTLP 1")
                       .replace("TOTAL_ERR_FATAL 0", "TOTAL_ERR_FATAL 1")
    )
    assert op.healthy_indexes() == {0, 2, 3}
    assert "fatal" in op.health_reasons()[1]


def test_read_counter_file_shapes(tmp_path):
    from elastic_tpu_agent.tpu.tpuvm import read_counter_file

    p = tmp_path / "counter"
    p.write_text("42\n")
    assert read_counter_file(str(p)) == 42
    p.write_text(_REAL_AER_FATAL.replace("TOTAL_ERR_FATAL 0",
                                         "TOTAL_ERR_FATAL 3"))
    assert read_counter_file(str(p)) == 3  # TOTAL row preferred
    p.write_text("TLP 1\nFCP 2\n")        # no TOTAL row: sum
    assert read_counter_file(str(p)) == 3
    p.write_text("free-form text\n")
    assert read_counter_file(str(p)) is None
    p.write_text("")
    assert read_counter_file(str(p)) is None
    assert read_counter_file(str(tmp_path / "missing")) is None


def test_sticky_reason_survives_counter_rebaseline(tmp_path):
    """A chip held by the sticky error set must keep its specific reason
    even after its counter re-baselines (driver reload) — VERDICT r3
    weak #8."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel1" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("0\n")

    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    op.healthy_indexes()
    fatal.write_text("4\n")
    assert 1 not in op.healthy_indexes()
    specific = op.health_reasons()[1]
    assert "aer_dev_fatal" in specific and "4" in specific
    fatal.write_text("0\n")  # driver reload: counter resets
    assert 1 not in op.healthy_indexes()  # still sticky
    assert op.health_reasons()[1] == specific, (
        "re-baseline replaced the specific reason with a generic one"
    )


def test_sampler_flagged_chip_degrades_listandwatch(cluster):
    """ISSUE 2 acceptance: a chip the utilization sampler flags
    (telemetry failing) goes Unhealthy on the live ListAndWatch stream
    and recovers when telemetry comes back — without the operator itself
    ever reporting it broken."""
    client = cluster.kubelet.plugin_client(CORE_ENDPOINT)
    q: "queue.Queue" = queue.Queue()
    stop = threading.Event()
    threading.Thread(
        target=_stream_responses, args=(client, q, stop), daemon=True
    ).start()
    first = q.get(timeout=10)
    assert all(
        h == {rpc.HEALTHY} for h in _health_by_chip(first).values()
    )

    sampler = cluster.manager.sampler
    assert sampler is not None
    cluster.manager.operator.set_utilization({0: 5.0})
    cluster.manager.operator.fail_utilization({2}, reason="EIO on sysfs")
    for _ in range(sampler.unhealthy_after):
        sampler.sample_once()
    # the operator's own view stays clean — only the sampler flags
    assert cluster.manager.operator.healthy_indexes() == {0, 1, 2, 3}
    assert cluster.manager.plugin.health_once()
    resp = q.get(timeout=10)
    by_chip = _health_by_chip(resp)
    assert by_chip[2] == {rpc.UNHEALTHY}
    for chip in (0, 1, 3):
        assert by_chip[chip] == {rpc.HEALTHY}
    # the node event names the telemetry failure
    assert cluster.manager.events.flush()
    bad = [
        e for e in cluster.apiserver.core_events
        if e["reason"] == "TPUChipUnhealthy"
        and e["involvedObject"]["kind"] == "Node"
    ]
    assert bad and "EIO on sysfs" in bad[0]["message"]

    # telemetry recovers -> chip re-advertised Healthy
    cluster.manager.operator.set_utilization({0: 5.0, 2: 5.0})
    sampler.sample_once()
    assert cluster.manager.plugin.health_once()
    resp = q.get(timeout=10)
    assert all(
        h == {rpc.HEALTHY} for h in _health_by_chip(resp).values()
    )
    stop.set()


# -- TPUVMOperator.health_reasons / _maintenance_imminent (satellite) ---------


def test_maintenance_poll_respects_ttl(tmp_path):
    """_maintenance_imminent caches a successful fetch for the poll TTL —
    the 5s health tick must not hammer the metadata server."""
    calls = {"n": 0}

    def counting():
        calls["n"] += 1
        return "NONE"

    op = _tpuvm_op(tmp_path, maintenance=counting)
    assert op._maintenance_imminent() is False
    assert op._maintenance_imminent() is False
    assert calls["n"] == 1, "fetch not cached within the TTL"
    op._maint_next_poll = 0.0  # TTL expired
    assert op._maintenance_imminent() is False
    assert calls["n"] == 2


def test_maintenance_imminent_values(tmp_path):
    state = {"event": "NONE"}
    op = _tpuvm_op(tmp_path, maintenance=lambda: state["event"])
    for value, expected in (
        ("NONE", False),
        ("", False),
        ("MIGRATE_ON_HOST_MAINTENANCE", True),
        ("TERMINATE_ON_HOST_MAINTENANCE", True),
    ):
        state["event"] = value
        op._maint_next_poll = 0.0
        assert op._maintenance_imminent() is expected, value


def test_health_reasons_device_node_missing_and_recovery(tmp_path):
    """A chip whose /dev/accelN vanishes gets the 'device node missing'
    reason; the reason clears when the node returns."""
    op = _tpuvm_op(tmp_path)
    scan = tmp_path / "hostdev"
    assert op.healthy_indexes() == {0, 1, 2, 3}
    assert op.health_reasons() == {}
    (scan / "accel2").unlink()
    assert op.healthy_indexes() == {0, 1, 3}
    assert op.health_reasons() == {2: "device node missing"}
    (scan / "accel2").touch()
    assert op.healthy_indexes() == {0, 1, 2, 3}
    assert op.health_reasons() == {}


def test_health_reasons_degraded_counter_path(tmp_path):
    """A degraded (risen) fatal counter puts its specific reason in
    health_reasons; a recovered (reset) counter re-baselines without
    clearing the sticky reason (VERDICT r3 semantics, asserted through
    the public surface)."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel1" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("0\n")
    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    op.healthy_indexes()
    assert op.health_reasons() == {}
    fatal.write_text("3\n")  # degraded
    op.healthy_indexes()
    reasons = op.health_reasons()
    assert set(reasons) == {1}
    assert "aer_dev_fatal" in reasons[1] and "3" in reasons[1]
    fatal.write_text("0\n")  # "recovered" (driver reload reset)
    op.healthy_indexes()
    assert op.health_reasons()[1] == reasons[1], "sticky reason lost"
    # error_counters snapshot shows the raw current value for the doctor
    assert list(op.error_counters()[1].values()) == [0]


def test_health_reasons_unaffected_by_maintenance_event(tmp_path):
    """New contract (drain.py owns maintenance): an announced event
    neither fails chips nor pollutes health_reasons — only real causes
    (here a sticky counter chip) appear, before, during and after the
    event window."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel0" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("0\n")
    state = {"event": "NONE"}
    op = _tpuvm_op(
        tmp_path, maintenance=lambda: state["event"],
        sys_accel_root=str(sys_root),
    )
    op.healthy_indexes()
    fatal.write_text("1\n")  # chip 0 degrades before the event
    op.healthy_indexes()
    state["event"] = "MIGRATE_ON_HOST_MAINTENANCE"
    op._maint_next_poll = 0.0
    assert op.maintenance_event() == "MIGRATE_ON_HOST_MAINTENANCE"
    assert op.healthy_indexes() == {1, 2, 3}
    reasons = op.health_reasons()
    assert set(reasons) == {0}
    assert "aer_dev_fatal" in reasons[0]
    state["event"] = "NONE"
    op._maint_next_poll = 0.0
    assert op.healthy_indexes() == {1, 2, 3}
    assert set(op.health_reasons()) == {0}


def test_sysfs_counter_reset_rebaselines(tmp_path):
    """A driver reload zeroing the counter must re-baseline downward, or
    errors below the stale baseline would be masked forever."""
    sys_root = tmp_path / "sysaccel"
    err_dir = sys_root / "accel0" / "device"
    err_dir.mkdir(parents=True)
    fatal = err_dir / "aer_dev_fatal"
    fatal.write_text("7\n")

    op = _tpuvm_op(tmp_path, sys_accel_root=str(sys_root))
    assert 0 in op.healthy_indexes()          # 7 is baseline, not a fault
    fatal.write_text("0\n")                   # driver reload reset
    assert 0 in op.healthy_indexes()          # re-baselined at 0
    fatal.write_text("2\n")                   # 2 NEW fatal errors
    assert 0 not in op.healthy_indexes()
