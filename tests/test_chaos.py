"""Serve-the-ugly-day units: trace determinism, chaos scheduling,
the compound-invariant checker, and the pinned regressions behind them.

Fast tier (pure data + tiny rigs, no fleet):
- byte-identical trace/program generation from one seed, and the
  cursors (TraceCursor/OpCursor) stepped on a ManualClock — nothing in
  schedule-land may read a real clock;
- the compound-invariant checker (sim/scale.py) judged against
  handcrafted reports: a clean report passes, every violation class
  trips;
- repro-line plumbing: any violating scenario must carry the exact
  one-liner that rebuilds its (trace, program) pair;
- two PINNED regressions (seed in the test name, repro in the
  comment): brownout 503s must surface as KubeError and never be
  misread as NotFound, and a failed group commit must roll back
  cleanly and land exactly once on retry.

Slow tier (a real 2-node FleetSim, same budget reasoning as
test_fleet.py — `make chaos-matrix-smoke` is the build-time gate):
- the compound scenario the issue names: a maintenance drain during
  slice reform during a QoS throttle, under live trace traffic, with
  the FULL invariant set asserted;
- the sabotaged known-bad run: the checker must trip and emit the
  repro line (a gate that cannot fail is not a gate).
"""

import tempfile
import time

import pytest

from elastic_tpu_agent import faults
from elastic_tpu_agent.common import EnvSliceEpoch, ManualClock
from elastic_tpu_agent.kube.client import KubeClient, KubeError
from elastic_tpu_agent.sim import (
    ChaosMatrix,
    ChaosProgram,
    FleetSim,
    OpCursor,
    ScenarioRunner,
    TraceCursor,
    TraceGenerator,
    repro_line,
    scale_problems,
)
from elastic_tpu_agent.storage import Storage, StorageError
from elastic_tpu_agent.types import AllocationRecord, Device, PodInfo

from fake_apiserver import FakeAPIServer, make_pod


# -- trace generation: the determinism contract -------------------------------


def test_trace_same_seed_is_byte_identical():
    a = TraceGenerator(seed=7, duration_s=1.5, base_rps=20.0).generate()
    b = TraceGenerator(seed=7, duration_s=1.5, base_rps=20.0).generate()
    assert a.lines() == b.lines()
    assert a.digest() == b.digest()
    # and the digest actually discriminates
    c = TraceGenerator(seed=8, duration_s=1.5, base_rps=20.0).generate()
    assert c.digest() != a.digest()


def test_trace_mixes_tenancy_and_slo_classes():
    t = TraceGenerator(
        seed=11, duration_s=2.0, base_rps=30.0, train_pods=2,
    ).generate()
    reqs = t.requests()
    assert len(reqs) > 10
    assert {e["kind"] for e in t.pod_events()} == {
        "pod_admit", "pod_delete",
    }
    assert len({r["slo"] for r in reqs}) >= 2
    # every rid unique, times inside the window, events time-sorted
    assert len({r["rid"] for r in reqs}) == len(reqs)
    ts = [e["t"] for e in t.events]
    assert ts == sorted(ts)
    assert all(0.0 <= x <= t.meta["duration_s"] for x in ts)


def test_hostile_chains_share_only_the_root_block():
    t = TraceGenerator(
        seed=3, duration_s=2.0, base_rps=30.0, hostile_fraction=1.0,
    ).generate()
    chains = [r["chain"] for r in t.requests()]
    assert len(chains) > 5
    assert len({c[0] for c in chains}) == 1  # shared root
    assert len({c[1] for c in chains}) == len(chains)  # instant divergence


def test_trace_cursor_paces_on_a_manual_clock():
    trace = TraceGenerator(seed=5, duration_s=2.0, base_rps=15.0).generate()
    clock = ManualClock()
    cur = TraceCursor(trace)
    seen = []
    while not cur.exhausted:
        clock.advance(0.25)
        batch = list(cur.due(clock.monotonic()))
        assert all(e["t"] <= clock.monotonic() for e in batch)
        seen.extend(batch)
    assert seen == trace.events  # consumed exactly once, in order


# -- chaos programs: seeded overlap, scheduled on a manual clock --------------


def test_program_same_seed_is_byte_identical_and_overlapping():
    a = ChaosProgram.generate(seed=42, duration_s=3.0, nodes=2)
    b = ChaosProgram.generate(seed=42, duration_s=3.0, nodes=2)
    assert a.lines() == b.lines()
    assert a.digest() == b.digest()
    assert a.meta["overlapping_pairs"] >= 1  # compound by construction
    assert "apiserver_brownout" in a.meta["kinds"]
    assert ChaosProgram.generate(seed=43, duration_s=3.0).digest() != \
        a.digest()


def test_op_cursor_runs_the_start_stop_timeline_on_a_manual_clock():
    prog = ChaosProgram.generate(
        seed=9, duration_s=2.0, nodes=2, include_throttle=True,
    )
    ops = prog.ops()
    assert [o["t"] for o in ops] == sorted(o["t"] for o in ops)
    # every windowed action opens before it closes
    windows = {}
    for o in ops:
        windows.setdefault(o["id"], []).append(o["op"])
    for phases in windows.values():
        assert phases in (["start"], ["start", "stop"])

    clock = ManualClock()
    cur = OpCursor(prog.ops())
    fired = []
    while not cur.exhausted:
        clock.advance(0.1)
        for op in cur.due(clock.monotonic()):
            assert op["t"] <= clock.monotonic()
            fired.append((op["op"], op["id"]))
    assert len(fired) == len(ops)
    # a stop never fires before its start
    for i, a in enumerate(prog.actions):
        if a.get("duration_s"):
            assert fired.index(("start", i)) < fired.index(("stop", i))


def test_repro_line_names_the_exact_bench_invocation():
    line = repro_line(1001, 2001, "drain-under-hostile-prefix")
    assert line == (
        "python bench.py --chaos-matrix-smoke --trace-seed 1001 "
        "--chaos-seed 2001 --scenario drain-under-hostile-prefix"
    )


def test_matrix_schedule_digest_is_reproducible():
    # generation-only: no fleet is started here
    a = ChaosMatrix(trace_seed=3, chaos_seed=4).schedule_digest()
    b = ChaosMatrix(trace_seed=3, chaos_seed=4).schedule_digest()
    assert a == b
    assert ChaosMatrix(trace_seed=3, chaos_seed=5).schedule_digest() != a


# -- the compound-invariant checker, judged in isolation ----------------------


def _clean_report():
    """The shape ScenarioRunner._score emits, with every ledger
    balanced — the checker must stay silent on this."""
    return {
        "scenario": "unit",
        "repro": repro_line(1, 1, "unit"),
        "goodput": {
            "goodput_percent": 97.5,
            "conservation_problems": [],
            "unreachable_nodes": [],
        },
        "slo": {"ttft": {"attainment": 1.0}, "tpot": {"attainment": 0.98}},
        "compound": {
            "streams": {
                "admitted": 10, "finished": 10, "live_leftover": 0,
                "pending_handoff_leftover": 0, "client_visible_drops": 0,
                "finish_reasons": {"released": 10},
            },
            "handoffs": {"published": 2, "adopted": 2, "expired": 0},
            "worst_residual_s": 0.001,
            "tokens": {"emitted": 500, "accounted": 500},
            "binds": {
                "serve_pods": 4, "double_lands": 0,
                "records_missing": 0, "bind_errors_during_faults": 1,
            },
            "open_intents": 0,
            "throttled": {},
        },
        "recovery": {
            "binds_never_landed": [], "reclaimed_bind_replays": [],
        },
    }


def test_checker_passes_a_balanced_compound_report():
    assert scale_problems(_clean_report()) == []


def test_checker_trips_every_compound_violation_class():
    bad = _clean_report()
    bad["compound"]["streams"]["client_visible_drops"] = 3
    bad["compound"]["streams"]["finished"] = 7
    bad["compound"]["handoffs"]["expired"] = 1
    bad["compound"]["tokens"]["accounted"] = 400
    bad["compound"]["binds"]["double_lands"] = 1
    bad["compound"]["open_intents"] = 2
    bad["recovery"]["reclaimed_bind_replays"] = ["train/t-0"]
    bad["goodput"]["conservation_problems"] = ["pod x: gap 0.2s"]
    problems = scale_problems(bad)
    text = "\n".join(problems)
    for needle in (
        "drops", "finished", "expired", "token conservation", "double",
        "intent",
        "replay", "conservation",
    ):
        assert needle in text, f"checker missed {needle!r}: {problems}"


def test_checker_enforces_goodput_and_slo_floors():
    r = _clean_report()
    r["goodput"]["goodput_percent"] = 40.0
    r["slo"]["tpot"]["attainment"] = 0.5
    problems = scale_problems(r, {
        "min_goodput_percent": 90.0, "min_slo_attainment": 0.9,
    })
    text = "\n".join(problems)
    assert "goodput" in text and "tpot" in text
    # floors default to off: the same report is clean without bounds
    assert scale_problems(r) == []


# -- pinned regression: brownout 503 is an OUTAGE, never a deletion -----------


def test_brownout_503_surfaces_as_kube_error_never_notfound_seed_20260807():
    """PINNED (seed=20260807, error_rate=1.0): during an apiserver
    brownout every get must raise KubeError — get_pod returning None
    (the NotFound contract) would let the GC read an outage as "pod
    deleted" and reclaim live bindings. Repro: FakeAPIServer +
    set_brownout(error_rate=1.0, seed=20260807), then GET an existing
    pod."""
    api = FakeAPIServer()
    base = api.start()
    try:
        api.upsert_pod(make_pod("default", "alive", "node-a"))
        client = KubeClient(base)
        assert client.get_pod("default", "alive") is not None

        api.set_brownout(error_rate=1.0, seed=20260807)
        with pytest.raises(KubeError):
            client.get_pod("default", "alive")
        # even a pod that truly doesn't exist must NOT report NotFound
        # mid-brownout: the 503 wins over the 404
        with pytest.raises(KubeError):
            client.get_pod("default", "ghost")

        api.clear_brownout()
        assert client.get_pod("default", "alive") is not None
        counts = api.request_counts
        assert counts.get("pod_get_failed", 0) >= 2  # failures split out
        assert counts.get("pod_get", 0) >= 2  # served before/after
    finally:
        api.stop()


def test_brownout_failure_sequence_replays_from_its_seed():
    """Same seed, same request sequence ⇒ the same requests fail: the
    brownout is part of the chaos determinism contract, not noise."""
    def run_once():
        api = FakeAPIServer()
        base = api.start()
        try:
            api.upsert_pod(make_pod("default", "p", "node-a"))
            client = KubeClient(base)
            api.set_brownout(error_rate=0.5, seed=99)
            outcomes = []
            for _ in range(12):
                try:
                    client.get_pod("default", "p")
                    outcomes.append("ok")
                except KubeError:
                    outcomes.append("503")
            return outcomes
        finally:
            api.stop()

    a, b = run_once(), run_once()
    assert a == b
    assert "503" in a and "ok" in a  # genuinely mixed at 0.5


# -- pinned regression: flaky group commit rolls back, lands once -------------


def _pod_info(name):
    return PodInfo(
        namespace="train",
        name=name,
        allocations={
            "jax": {
                "elasticgpu.io/tpu-core": AllocationRecord(
                    device=Device(("d1",), "elasticgpu.io/tpu-core"),
                    chip_indexes=[0],
                    created_node_ids=[],
                )
            }
        },
    )


def test_flush_fault_rolls_back_then_lands_once_on_retry_seed_20260807():
    """PINNED (storage.batch_flush raise-once, batch_window_s=0.02):
    a failed group commit must surface as StorageError with the write
    ROLLED BACK — nothing partially landed — and the retry must land
    the record exactly once (the no-double-land half of the chaos bind
    invariant). Repro: arm storage.batch_flush=raise-once on a batched
    store, save, retry."""
    with tempfile.TemporaryDirectory(prefix="etpu-flush") as tmp:
        path = f"{tmp}/meta.db"
        store = Storage(path, batch_window_s=0.02)
        try:
            faults.get_registry().arm("storage.batch_flush", "raise-once")
            with pytest.raises(StorageError):
                store.save(_pod_info("flaky"))
            # rolled back: a second connection sees NOTHING
            reader = Storage(path)
            try:
                assert reader.load("train", "flaky") is None
            finally:
                reader.close()
            # the fault was raise-once: the retry lands, exactly once
            store.save(_pod_info("flaky"))
            reader = Storage(path)
            try:
                assert reader.load("train", "flaky") is not None
                assert reader.count() == 1
            finally:
                reader.close()
        finally:
            faults.get_registry().disarm()
            store.close()


# -- the compound scenario itself (slow tier: real 2-node fleet) --------------
#
# Budget reasoning mirrors test_fleet.py: a live fleet costs seconds of
# fixture on the 1-CPU CI box and the fast tier runs within sight of
# its timeout; `make chaos-matrix-smoke` (part of `make verify`) is the
# build-time gate that executes compound scenarios every round.

chaos_tier = pytest.mark.slow


def _chaos_fleet(tmp):
    return FleetSim(
        tmp,
        nodes=2,
        reconcile_period_s=0.5,
        slice_membership_ttl_s=0.25,
        drain_deadline_s=30.0,
        drain_period_s=0.25,
        migration_period_s=0.1,
        goodput_period_s=3600.0,
        enable_sampler=True,
        sampler_period_s=3600.0,
        repartition_period_s=3600.0,
        storage_batch_window_s=0.004,
        sink_flush_window_s=0.02,
    )


@chaos_tier
def test_compound_drain_during_reform_during_throttle():
    """The issue's named worst case: node 1 takes a maintenance drain
    (forcing slice reform on the survivor) while node 0's QoS loop is
    mid-throttle, under live trace traffic and the standing brownout/
    flush/delay faults — and every compound invariant still holds."""
    from elastic_tpu_agent.slice_env import ordered_worker_hostnames

    with tempfile.TemporaryDirectory(prefix="etpu-cx") as tmp:
        sim = _chaos_fleet(tmp)
        sim.start()
        try:
            # a live slice across both nodes: the drain must reform it
            slice_refs = sim.admit_slice("cx", [0, 1])
            sim.wait_synced(slice_refs)
            for ref in slice_refs:
                sim.bind_pod(ref)

            trace = TraceGenerator(
                seed=1001, duration_s=2.0, base_rps=10.0, train_pods=1,
            ).generate()
            # Handcrafted program (ChaosProgram is pure data; the same
            # ops/executor path as generate()): the drain must OUTLAST
            # the survivor's reform-detection latency — generate()'s
            # windows are tempo-sized for the smoke and can close
            # before the reform lands, which proves nothing either
            # way. Drain on node 1 overlaps the throttle on node 0,
            # the brownout and the flaky group commit: drain DURING
            # reform DURING throttle.
            program = ChaosProgram(2001, [
                {"kind": "failpoint", "t": 0.2, "duration_s": 1.5,
                 "point": "storage.batch_flush", "spec": "prob:0.1:11"},
                {"kind": "apiserver_brownout", "t": 0.3,
                 "duration_s": 1.2, "error_rate": 0.2,
                 "latency_s": 0.001, "seed": 7},
                {"kind": "throttle", "t": 0.4, "duration_s": 2.2,
                 "node": 0},
                {"kind": "maintenance_drain", "t": 0.5,
                 "duration_s": 2.5, "node": 1},
                {"kind": "kubelet_flap", "t": 1.0, "node": 0},
            ], {"chaos_seed": 2001, "duration_s": 3.0, "nodes": 2})
            assert program.overlaps() >= 3  # genuinely compound

            runner = ScenarioRunner(
                sim, trace, program, name="drain-reform-throttle",
            )
            report = runner.run()

            # full invariant set, with loose floors on top
            problems = scale_problems(report, {
                "min_goodput_percent": 10.0,
                "min_slo_attainment": 0.5,
            })
            assert problems == [], problems

            comp = report["compound"]
            streams = comp["streams"]
            assert streams["admitted"] > 0
            assert streams["admitted"] == streams["finished"]
            assert streams["client_visible_drops"] == 0
            assert comp["handoffs"]["published"] == \
                comp["handoffs"]["adopted"]
            assert comp["binds"]["double_lands"] == 0
            assert comp["open_intents"] == 0
            assert comp["throttled"].get("node-0") is True  # clamp seen
            assert report["repro"] == repro_line(
                1001, 2001, "drain-reform-throttle"
            )

            # the drain really reformed the slice: the survivor's
            # stamped env reached a post-reform epoch
            survivor = slice_refs[0]
            surviving_order, _ = ordered_worker_hostnames(
                [sim.nodes[0].name]
            )
            deadline = time.monotonic() + 15.0
            epoch = -1
            while time.monotonic() < deadline:
                env = sim.slice_env_of(survivor)
                epoch = int(env.get(EnvSliceEpoch, -1))
                if epoch >= 1:
                    break
                time.sleep(0.1)
            assert epoch >= 1, f"slice never reformed: epoch={epoch}"
        finally:
            faults.get_registry().disarm()
            sim.stop()


@chaos_tier
def test_sabotaged_run_trips_the_checker_with_a_repro_line():
    """Known-bad self-test: sabotaged stream accounting (every finish
    a client-visible drop) MUST produce violations, and the verdict
    must carry the exact repro line — the checker checking itself."""
    matrix = ChaosMatrix(trace_seed=1, chaos_seed=1)
    matrix.scenarios = [{
        "name": "self-test",
        "index": 0,
        "trace": {
            "duration_s": 1.0, "base_rps": 10.0,
            "flash_crowds": 0, "train_pods": 0,
        },
        "program": {"duration_s": 1.0, "include_drain": False},
    }]
    with tempfile.TemporaryDirectory(prefix="etpu-st") as tmp:
        verdict = matrix.self_test(tmp)
    assert verdict["tripped"]
    assert any("drops" in p for p in verdict["problems"])
    assert verdict["repro"] == repro_line(1, 1, "self-test")
