"""Pipelined flagship transformer (VERDICT r3 #5): the real LM staged
over the "pp" ppermute schedule — GPipe and 1F1B — must match the
unpipelined model numerically, and 1F1B's explicit-vjp backward must
match GPipe's autodiff gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elastic_tpu_agent.workloads.pipeline import make_pipeline_mesh
from elastic_tpu_agent.workloads.transformer import ModelConfig
from elastic_tpu_agent.workloads.transformer_pipeline import (
    _embed_fn,
    _head_loss,
    _stage_fn,
    init_pipeline_params,
    make_pipeline_transformer_step,
    pipeline_1f1b_grads,
)

CFG = ModelConfig(
    vocab=128, d_model=32, n_heads=2, n_layers=4, d_ff=64, max_seq=32,
    dtype=jnp.float32,
)
PP = 4
M, MB, S = 6, 2, 16  # microbatches, microbatch size, seq


@pytest.fixture(scope="module")
def mesh():
    return make_pipeline_mesh(pp=PP, dp=2)


@pytest.fixture(scope="module")
def params():
    return init_pipeline_params(CFG, jax.random.key(0), PP)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(
        jax.random.key(1), (M, MB, S + 1), 0, CFG.vocab
    )


def unpipelined_loss(params, toks):
    """Oracle: same stacked weights applied sequentially, no pipeline."""
    xs = _embed_fn(params, toks[:, :, :-1], CFG)
    head = {
        "final_norm_scale": params["final_norm_scale"],
        "lm_head": params["lm_head"],
    }

    def per_micro(x, tgt):
        for p in range(PP):
            stage_p = jax.tree.map(lambda a: a[p], params["stages"])
            x = _stage_fn(stage_p, x, CFG)
        return _head_loss(x, head, tgt, CFG)

    losses = jax.vmap(per_micro)(xs, toks[:, :, 1:])
    return jnp.mean(losses)


def _copy(tree):
    # step() donates params/opt buffers; module-scoped fixtures must not
    # hand over their originals
    return jax.tree.map(jnp.copy, tree)


@pytest.mark.slow
def test_gpipe_matches_unpipelined(mesh, params, tokens):
    step, init_all = make_pipeline_transformer_step(
        CFG, mesh, n_micro=M, schedule="gpipe"
    )
    _, opt0 = init_all(jax.random.key(0))
    want = float(unpipelined_loss(params, tokens))
    _, _, loss = step(_copy(params), opt0, tokens)
    assert np.isfinite(want)
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)


@pytest.mark.slow
def test_1f1b_loss_matches_unpipelined(mesh, params, tokens):
    step, init_all = make_pipeline_transformer_step(
        CFG, mesh, n_micro=M, schedule="1f1b"
    )
    _, opt0 = init_all(jax.random.key(0))
    want = float(unpipelined_loss(params, tokens))
    _, _, loss = step(_copy(params), opt0, tokens)
    np.testing.assert_allclose(float(loss), want, rtol=1e-5)


@pytest.mark.slow
def test_1f1b_grads_match_gpipe_autodiff(mesh, params, tokens):
    """The explicit-vjp 1F1B backward against autodiff of the oracle."""
    want = jax.grad(unpipelined_loss)(params, tokens)

    head = {
        "final_norm_scale": params["final_norm_scale"],
        "lm_head": params["lm_head"],
    }
    embed_params = {
        "embed": params["embed"], "pos_embed": params["pos_embed"]
    }
    xs, embed_vjp = jax.vjp(
        lambda ep: _embed_fn(ep, tokens[:, :, :-1], CFG), embed_params
    )
    g_stage, g_head, dxs, loss = pipeline_1f1b_grads(
        mesh, CFG, params["stages"], head, xs, tokens[:, :, 1:]
    )
    (g_embed,) = embed_vjp(dxs.astype(xs.dtype))

    np.testing.assert_allclose(
        float(loss), float(unpipelined_loss(params, tokens)), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5, rtol=1e-4),
        g_stage, want["stages"],
    )
    np.testing.assert_allclose(
        g_head["lm_head"], want["lm_head"], atol=2e-5, rtol=1e-4
    )
    np.testing.assert_allclose(
        g_head["final_norm_scale"], want["final_norm_scale"],
        atol=2e-5, rtol=1e-4,
    )
    np.testing.assert_allclose(
        g_embed["embed"], want["embed"], atol=2e-5, rtol=1e-4
    )
    np.testing.assert_allclose(
        g_embed["pos_embed"], want["pos_embed"], atol=2e-5, rtol=1e-4
    )


def test_training_reduces_loss_both_schedules(mesh, tokens):
    for schedule in ("gpipe", "1f1b"):
        step, init_all = make_pipeline_transformer_step(
            CFG, mesh, n_micro=M, schedule=schedule, learning_rate=1e-2
        )
        params, opt = init_all(jax.random.key(2))
        first = None
        for _ in range(5):
            params, opt, loss = step(params, opt, tokens)
            if first is None:
                first = float(loss)
        assert float(loss) < first, (
            f"{schedule}: loss did not drop ({first} -> {float(loss)})"
        )


@pytest.mark.slow
def test_runner_pipeline_mode(tmp_path):
    """The in-pod runner trains the pipelined flagship end-to-end in a
    real process (--pp), both schedules."""
    import json
    import os
    import subprocess
    import sys

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": os.pathsep.join(
            [os.path.join(os.path.dirname(__file__), "..")]
            + os.environ.get("PYTHONPATH", "").split(os.pathsep)
        ).rstrip(os.pathsep),
    }
    for schedule in ("gpipe", "1f1b"):
        out = subprocess.run(
            [
                sys.executable, "-m", "elastic_tpu_agent.workloads.runner",
                "--preset", "tiny", "--steps", "2", "--batch", "8",
                "--seq", "32", "--pp", "2", "--n-micro", "4",
                "--pp-schedule", schedule, "--dp", "2",
            ],
            env=env, capture_output=True, text=True, timeout=600,
        )
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout.strip().splitlines()[-1])
        assert report["mesh"] == {"pp": 2, "dp": 2}, report
        assert report["final_loss"] == report["final_loss"], schedule


@pytest.mark.slow
def test_pipeline_checkpoint_resume(tmp_path, mesh, tokens):
    """Orbax checkpointing round-trips the pipelined (pp-sharded) params:
    save mid-training, restore onto the live mesh, losses continue
    identically."""
    from elastic_tpu_agent.workloads.checkpointing import TrainCheckpointer

    step, init_all = make_pipeline_transformer_step(
        CFG, mesh, n_micro=M, schedule="gpipe", learning_rate=1e-2
    )
    params, opt = init_all(jax.random.key(5))
    for s in range(3):
        params, opt, _ = step(params, opt, tokens)
    ckpt = TrainCheckpointer(str(tmp_path / "ck"))
    ckpt.save(2, params, opt)
    ckpt.wait()

    # continue the original for one step
    p_cont, o_cont, loss_cont = step(_copy(params), _copy(opt), tokens)

    # restore into fresh templates and take the same step
    params2, opt2 = init_all(jax.random.key(999))
    params2, opt2, restored_step = ckpt.restore(params2, opt2)
    assert restored_step == 2
    _, _, loss_restored = step(params2, opt2, tokens)
    ckpt.close()
    np.testing.assert_allclose(
        float(loss_restored), float(loss_cont), rtol=1e-6
    )


@pytest.mark.slow
def test_rope_pipeline_smoke(tokens):
    """pos='rope' works under the pipeline (stages see full sequences, so
    local indices are global positions); no pos_embed param exists."""
    mesh2 = make_pipeline_mesh(pp=2, dp=2)
    cfg = ModelConfig(
        vocab=128, d_model=32, n_heads=2, n_layers=4, d_ff=64, max_seq=32,
        dtype=jnp.float32, pos="rope",
    )
    params = init_pipeline_params(cfg, jax.random.key(7), 2)
    assert "pos_embed" not in params
    for schedule in ("gpipe", "1f1b"):
        step, init_all = make_pipeline_transformer_step(
            cfg, mesh2, n_micro=M, schedule=schedule
        )
        _, opt0 = init_all(jax.random.key(0))
        _, _, loss = step(_copy(params), opt0, tokens)
        assert np.isfinite(float(loss)), schedule


@pytest.mark.slow
def test_pp2_also_works(tokens):
    mesh2 = make_pipeline_mesh(pp=2, dp=2)
    cfg = ModelConfig(
        vocab=128, d_model=32, n_heads=2, n_layers=4, d_ff=64, max_seq=32,
        dtype=jnp.float32,
    )
    params = init_pipeline_params(cfg, jax.random.key(3), 2)
    for schedule in ("gpipe", "1f1b"):
        step, init_all = make_pipeline_transformer_step(
            cfg, mesh2, n_micro=M, schedule=schedule
        )
        _, opt0 = init_all(jax.random.key(0))
        _, _, loss = step(_copy(params), opt0, tokens)
        assert np.isfinite(float(loss)), schedule
