"""Streaming ring-buffer decode (workloads/streaming.py): with a
cache of exactly window slots, the stream must EQUAL the full-cache
windowed decode at every length — eviction only drops keys no query
can reach."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elastic_tpu_agent.workloads.generate import generate
from elastic_tpu_agent.workloads.streaming import streaming_generate
from elastic_tpu_agent.workloads.transformer import (
    ModelConfig,
    init_params,
)

BASE = dict(
    vocab=97, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64,
    dtype=jnp.float32, attn="reference", pos="rope", window=8,
)


@pytest.mark.parametrize("kv_heads", [0, 2], ids=["mha", "gqa"])
def test_stream_equals_full_cache_windowed_decode(kv_heads):
    cfg = ModelConfig(**BASE, n_kv_heads=kv_heads)
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, cfg.vocab)
    n = 30  # total 35: nearly 4x the ring, many wrap-arounds
    want = generate(params, prompt, cfg, max_new_tokens=n)
    got = streaming_generate(params, prompt, cfg, max_new_tokens=n)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stream_runs_far_past_any_full_cache_budget():
    """400 generated tokens through an 8-slot ring: HBM for the cache
    never exceeds window size, and the stream still matches the
    full-cache oracle token for token."""
    cfg = ModelConfig(**BASE)
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(2), (1, 8), 0, cfg.vocab)
    n = 400
    got = streaming_generate(params, prompt, cfg, max_new_tokens=n)
    want = generate(
        params, prompt, cfg, max_new_tokens=n, max_len=8 + n,
    )
    assert got.shape == (1, 408)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stream_sampling_deterministic_per_key():
    cfg = ModelConfig(**BASE)
    params = init_params(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(3), (2, 4), 0, cfg.vocab)
    o1 = streaming_generate(
        params, prompt, cfg, max_new_tokens=20, temperature=0.8,
        top_k=10, key=jax.random.key(7),
    )
    o2 = streaming_generate(
        params, prompt, cfg, max_new_tokens=20, temperature=0.8,
        top_k=10, key=jax.random.key(7),
    )
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))


def test_stream_rejects_bad_configs():
    params_cfg = ModelConfig(**BASE)
    params = init_params(params_cfg, jax.random.key(0))
    full = ModelConfig(**{**BASE, "window": 0})
    with pytest.raises(AssertionError, match="sliding-window"):
        streaming_generate(
            params, jnp.zeros((1, 4), jnp.int32), full, 4
        )
    learned = ModelConfig(**{**BASE, "pos": "learned"})
    with pytest.raises(AssertionError, match="rope"):
        streaming_generate(
            params, jnp.zeros((1, 4), jnp.int32), learned, 4
        )
    with pytest.raises(AssertionError, match="fit the attention window"):
        streaming_generate(
            params, jnp.zeros((1, 9), jnp.int32), params_cfg, 4
        )
